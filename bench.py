"""Headline benchmark: checkpoint save throughput (GB/s) from TPU HBM to
local FS, the analog of the reference's DDP benchmark
(benchmarks/ddp/README.md: 20 GB model, 1 node x 1 GPU -> ~13.91 s,
~1.4 GB/s on local FS — BASELINE.md).

Prints ONE JSON line with the three north stars (BASELINE.md):

- save GB/s: median of 3 timed takes with [min, max] range (the dev
  tunnel's D2H fluctuates 2-4x between runs; a single trial can't
  support a committed ratio), and pipeline_efficiency = median of the
  per-trial take/probe ratios, where each take is paired with a
  temporally-adjacent PATTERN-MATCHED attainable-D2H probe (same stream
  count and transfer size) so intra-run link drift cancels per pair. A
  value > 1 means the link sped up between probe and take (the probe is
  a lower bound of attainable).
- restore GB/s: median of 3 timed restores into device-committed
  destinations (storage reads + H2D placement), checksums on.
- async-take stall: wall time until async_take returns (staging done,
  training would resume) vs total time to durable commit.

Context fields: incremental unchanged-state save, and the CPU-backend
protocol-overhead scaling rows (per-rank bytes written must halve at 2
ranks; protocol wall stays ~flat — benchmarks/replicated_save/
protocol_overhead.py), both fail-soft.

Size configurable via TS_BENCH_GB (default 4; 1 on tunneled links).
TS_BENCH_SKIP_PROTOCOL=1 skips the subprocess leg.
"""

import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

import torchsnapshot_tpu as ts

REFERENCE_SINGLE_ACCEL_GBPS = 20.0 / 13.91  # benchmarks/ddp/README.md:17


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_state(total_bytes: int, seed: int = 0) -> dict:
    """A pytree of bf16 arrays totaling ~total_bytes on device, shaped like
    transformer params (a few large 2-d weights + long 1-d tails).

    Each timed take gets a FRESH state (distinct seed): jax caches an
    array's host copy after its first D2H, so re-taking the same arrays
    measures a memcpy, not the device link."""
    key = jax.random.PRNGKey(seed)
    arrays = {}
    # 256 MiB bf16 blocks: (16384, 8192) * 2 bytes
    block_bytes = 16384 * 8192 * 2
    n_blocks = max(1, total_bytes // block_bytes)
    for i in range(n_blocks):
        key, sub = jax.random.split(key)
        arrays[f"w{i}"] = jax.random.normal(
            sub, (16384, 8192), dtype=jnp.bfloat16
        )
    arrays["bias"] = jnp.ones((65536,), dtype=jnp.float32)
    jax.block_until_ready(arrays)
    return arrays


def probe_d2h(n_streams: int, chunk_mib: int = 32) -> float:
    """Measured D2H GB/s with ``n_streams`` concurrent async copies.

    ``copy_to_host_async`` on every array first, then materialize: the
    transfers overlap inside the runtime, so this measures the *attainable*
    device→host bandwidth — the checkpoint pipeline's physical ceiling —
    rather than the single-stream latency-bound rate.
    """
    side = int((chunk_mib * (1 << 20) // 2) ** 0.5)  # bf16 square
    keys = jax.random.split(jax.random.PRNGKey(1), n_streams)
    arrs = [jax.random.normal(k, (side, side), jnp.bfloat16) for k in keys]
    jax.block_until_ready(arrs)
    total = sum(a.nbytes for a in arrs)
    t0 = time.perf_counter()
    for a in arrs:
        a.copy_to_host_async()
    hosts = [np.asarray(a) for a in arrs]
    elapsed = time.perf_counter() - t0
    del hosts
    return total / (1 << 30) / elapsed


def probe_ceiling(tunneled: bool) -> float:
    """Best concurrent-stream D2H rate over the probe plan."""
    if tunneled:
        # Per-transfer overhead dominates small probes on ~MB/s links;
        # match the pipeline's actual transfer size.
        plan = [(1, 256), (4, 64)]
    else:
        plan = [(2, 32), (4, 32), (8, 32)]
    best = 0.0
    for n, mib in plan:
        r = probe_d2h(n, chunk_mib=mib)
        _log(f"bench: D2H x{n} streams of {mib} MiB = {r:.3f} GB/s")
        best = max(best, r)
    return best


def _median_range(samples):
    return round(statistics.median(samples), 3), [
        round(min(samples), 3),
        round(max(samples), 3),
    ]


def protocol_overhead_rows():
    """CPU-backend multi-process protocol scaling (fail-soft)."""
    if os.environ.get("TS_BENCH_SKIP_PROTOCOL") == "1":
        return None
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        "replicated_save",
        "protocol_overhead.py",
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TS_BENCH_GB", None)
    try:
        proc = subprocess.run(
            [sys.executable, script, "--gb", "0.125"],
            env=env,
            capture_output=True,
            text=True,
            timeout=900,
        )
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip()[-500:])
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 - context metric only
        _log(f"bench: protocol-overhead leg failed: {e!r}")
        return None


def main() -> None:
    d2h_single = probe_d2h(1)
    tunneled = d2h_single <= 0.5
    ceiling_before = max(d2h_single, probe_ceiling(tunneled))
    _log(
        f"bench: raw D2H single-stream = {d2h_single:.3f} GB/s, "
        f"concurrent ceiling = {ceiling_before:.3f} GB/s"
    )

    gb_env = os.environ.get("TS_BENCH_GB")
    gb = float(gb_env) if gb_env is not None else 4.0
    if gb_env is None and tunneled:
        # Tunnel-limited link: the save is pure D2H wall time, so extra
        # gigabytes add minutes without changing any reported ratio.
        gb = 1.0
        _log("bench: tunneled D2H detected; defaulting to 1 GiB state")
    total_bytes = int(gb * (1 << 30))
    _log(f"bench: materializing ~{gb:.1f} GiB of bf16 state on {jax.devices()[0]}")
    state = make_state(total_bytes, seed=0)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    gib = nbytes / (1 << 30)

    workdir = tempfile.mkdtemp(prefix="ts_bench_", dir="/tmp")
    incr_elapsed = None
    stall_s = async_total_s = None
    try:
        # Warm-up on a small state: first-take costs (event loop, thread
        # pools, XLA transfer program) should not pollute the measurement.
        warm = {"x": jnp.ones((1024, 1024), jnp.bfloat16)}
        ts.Snapshot.take(os.path.join(workdir, "warm"), {"s": ts.PyTreeState(warm)})

        # Headline: median of 3 PLAIN takes — comparable to the reference
        # baseline and earlier rounds (no digest recording in the timed
        # path). Every trial snapshots a FRESH state: jax caches host
        # copies per array, and re-taking cached arrays would time a
        # memcpy instead of the device link. On tunneled links each take
        # is paired with a PATTERN-MATCHED ceiling probe (same stream
        # count and transfer size as the take's leaves, interleaved in
        # time): the link drifts minute-to-minute, so an efficiency ratio
        # is only meaningful against the attainable rate measured around
        # each trial with the same transfer shape.
        dest_template = {k: (v.shape, v.dtype) for k, v in state.items()}
        take_times = []
        matched_ceilings = []
        trial_state = state
        state = None  # one state on device at a time: 1x HBM, not 2x
        n_blocks = max(1, total_bytes // (16384 * 8192 * 2))
        probe_streams = min(4, n_blocks)
        for i in range(3):
            if tunneled:
                mc = probe_d2h(probe_streams, chunk_mib=256)
                matched_ceilings.append(mc)
                _log(
                    f"bench: matched ceiling probe {i} "
                    f"({probe_streams}x256 MiB): {mc:.3f} GB/s"
                )
            path = os.path.join(workdir, f"snap{i}")
            t0 = time.perf_counter()
            ts.Snapshot.take(path, {"state": ts.PyTreeState(trial_state)})
            take_times.append(time.perf_counter() - t0)
            _log(f"bench: take {i}: {take_times[-1]:.2f} s")
            if i < 2:
                shutil.rmtree(path, ignore_errors=True)
                trial_state = None
                trial_state = make_state(total_bytes, seed=i + 1)
        state = trial_state  # snap2's source; later phases reuse it
        save_med_s = statistics.median(take_times)
        save_gbps, save_range = _median_range([gib / t for t in take_times])

        # Timed restores (median of 3): storage reads + streaming H2D
        # placement into device-committed destinations, checksums on.
        # os.sync() first — the takes above left ~size_gib of dirty pages,
        # and background writeback on this one-core box otherwise bleeds
        # into the restore timings (measured 10x inflation).
        restore_times = []
        try:
            dev = jax.devices()[0]
            snap = ts.Snapshot(os.path.join(workdir, "snap2"))
            for i in range(3):
                dest = ts.PyTreeState(
                    {
                        k: jax.device_put(np.zeros(shape, dtype), dev)
                        for k, (shape, dtype) in dest_template.items()
                    }
                )
                jax.block_until_ready(dest.tree)
                os.sync()
                t0 = time.perf_counter()
                snap.restore({"state": dest})
                jax.block_until_ready(dest.tree)
                restore_times.append(time.perf_counter() - t0)
                _log(f"bench: restore {i}: {restore_times[-1]:.2f} s")
                del dest
        except Exception as e:  # noqa: BLE001
            _log(f"bench: restore measurement failed: {e!r}")

        # Incremental save of the SAME state (all chunks unchanged ->
        # manifest refs only, no D2H, no data writes). Needs a
        # digest-recorded base (untimed) + a warm-up for the one-time
        # digest-program compile. Fail-soft.
        try:
            base = os.path.join(workdir, "snap_base")
            ts.Snapshot.take(
                base, {"state": ts.PyTreeState(state)}, record_digests=True
            )
            ts.Snapshot.take(
                os.path.join(workdir, "snap_incr_warm"),
                {"state": ts.PyTreeState(state)},
                incremental_base=base,
            )
            t0 = time.perf_counter()
            ts.Snapshot.take(
                os.path.join(workdir, "snap_incr"),
                {"state": ts.PyTreeState(state)},
                incremental_base=base,
            )
            incr_elapsed = time.perf_counter() - t0
            _log(
                f"bench: incremental save (unchanged state) {incr_elapsed:.2f} s "
                f"vs full {save_med_s:.2f} s ({save_med_s / incr_elapsed:.0f}x)"
            )
        except Exception as e:  # noqa: BLE001
            _log(f"bench: incremental context measurement failed: {e!r}")
        # Release the last trial state before the async-stall state
        # materializes: 1x HBM peak throughout.
        state = None

        # Async-take stall split: time to staging-done (training resumes)
        # vs time to durable commit. Fresh state again — a cached host
        # copy would fake a near-zero stall on links where staging IS the
        # D2H.
        try:
            async_state = make_state(total_bytes, seed=11)
            t0 = time.perf_counter()
            pending = ts.Snapshot.async_take(
                os.path.join(workdir, "snap_async"),
                {"state": ts.PyTreeState(async_state)},
            )
            stall_s = time.perf_counter() - t0
            pending.wait()
            async_total_s = time.perf_counter() - t0
            _log(
                f"bench: async take stall {stall_s:.2f} s of "
                f"{async_total_s:.2f} s total"
            )
            del async_state
        except Exception as e:  # noqa: BLE001
            _log(f"bench: async stall measurement failed: {e!r}")

    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    # Re-probe the generic ceiling after the timed work (context field;
    # the efficiency denominator is the matched interleaved probes when
    # available).
    ceiling_after = max(probe_d2h(1), probe_ceiling(tunneled))
    ceiling = max(ceiling_before, ceiling_after)
    if matched_ceilings:
        # Median of per-trial ratios: each take divided by its own
        # temporally-adjacent matched probe, so intra-run link drift
        # (observed 2.6x within one run) cancels per pair. A ratio > 1
        # means the link sped up between probe and take — the probe is a
        # lower bound of attainable, and the pipeline is not the limit.
        denom = statistics.median(matched_ceilings)
        ratios = [
            (gib / t) / c for t, c in zip(take_times, matched_ceilings) if c > 0
        ]
        efficiency = statistics.median(ratios) if ratios else 0.0
        _log(
            f"bench: matched-pattern ceiling median {denom:.3f} GB/s, "
            f"per-trial efficiency ratios "
            f"{[round(r, 2) for r in ratios]} (generic probes: before "
            f"{ceiling_before:.3f} / after {ceiling_after:.3f})"
        )
    else:
        denom = ceiling
        efficiency = save_gbps / denom if denom > 0 else 0.0
        _log(
            f"bench: ceiling before {ceiling_before:.3f} / after "
            f"{ceiling_after:.3f} GB/s -> using {ceiling:.3f}"
        )
    _log(
        f"bench: wrote {gib:.2f} GiB, median {save_med_s:.2f} s "
        f"({save_gbps:.2f} GB/s, {efficiency:.2f}x of attainable D2H)"
    )
    result = {
        "metric": "checkpoint_save_throughput",
        "value": save_gbps,
        "unit": "GB/s",
        "vs_baseline": round(save_gbps / REFERENCE_SINGLE_ACCEL_GBPS, 3),
        "save_gbps_range": save_range,
        "pipeline_efficiency": round(efficiency, 3),
        "d2h_ceiling_gbps": round(denom, 3),
        "d2h_ceiling_before_after": [
            round(ceiling_before, 3),
            round(ceiling_after, 3),
        ],
        "d2h_single_gbps": round(d2h_single, 3),
        "size_gib": round(gib, 2),
    }
    if matched_ceilings:
        result["d2h_matched_probes"] = [round(c, 3) for c in matched_ceilings]
    if restore_times:
        med, rng = _median_range([gib / t for t in restore_times])
        result["restore_gbps"] = med
        result["restore_gbps_range"] = rng
    if stall_s is not None and async_total_s is not None:
        result["async_stall_ms"] = round(stall_s * 1000, 1)
        result["async_total_s"] = round(async_total_s, 2)
    if incr_elapsed is not None:
        result["incremental_unchanged_save_s"] = round(incr_elapsed, 3)
        result["incremental_speedup"] = round(save_med_s / incr_elapsed, 1)
    proto = protocol_overhead_rows()
    if proto is not None:
        result["protocol_overhead"] = proto
    print(json.dumps(result))


if __name__ == "__main__":
    main()
