"""Headline benchmark: checkpoint save throughput (GB/s) from TPU HBM to
local FS, the analog of the reference's DDP benchmark
(benchmarks/ddp/README.md: 20 GB model, 1 node x 1 GPU -> ~13.91 s,
~1.4 GB/s on local FS — BASELINE.md).

The record is designed to SURVIVE any driver budget (round 4's lesson:
a single end-of-run emission point + a methodology sized for a fast
link produced ``rc: 124, parsed: null`` on a 0.015 GB/s tunnel):

- **Partial emission**: after every leg the full current record is
  printed as a ``bench-partial:``-prefixed JSON line and mirrored to
  ``BENCH_partial.json``; ``atexit`` and SIGTERM/SIGINT handlers flush
  the final bare JSON line with ``"complete": false`` on early death
  (``timeout(1)`` sends SIGTERM first — rc 124 still yields a parsed
  record). The final bare JSON line is the only unprefixed one.
- **Wall-clock budget**: ``TS_BENCH_BUDGET_S`` (default 1200 s). Legs
  run in value order, each gated on remaining budget with a cost
  estimate from the *measured* link; skipped legs are recorded in
  ``skipped_legs`` instead of silently truncating coverage.
- **Scaled probes**: attainable-bandwidth probes keep the pipeline's
  stream pattern but scale transfer volume to the measured link so a
  probe costs ~12 s, not 67 s.

Leg order and what each contributes:

1. Link probe: single-stream + concurrent scaled D2H → ``d2h_single_gbps``,
   ceiling-before; sets every later cost estimate.
2. Subprocess legs (CPU mesh, fail-soft, each time-boxed; they precede
   the long take loop so a driver kill cannot erase them): orbax
   head-to-head (``orbax_save_ratio``/``orbax_restore_ratio`` = orbax
   median / ours, >1 = we are faster, our checksums ON), async-stall on
   the 8-device sharded-transformer (``cpu_mesh_stall_ms`` — the regime
   where staging is NOT the D2H), restore-to-step0 cold start
   (``cold_start_sync_s`` vs ``cold_start_async_visible_s`` — sync
   restore wall vs the part async restore fails to hide under
   compilation; BASELINE.md north star), protocol-overhead scaling.
3. Save: median of N timed takes (N scaled to the link), each BRACKETED
   by pattern-matched D2H probes; ``pipeline_efficiency`` = median of
   per-trial achieved / max(bracket). ``link_unstable`` when adjacent
   probes disagree >1.5x. Each trial also records the scheduler's phase
   timestamps (staging-done / writing-done) and an ``in_take_stall``
   flag when achieved < 0.5x of a *stable* bracket — a 439 s-style
   outlier now carries its own diagnosis instead of being absorbed by
   the median (reference per-phase reporter: torchsnapshot
   scheduler.py:96-175).
4. Restore: timed restores into device-committed destinations bracketed
   by matched H2D probes → ``restore_gbps`` AND ``restore_efficiency``
   + ``restore_link_unstable`` — the same epistemics as save (reference
   analog: the isolated read path in benchmarks/load_tensor/main.py:
   24-61). ``os.sync()`` before each timed restore (writeback from the
   takes otherwise bleeds in; measured 10x inflation). Then the COLD
   restore leg (benchmarks/cold_restore.py, fresh default-platform
   subprocess): the restore-after-restart scenario, and on this tunnel
   the only unpoisoned one — a process's first D2H collapses its H2D
   ~40x irreversibly (measured 1.3 → 0.03 GB/s), so the in-process
   number is the artifact-bound worst case while
   ``cold_restore_gbps``/``cold_restore_efficiency`` is the
   hardware-limit figure.
5. Incremental unchanged-state save, the zero-pack write-path
   microbench (packed vs vectorized vs O_DIRECT on a >=256 MiB batched
   take — ``write_path`` / ``write_path_zero_pack_speedup``), and the
   on-TPU async-take stall
   split, budget-gated context fields. The steady-state autotune leg
   and the preemption-recovery leg additionally run with the goodput
   ledger on and record ``RESULT.goodput`` (run-level overhead
   fraction, recovery cost, storage bytes/step from
   ``telemetry/goodput.py``) — BENCH_r06+ carries run-level numbers,
   not just per-op medians.

After a full default run the result is written into BENCH.md's
BENCH_SIGNAL_OF_RECORD block (single source of truth —
``tools/check_bench_docs.py`` verifies it against the newest parsed
``BENCH_r*.json``). ``python bench.py --sync-docs`` rewrites the block
from the newest parsed record without benchmarking.

Size configurable via TS_BENCH_GB (default 4; 1 on tunneled links).
TS_BENCH_TRIALS overrides the take-trial count (still deadline-guarded).
TS_BENCH_SKIP_PROTOCOL=1 skips the CPU-mesh subprocess legs (the cold
restore leg still runs — it is part of the restore story).
TS_BENCH_BUDGET_S overrides the wall-clock budget.
TS_BENCH_STEADY_TAKES overrides the steady-state autotune leg's take
count. TS_BENCH_RETENTION_MIB / TS_BENCH_RETENTION_STEPS size leg 9
(``retention_curve``): the 2-proc keep-last-N dense-retention loop
comparing cumulative storage, mirror-shipped and peer-pushed bytes with
the content-addressed chunk store on vs off (docs/cas.md).
TS_BENCH_COORD_WORLDS sizes leg 10 (``coordination_scaling``): storms
of simulated ranks through the real coordination code paths, tuned
topology vs the linear/per-key baseline plus the tree barrier's growth
curve (docs/scaling.md).
TS_BENCH_CDN_SUBSCRIBERS sizes leg 11 (``cdn_streaming``): the serving
fleet tracking a publishing trainer through a rolling update — median
publish-to-swap staleness, ~1x durable read amplification, and the
rolling-update dedup ratio (docs/cdn.md).
``--json-out PATH`` additionally writes the final record to a
file (the stdout tail can be truncated by the driver's capture —
BENCH_r04/r05 both parsed null for exactly that reason).
"""

import atexit
import json
import os
import re
import shutil
import signal
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs as ts_knobs
from torchsnapshot_tpu import scheduler as ts_scheduler
from torchsnapshot_tpu.telemetry import doctor as ts_doctor
from torchsnapshot_tpu.telemetry import names as ts_names

REFERENCE_SINGLE_ACCEL_GBPS = 20.0 / 13.91  # benchmarks/ddp/README.md:17

START = time.monotonic()
BUDGET_S = float(os.environ.get("TS_BENCH_BUDGET_S", "1200"))
RESERVE_S = 45.0  # kept back for finalization (ceiling-after, emission)
PROBE_TARGET_S = 12.0  # a scaled probe should cost about this much
# Repo-root by default (stable regardless of cwd, where the driver looks);
# overridable so tests/sandboxed runs don't dirty the working tree.
_PARTIAL_PATH = Path(
    os.environ.get(
        "TS_BENCH_PARTIAL_PATH",
        Path(__file__).resolve().parent / "BENCH_partial.json",
    )
)

# The record, filled leg by leg. Headline fields first so a partial
# record still leads with the metric contract.
RESULT = {
    "metric": "checkpoint_save_throughput",
    "value": None,
    "unit": "GB/s",
    "vs_baseline": None,
    "complete": False,
    "budget_s": BUDGET_S,
}
_FINAL_EMITTED = False
# --json-out: a file that receives the same final JSON record the last
# stdout line carries (set in __main__; None = stdout only).
_JSON_OUT = None
_OVERRIDES = [
    k
    for k in (
        "TS_BENCH_GB",
        "TS_BENCH_TRIALS",
        "TS_BENCH_SKIP_PROTOCOL",
        "TS_BENCH_BUDGET_S",
        "TS_BENCH_STEADY_TAKES",
        "TS_BENCH_RETENTION_MIB",
        "TS_BENCH_RETENTION_STEPS",
        "TS_BENCH_COORD_WORLDS",
    )
    if os.environ.get(k)
]


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - START)


def _have_budget(leg: str, est_s: float) -> bool:
    """Gate a leg on remaining budget; record the skip instead of
    silently narrowing coverage."""
    rem = _remaining() - RESERVE_S
    if rem < est_s:
        _log(
            f"bench: SKIPPING leg '{leg}' (est {est_s:.0f}s > {rem:.0f}s "
            f"left of {BUDGET_S:.0f}s budget)"
        )
        RESULT.setdefault("skipped_legs", []).append(leg)
        return False
    return True


def _write_partial_file() -> None:
    try:
        _PARTIAL_PATH.write_text(json.dumps(RESULT, indent=1))
    except OSError:
        pass


def _emit_partial(leg: str) -> None:
    """Print the full current record after every leg — the driver's tail
    carries the newest one even if the process is later SIGKILLed."""
    RESULT["last_leg"] = leg
    RESULT["elapsed_s"] = round(time.monotonic() - START, 1)
    print("bench-partial: " + json.dumps(RESULT, separators=(",", ":")), flush=True)
    _write_partial_file()


def _finalize_record(complete: bool) -> None:
    """Settle RESULT and keep BENCH.md's generated block equal to it.

    The block is rewritten on the termination path too: a killed default
    run still emits its final line, which the driver parses into the
    newest BENCH_r*.json — if the committed block kept quoting the
    previous round, the drift checker would go red through no drift at
    all. Non-default runs (TS_BENCH_* overrides) never touch the block."""
    RESULT["complete"] = complete
    RESULT["elapsed_s"] = round(time.monotonic() - START, 1)
    if complete:
        RESULT.pop("last_leg", None)
        try:
            _PARTIAL_PATH.unlink()
        except OSError:
            pass
    else:
        _write_partial_file()
    if _OVERRIDES:
        _log(
            f"bench: {'/'.join(_OVERRIDES)} set — leaving BENCH.md's "
            f"signal-of-record block untouched (non-default run)"
        )
    else:
        write_signal_of_record(RESULT)


def _write_json_out() -> None:
    """Best-effort copy of the final record to the --json-out file: a
    parse surface the driver's stdout capture cannot truncate."""
    if _JSON_OUT is None:
        return
    try:
        Path(_JSON_OUT).write_text(json.dumps(RESULT, indent=1))
    except OSError as e:
        _log(f"bench: could not write --json-out {_JSON_OUT}: {e!r}")


def _emit_final(complete: bool) -> None:
    global _FINAL_EMITTED
    if _FINAL_EMITTED:
        return
    _FINAL_EMITTED = True
    _finalize_record(complete)
    _write_json_out()
    # The final bare JSON line — the ONLY unprefixed stdout line, last,
    # single-line (compact separators keep it well under pipe-buffer
    # sizes so a tail capture gets all of it or none).
    print(json.dumps(RESULT, separators=(",", ":")), flush=True)


def _on_signal(signum, frame):  # noqa: ANN001 - signal handler signature
    """Flush a parseable record before dying. The bare JSON line goes out
    FIRST via raw os.write (print() is not re-entrant if the signal lands
    mid-print on the buffer lock, and this line IS the record the driver
    parses); the best-effort extras (partial file, BENCH.md rewrite —
    both print-happy) run after it, wrapped so a re-entrancy failure
    there can no longer cost the record itself."""
    global _FINAL_EMITTED
    if not _FINAL_EMITTED:
        _FINAL_EMITTED = True
        RESULT["terminated_by"] = signal.Signals(signum).name
        RESULT["complete"] = False
        RESULT["elapsed_s"] = round(time.monotonic() - START, 1)
        os.write(1, (json.dumps(RESULT, separators=(",", ":")) + "\n").encode())
        try:
            _write_json_out()
            _write_partial_file()
            if not _OVERRIDES:
                write_signal_of_record(RESULT)
        except BaseException:  # noqa: BLE001 - record already emitted
            pass
    os._exit(128 + signum)


def _install_handlers() -> None:
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    atexit.register(lambda: _emit_final(False))


def make_state(total_bytes: int, seed: int = 0) -> dict:
    """A pytree of bf16 arrays totaling ~total_bytes on device, shaped like
    transformer params (a few large 2-d weights + long 1-d tails).

    Each timed take gets a FRESH state (distinct seed): jax caches an
    array's host copy after its first D2H, so re-taking the same arrays
    measures a memcpy, not the device link."""
    key = jax.random.PRNGKey(seed)
    arrays = {}
    # 256 MiB bf16 blocks: (16384, 8192) * 2 bytes
    block_bytes = 16384 * 8192 * 2
    n_blocks = max(1, total_bytes // block_bytes)
    for i in range(n_blocks):
        key, sub = jax.random.split(key)
        arrays[f"w{i}"] = jax.random.normal(
            sub, (16384, 8192), dtype=jnp.bfloat16
        )
    arrays["bias"] = jnp.ones((65536,), dtype=jnp.float32)
    jax.block_until_ready(arrays)
    return arrays


def mutate_state_fraction(
    state: dict, step: int, fraction: float = 0.25
) -> dict:
    """Regenerate ~``fraction`` of the state's weight blocks (rotating
    by step) and leave the rest byte-identical — the partial-update
    shape real training hands the incremental/CAS path (frozen base +
    hot layers), where chunk reuse is a property of the workload rather
    than structurally zero. Mutated blocks are FRESH device arrays
    (fresh PRNG fold), so their D2H is honestly re-measured; the
    untouched blocks model frozen layers, whose host-copy cache hit is
    exactly the reuse the dedup path is supposed to exploit."""
    keys = [k for k in sorted(state) if k.startswith("w")]
    if not keys:
        return state
    n_hot = max(1, int(len(keys) * fraction))
    hot = {keys[(step * n_hot + j) % len(keys)] for j in range(n_hot)}
    out = dict(state)
    for k in sorted(hot):
        # Stable per-(step, block) fold: str hash() is process-salted.
        key = jax.random.PRNGKey(
            (step * 131071 + keys.index(k) * 8191 + 1) & 0x7FFFFFFF
        )
        out[k] = jax.random.normal(
            key, state[k].shape, dtype=state[k].dtype
        )
    jax.block_until_ready([out[k] for k in hot])
    return out


def probe_d2h(n_streams: int, chunk_mib: int = 32) -> float:
    """Measured D2H GB/s with ``n_streams`` concurrent async copies.

    ``copy_to_host_async`` on every array first, then materialize: the
    transfers overlap inside the runtime, so this measures the *attainable*
    device→host bandwidth — the checkpoint pipeline's physical ceiling —
    rather than the single-stream latency-bound rate.
    """
    side = int((chunk_mib * (1 << 20) // 2) ** 0.5)  # bf16 square
    keys = jax.random.split(jax.random.PRNGKey(1), n_streams)
    arrs = [jax.random.normal(k, (side, side), jnp.bfloat16) for k in keys]
    jax.block_until_ready(arrs)
    total = sum(a.nbytes for a in arrs)
    t0 = time.perf_counter()
    for a in arrs:
        a.copy_to_host_async()
    hosts = [np.asarray(a) for a in arrs]
    elapsed = time.perf_counter() - t0
    del hosts
    return total / (1 << 30) / elapsed


def probe_h2d(n_streams: int, chunk_mib: int = 32) -> float:
    """Measured H2D GB/s with ``n_streams`` concurrent ``device_put``s —
    the restore path's physical ceiling (storage reads feed streaming
    host→device placement). Pattern-matched to the restore's per-leaf
    placement streams the way ``probe_d2h`` matches the take's. RANDOM
    content (generated untimed): a transport layer that transparently
    compresses would make an all-zeros probe overstate the ceiling the
    efficiency ratio divides by."""
    dev = jax.devices()[0]
    rng = np.random.default_rng(2)
    side = int((chunk_mib * (1 << 20)) ** 0.5)
    hosts = [
        rng.integers(0, 255, (side, side), dtype=np.uint8)
        for _ in range(n_streams)
    ]
    total = sum(h.nbytes for h in hosts)
    t0 = time.perf_counter()
    devs = [jax.device_put(h, dev) for h in hosts]
    jax.block_until_ready(devs)
    elapsed = time.perf_counter() - t0
    del devs
    return total / (1 << 30) / elapsed


def _scaled_chunk_mib(rate_gbps: float, n_streams: int) -> int:
    """Probe chunk size targeting ~PROBE_TARGET_S of wall per probe at
    the measured rate, clamped to [32, 256] MiB: >=32 keeps the probe
    bandwidth-bound (not per-transfer-latency-bound) on slow links, and
    256 is the pipeline's actual leaf size."""
    if rate_gbps <= 0:
        return 32
    total_mib = rate_gbps * PROBE_TARGET_S * 1024
    return int(min(256, max(32, total_mib / n_streams)))


def _median_range(samples):
    return round(statistics.median(samples), 3), [
        round(min(samples), 3),
        round(max(samples), 3),
    ]


def _bracketed_efficiency(times_s, probes_gbps, gib, warmup=0):
    """Shared bracketed-efficiency epistemics for save AND restore (one
    definition, so the two legs can never drift apart): transfer i's
    ratio is achieved / max(probe_before, probe_after) — probes are
    lower bounds of attainable, so the bracket's max is the tightest
    estimate covering that window. Stability thresholds now live in the
    checkpoint doctor (telemetry/doctor.py) so the bench and production
    agree on what "unstable" means; ``link_unstable`` is the doctor's
    series-level probe check.

    ``warmup`` transfers are excluded from the MEDIAN efficiency and the
    instability check (r05's 0.429 first-take ratio was compile/pool
    warm-up, not link behavior, yet it dragged the reported mean and
    tripped link_unstable) — the raw per-transfer ratio list still
    carries every transfer, warm-up included. With too few transfers to
    spare the warm-up (len <= warmup) the full series is used. Returns
    (brackets, ratios, median_efficiency, link_unstable)."""
    brackets = [
        max(probes_gbps[i], probes_gbps[i + 1]) for i in range(len(times_s))
    ]
    ratios = [(gib / t) / b for t, b in zip(times_s, brackets) if b > 0]
    if not (0 < warmup < len(ratios)):
        warmup = 0
    efficiency = statistics.median(ratios[warmup:]) if ratios else 0.0
    unstable = ts_doctor.probes_unstable(probes_gbps[warmup:])
    return brackets, ratios, efficiency, unstable


def _cpu_mesh_env() -> dict:
    """Env for a CPU-backend subprocess leg: 8 virtual devices so the
    leg exercises real GSPMD shardings regardless of this host's chip."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TS_BENCH_GB", None)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
        env["XLA_FLAGS"] = flags
    return env


def _subprocess_json(label: str, script_parts, args, timeout: float, env=None):
    """Run a benchmark script in a subprocess (CPU backend by default;
    pass ``env`` for a default-platform leg); parse its final stdout line
    as JSON. Fail-soft: every leg is a context metric — a broken leg
    logs and returns None instead of killing the headline record. The
    timeout is additionally capped by the remaining wall budget."""
    timeout = min(timeout, max(30.0, _remaining() - RESERVE_S))
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), *script_parts
    )
    try:
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, script, *args],
            env=_cpu_mesh_env() if env is None else env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip()[-500:])
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        _log(f"bench: {label} leg took {time.perf_counter() - t0:.1f}s")
        return out
    except Exception as e:  # noqa: BLE001 - context metric only
        _log(f"bench: {label} leg failed: {e!r}")
        return None


def run_subprocess_legs() -> None:
    """The CPU-mesh legs, in value order, each budget-gated and
    time-boxed. They run BEFORE the take loop: round 4's record died
    with the orbax head-to-head — the single most load-bearing
    competitive claim — queued behind a take loop that overran."""
    if os.environ.get("TS_BENCH_SKIP_PROTOCOL") == "1":
        _log("bench: TS_BENCH_SKIP_PROTOCOL=1 — skipping subprocess legs")
        return

    if _have_budget("orbax", 240):
        orbax = _subprocess_json(
            "orbax-compare",
            ("benchmarks", "orbax_compare", "main.py"),
            ["--gb", "1", "--trials", "3", "--json"],
            timeout=600,
        )
        if orbax is not None:
            RESULT["orbax_save_ratio"] = orbax.get("orbax_save_ratio")
            RESULT["orbax_restore_ratio"] = orbax.get("orbax_restore_ratio")
            RESULT["orbax"] = orbax
            _log(
                f"bench: orbax head-to-head (1 GiB, CPU mesh, checksums on): "
                f"save ratio {orbax.get('orbax_save_ratio')}x, restore ratio "
                f"{orbax.get('orbax_restore_ratio')}x (orbax/ours, >1 = ours "
                f"faster)"
            )
        _emit_partial("orbax")

    if _have_budget("cpu_mesh_stall", 180):
        mesh_row = _subprocess_json(
            "cpu-mesh-stall",
            ("benchmarks", "sharded_transformer", "main.py"),
            ["--d-model", "512", "--layers", "8", "--async-take", "--json"],
            timeout=420,
        )
        if mesh_row is not None and "stall_ms" in mesh_row:
            RESULT["cpu_mesh_stall_ms"] = mesh_row["stall_ms"]
            RESULT["cpu_mesh_save_total_s"] = mesh_row.get("save_total_s")
            RESULT["cpu_mesh_state_gib"] = mesh_row.get("state_gib")
            _log(
                f"bench: cpu-mesh async stall {mesh_row['stall_ms']} ms of "
                f"{mesh_row.get('save_total_s')} s total "
                f"({mesh_row.get('state_gib')} GiB sharded train state)"
            )
        _emit_partial("cpu_mesh_stall")

    if _have_budget("cold_start", 240):
        cold_start_rows()
        _emit_partial("cold_start")

    if _have_budget("protocol_overhead", 150):
        proto = _subprocess_json(
            "protocol-overhead",
            ("benchmarks", "replicated_save", "protocol_overhead.py"),
            ["--gb", "0.125"],
            timeout=420,
        )
        if proto is not None:
            RESULT["protocol_overhead"] = proto
        _emit_partial("protocol_overhead")

    if _have_budget("fanout_restore", 180):
        # The read-path distributed story: 2-proc restore with fan-out
        # (each unique saved shard fetched from storage exactly once,
        # peers fed over the coordination store) vs the every-rank-reads
        # fallback — wall time plus the fleet read-amplification ratio
        # (total fetched / unique checkpoint bytes; fallback ~= world,
        # fan-out ~= 1.0). docs/restore.md.
        fr = _subprocess_json(
            "fanout-restore",
            ("benchmarks", "fanout_restore.py"),
            ["--mib", "256", "--json"],
            timeout=420,
        )
        if fr is not None:
            RESULT["fanout_restore"] = fr
            RESULT["fanout_restore_s"] = fr.get("fanout_restore_s")
            RESULT["fallback_restore_s"] = fr.get("fallback_restore_s")
            RESULT["fanout_read_amplification"] = fr.get(
                "fanout_read_amplification"
            )
            RESULT["fallback_read_amplification"] = fr.get(
                "fallback_read_amplification"
            )
            _log(
                f"bench: fan-out restore {fr.get('fanout_restore_s')} s at "
                f"{fr.get('fanout_read_amplification')}x fleet read "
                f"amplification vs fallback "
                f"{fr.get('fallback_restore_s')} s at "
                f"{fr.get('fallback_read_amplification')}x"
            )
        _emit_partial("fanout_restore")

    if _have_budget("peer_restore", 180):
        # The recovery half of the robustness story: 2-proc save with
        # the peer-RAM tier pushing shards into the ring neighbor,
        # rank 1 "preempted" (cache wiped, replacement re-announces),
        # then restore with peer on vs kill-switched off — recording
        # the replacement's recovery wall and the per-tier byte split
        # (peer vs storage) the ledger's restore-served events carry.
        # docs/peer.md.
        pr = _subprocess_json(
            "peer-restore",
            ("benchmarks", "peer_restore.py"),
            ["--mib", "64", "--json"],
            timeout=420,
        )
        if pr is not None:
            RESULT["peer_restore"] = pr
            RESULT["peer_recovery_wall_s"] = pr.get("peer_recovery_wall_s")
            RESULT["fallback_recovery_wall_s"] = pr.get(
                "fallback_recovery_wall_s"
            )
            _log(
                f"bench: peer-tier recovery "
                f"{pr.get('peer_recovery_wall_s')} s (tier split "
                f"{pr.get('peer_recovery_tier_split')}) vs fallback "
                f"{pr.get('fallback_recovery_wall_s')} s from storage"
            )
        _emit_partial("peer_restore")

    if _have_budget("retention_curve", 240):
        # Leg 9 — dense-retention economics (docs/cas.md): a 2-proc
        # keep_last_n=20 manager loop over a sparsely-updated layered
        # state on a tiered root with peer pushes and the ledger on,
        # content-addressed store ON vs the legacy layout. The three
        # curves (cumulative storage footprint, mirror bytes shipped,
        # peer bytes pushed) are the acceptance instrument: CAS should
        # hold storage at ~1 full step + deltas while mirror/peer
        # traffic shrinks to the novel chunks.
        rc = _subprocess_json(
            "retention-curve",
            ("benchmarks", "retention_curve.py"),
            ["--mib", os.environ.get("TS_BENCH_RETENTION_MIB", "32"),
             "--steps", os.environ.get("TS_BENCH_RETENTION_STEPS", "6"),
             "--json"],
            timeout=540,
        )
        if rc is not None:
            RESULT["retention_curve"] = rc
            RESULT["cas_storage_ratio_vs_one_step"] = (
                rc.get("cas") or {}
            ).get("storage_ratio_vs_one_step")
            RESULT["legacy_storage_ratio_vs_one_step"] = (
                rc.get("legacy") or {}
            ).get("storage_ratio_vs_one_step")
            RESULT["cas_storage_savings"] = rc.get("cas_storage_savings")
            _log(
                f"bench: retention curve — CAS storage "
                f"{RESULT['cas_storage_ratio_vs_one_step']}x of one step "
                f"vs legacy {RESULT['legacy_storage_ratio_vs_one_step']}x "
                f"({rc.get('cas_storage_savings')}x total savings)"
            )
        _emit_partial("retention_curve")

    if _have_budget("coordination_scaling", 150):
        # Leg 10 — coordination-plane scaling (docs/scaling.md): full
        # save/restore/endpoint storms through the REAL dist_store/
        # fanout code paths at world {8, 64, 256} simulated ranks over
        # TCP, tuned defaults (TreeBarrier + batched multi-key ops +
        # poll backoff + 2 store shards) vs the pre-scale-model
        # baseline (LinearBarrier, per-key wire ops, fixed 5 ms
        # polling, one hub), plus the tree barrier's growth curve and
        # hot-key fan-in. The acceptance instrument for the O(world)
        # coordination-wall work: regressions in the topology show up
        # as a speedup collapse or a super-linear slope here.
        cs = _subprocess_json(
            "coordination-scaling",
            ("benchmarks", "coordination_scaling.py"),
            ["--worlds", os.environ.get(
                "TS_BENCH_COORD_WORLDS", "8,64,256"
            ), "--json"],
            timeout=420,
        )
        if cs is not None:
            RESULT["coordination_scaling"] = cs
            RESULT["coordination_speedup_256"] = cs.get(
                "coordination_speedup_max_world"
            )
            RESULT["coordination_sublinear"] = cs.get("sublinear")
            _log(
                f"bench: coordination scaling — "
                f"{cs.get('coordination_speedup_max_world')}x vs the "
                f"linear/per-key baseline at world "
                f"{(cs.get('worlds') or [None])[-1]}, tree growth slope "
                f"{cs.get('tree_growth_slope')} "
                f"(sublinear={cs.get('sublinear')})"
            )
        _emit_partial("coordination_scaling")

    if _have_budget("cdn_streaming", 150):
        # Leg 11 — checkpoint-CDN weight streaming (docs/cdn.md): a
        # 100+ subscriber serving fleet (TS_BENCH_CDN_SUBSCRIBERS)
        # tracks a publishing trainer through a rolling update. The
        # pins: sub-second median publish-to-swap staleness, ~1x
        # durable read amplification (owner election: each unique
        # chunk leaves storage once, fleet-size-independent), and a
        # dedup ratio well under 1 (only churned chunks on the wire).
        cdn = _subprocess_json(
            "cdn-streaming",
            ("benchmarks", "cdn_streaming.py"),
            ["--subscribers", os.environ.get(
                "TS_BENCH_CDN_SUBSCRIBERS", "100"
            ), "--json"],
            timeout=420,
        )
        if cdn is not None:
            RESULT["cdn_streaming"] = cdn
            RESULT["cdn_staleness_median_s"] = cdn.get(
                "staleness_median_s"
            )
            RESULT["cdn_read_amplification"] = cdn.get(
                "read_amplification"
            )
            RESULT["cdn_dedup_ratio"] = cdn.get("dedup_ratio")
            _log(
                f"bench: cdn streaming — "
                f"{cdn.get('converged_subscribers')} subscribers, "
                f"staleness median {cdn.get('staleness_median_s')}s, "
                f"read amplification {cdn.get('read_amplification')}x, "
                f"dedup {cdn.get('dedup_ratio')}"
            )
        _emit_partial("cdn_streaming")


def cold_start_rows() -> None:
    """Restore-to-step0 (BASELINE.md north star): sync restore wall vs
    the visible (not-hidden) restore wall when async restore overlaps
    the train-step compile. Three fresh processes sharing one snapshot
    dir: prep (create), sync timed, async timed — fresh because jit
    caches would poison the compile timing."""
    snap_dir = os.path.join(tempfile.gettempdir(), "ts_bench_cold_start")
    shutil.rmtree(snap_dir, ignore_errors=True)
    script = ("benchmarks", "sharded_transformer", "cold_start.py")
    try:
        _subprocess_json(
            "cold-start-prep",
            script,
            ["--mode", "sync", "--snap", snap_dir, "--prep-only", "--json"],
            timeout=300,
        )
        sync_row = _subprocess_json(
            "cold-start-sync",
            script,
            ["--mode", "sync", "--snap", snap_dir, "--json"],
            timeout=300,
        )
        async_row = _subprocess_json(
            "cold-start-async",
            script,
            ["--mode", "async", "--snap", snap_dir, "--json"],
            timeout=300,
        )
        if sync_row and async_row:
            RESULT["cold_start_sync_s"] = sync_row["restore_visible_s"]
            RESULT["cold_start_async_visible_s"] = async_row["restore_visible_s"]
            RESULT["cold_start"] = {"sync": sync_row, "async": async_row}
            _log(
                f"bench: cold start restore-to-step0: sync restore "
                f"{sync_row['restore_visible_s']} s visible vs async "
                f"{async_row['restore_visible_s']} s visible (hidden under "
                f"{async_row['compile_s']} s compile)"
            )
    finally:
        shutil.rmtree(snap_dir, ignore_errors=True)


def _ledger_goodput(root: str) -> dict:
    """Run-level goodput fields for a RESULT leg, read from the leg's
    run ledger (telemetry/goodput.py): the overhead fraction, recovery
    cost, and storage bytes/step the per-op medians cannot show. {}
    when the ledger is disabled or empty (fail-soft context data)."""
    try:
        from torchsnapshot_tpu.telemetry import goodput as ts_goodput

        analysis = ts_goodput.analyze_root(root)
        run = ts_goodput.latest_run(analysis) if analysis else None
        if run is None:
            return {}
        storage = analysis["storage"]
        return {
            "overhead_fraction": run["overhead_fraction"],
            "wall_s": round(run["wall_s"], 3),
            "train_s": round(run["train_s"], 3),
            "visible_stall_s": round(run["visible_stall_s"], 3),
            "restore_s": round(run["restore_s"], 3),
            "lost_work_s": round(run["lost_work_s"], 3),
            "lost_steps": run["lost_steps"],
            "recovery_cost_s": round(
                sum(i["recovery_cost_s"] for i in run["interruptions"]), 3
            ),
            "interruptions": len(run["interruptions"]),
            "steps_committed": run["steps_committed"],
            "storage_bytes_per_step": storage["bytes_per_retained_step"],
            "incremental_reuse_ratio": storage["incremental_reuse_ratio"],
        }
    except Exception as e:  # noqa: BLE001 - context data, fail-soft
        _log(f"bench: goodput summary failed: {e!r}")
        return {}


def _slo_summary(root: str) -> dict:
    """SLO verdicts for a RESULT leg (telemetry/slo.py): the max burn
    rate, which objectives are breaching, and the per-objective burn —
    a bench record says not just how fast the leg was but whether the
    run kept its declared promises. {} when no ledger (fail-soft)."""
    try:
        from torchsnapshot_tpu.telemetry import slo as ts_slo

        result = ts_slo.evaluate_root(root)
        if result is None:
            return {}
        enabled = [
            o for o in result["objectives"] if not o["disabled"]
        ]
        return {
            "burn_rate": max(
                (o["burn_rate"] for o in enabled), default=0.0
            ),
            "breaching": result["breaching"],
            "objectives": {
                o["objective"]: {
                    "burn_rate": o["burn_rate"],
                    "samples": o["samples"],
                    "target": o["target"],
                }
                for o in enabled
                if o["samples"]
            },
        }
    except Exception as e:  # noqa: BLE001 - context data, fail-soft
        _log(f"bench: slo summary failed: {e!r}")
        return {}


def preemption_leg(workdir: str, total_bytes: int, est_take_s: float) -> None:
    """Leg 8: preemption recovery cost, ledger-accounted.

    A manager runs a short save-every-other-step loop with the run
    ledger on; a preemption notice lands AFTER the last save and the
    grace window is 'missed' (no coordinated save commits), so the
    trailing work is genuinely lost; a fresh manager then restores.
    ``RESULT.preemption.goodput`` carries what the fleet actually pays
    for that interruption — lost work + restore time — from the same
    ledger records the doctor's ``recovery-cost-high`` rule cites.
    Quarter-size state: this leg measures recovery accounting, not
    link bandwidth (the headline legs own that)."""
    nb = max(total_bytes // 4, 32 * 1024 * 1024)
    est = est_take_s / 2 + 5
    if not _have_budget("preemption", est * 3):
        return
    from torchsnapshot_tpu.preemption import PreemptionSaver

    root = os.path.join(workdir, "preempt")
    try:
        # CAS + incremental ON: a recurring save loop is exactly the
        # shape the dedup path exists for (step 2 re-saves step 0's
        # unchanged state), so the leg's ``incremental_reuse_ratio`` is
        # a real measurement instead of structurally 0.0.
        with ts_knobs.enable_cas():
            mgr = ts.CheckpointManager(
                root, keep_last_n=2, incremental=True
            )
            saver = PreemptionSaver(signals=(), ledger_root=root)
            state = make_state(nb, seed=97)
            try:
                for step in range(4):
                    if step % 2 == 0:
                        mgr.save(step, {"state": ts.PyTreeState(state)})
                    if step == 3:
                        # Eviction notice after the step-2 save; the
                        # agreed save misses the grace window (we never
                        # call mgr.save for it), so step 3's work is
                        # genuinely lost.
                        saver.request_save()
                        saver.should_save(step)
            finally:
                saver.uninstall()
            dest = make_state(nb, seed=97)
            t0 = time.perf_counter()
            mgr2 = ts.CheckpointManager(
                root, keep_last_n=2, incremental=True
            )
            restored = mgr2.restore_latest({"state": ts.PyTreeState(dest)})
            restore_s = time.perf_counter() - t0
        del state, dest
        # Recovery accounting the peer tier adds (docs/peer.md): the
        # wall the fleet paid for this restore and which tier of the
        # peer -> fast -> durable ladder served the bytes (single
        # process here, so the split is storage-only; the 2-proc
        # peer_restore leg pins the peer-served case).
        from torchsnapshot_tpu import telemetry as _telemetry

        recovery_report = _telemetry.last_report(
            "restore", path=mgr2.step_path(restored)
        ) if restored is not None else None
        RESULT["preemption"] = {
            "restored_step": restored,
            "restore_s": round(restore_s, 3),
            "recovery_wall_s": round(restore_s, 3),
            "recovery_tier_split": (
                recovery_report.tier_split if recovery_report else None
            ),
            "goodput": _ledger_goodput(root),
            "slo": _slo_summary(root),
        }
        _log(
            f"bench: preemption leg restored step {restored} in "
            f"{restore_s:.2f}s; goodput {RESULT['preemption']['goodput']}"
        )
    except Exception as e:  # noqa: BLE001 - context leg, fail-soft
        _log(f"bench: preemption leg failed: {e!r}")
    _emit_partial("preemption")


def write_path_leg(workdir: str) -> None:
    """Leg 5b: zero-pack write-path microbench (ISSUE 11's structural
    claim, measured): one >=256 MiB batched take through each write-path
    variant — the packed slab path (stage into a contiguous buffer, then
    fused write+CRC), the zero-pack vectorized path (member buffers
    straight to pwritev+CRC, no pack pass), and the packed path with
    O_DIRECT enabled (declines to buffered on filesystems without it).
    Host-numpy state on purpose: this leg isolates the host-side
    pack+write cost the tentpole removes, not the device link the
    headline legs own. Each variant's SnapshotReport ``write_path``
    split is recorded so the numbers are attributable."""
    if not _have_budget("write_path", 150):
        return
    from torchsnapshot_tpu import telemetry as _telemetry

    mib = int(os.environ.get("TS_BENCH_WRITE_PATH_MIB", "256"))
    trials = int(os.environ.get("TS_BENCH_WRITE_PATH_TRIALS", "3"))
    n_members = max(2, mib // 8)
    rng = np.random.default_rng(17)
    state = {
        f"w{i}": rng.integers(0, 255, (8 << 20,), dtype=np.uint8)
        for i in range(n_members)
    }
    gib = sum(a.nbytes for a in state.values()) / (1 << 30)
    variants = {
        "packed": ts_knobs.disable_write_vectorized,
        "vectorized": ts_knobs.enable_write_vectorized,
        "packed_direct": None,  # packed + O_DIRECT, see run_once
    }
    results = {
        "size_gib": round(gib, 3),
        "trials": trials,
        **{tag: {"times_s": []} for tag in variants},
    }

    def run_once(tag: str, timed: bool) -> float:
        path = os.path.join(workdir, f"wp_{tag}")
        if tag == "packed_direct":
            import contextlib

            ctx = contextlib.ExitStack()
            ctx.enter_context(ts_knobs.disable_write_vectorized())
            ctx.enter_context(ts_knobs.enable_fs_direct_io())
        else:
            ctx = variants[tag]()
        with ctx:
            os.sync()  # park earlier legs' dirty pages before timing
            t0 = time.perf_counter()
            ts.Snapshot.take(path, {"s": ts.PyTreeState(state)})
            elapsed = time.perf_counter() - t0
        if timed:
            rep = _telemetry.last_report("take", path=path)
            results[tag]["write_path"] = (
                rep.write_path if rep is not None else None
            )
        shutil.rmtree(path, ignore_errors=True)
        return elapsed

    try:
        with ts_knobs.enable_batching():
            # One untimed warm-up round (thread pools, native lib, dir
            # cache), then INTERLEAVED timed rounds: background
            # writeback drifts minute-to-minute on a shared box, and
            # back-to-back per-variant runs would charge that drift to
            # whichever variant ran last. Median per variant.
            for tag in variants:
                run_once(tag, timed=False)
            for _ in range(trials):
                for tag in variants:
                    results[tag]["times_s"].append(
                        round(run_once(tag, timed=True), 3)
                    )
        for tag in variants:
            med = statistics.median(results[tag]["times_s"])
            results[tag]["take_s"] = round(med, 3)
            results[tag]["gbps"] = round(gib / med, 3)
        results["zero_pack_speedup"] = round(
            results["packed"]["take_s"] / results["vectorized"]["take_s"], 3
        )
        RESULT["write_path"] = results
        RESULT["write_path_zero_pack_speedup"] = results["zero_pack_speedup"]
        _log(
            f"bench: write-path microbench ({gib:.2f} GiB batched take, "
            f"median of {trials} interleaved): packed "
            f"{results['packed']['take_s']} s "
            f"({results['packed']['gbps']} GB/s, {results['packed']['times_s']}) "
            f"vs zero-pack {results['vectorized']['take_s']} s "
            f"({results['vectorized']['gbps']} GB/s, "
            f"{results['vectorized']['times_s']}) — "
            f"{results['zero_pack_speedup']}x; packed+O_DIRECT "
            f"{results['packed_direct']['take_s']} s "
            f"({results['packed_direct']['times_s']}, variants "
            f"{results['packed_direct'].get('write_path')})"
        )
    except Exception as e:  # noqa: BLE001 - context leg, fail-soft
        _log(f"bench: write-path leg failed: {e!r}")
    _emit_partial("write_path")


def steady_state_leg(
    workdir: str,
    total_bytes: int,
    gib: float,
    probe_streams: int,
    link_est: float,
    est_take_s: float,
) -> None:
    """Leg 7: steady-state multi-take convergence under the autotuner.

    The single-take legs above measure the pipeline as configured; this
    leg measures whether the closed loop (tuner/autotuner.py) *improves*
    it across a recurring-checkpoint run: a CheckpointManager saves N
    fresh states through the same bracketed-probe epistemics as the
    headline leg, the autotuner adjusting knobs between takes, and the
    record carries per-take efficiency + the applied knob trajectory so
    convergence (or thrashing) is visible in the BENCH_r* series.
    Fail-soft and budget-gated per take like every other context leg."""
    takes = int(os.environ.get("TS_BENCH_STEADY_TAKES", "5"))
    per_take_est = est_take_s + PROBE_TARGET_S
    if not _have_budget("steady_state", per_take_est * min(takes, 2)):
        return
    from torchsnapshot_tpu.tuner import state as tuner_state_mod
    from torchsnapshot_tpu.tuner import reset_overrides

    from torchsnapshot_tpu import telemetry as _telemetry

    root = os.path.join(workdir, "steady")
    autotune_on = ts_knobs.is_autotune_enabled()
    times, probes, effs, knob_traj, write_paths = [], [], [], [], []
    legacy_times = []
    try:
        est = max(link_est, 1e-3)

        def probe(tag: str) -> None:
            nonlocal est
            chunk = _scaled_chunk_mib(est, probe_streams)
            p = probe_d2h(probe_streams, chunk_mib=chunk)
            probes.append(p)
            est = p
            _log(f"bench: steady-state probe {tag}: {p:.3f} GB/s")

        probe("before steady 0")
        # CAS + incremental ON, one persistent state mutated a fraction
        # per take: a recurring-checkpoint loop over a partially-updated
        # model is the workload the dedup path exists for, so the leg's
        # ``incremental_reuse_ratio`` measures the workload instead of
        # being structurally 0.0 (fresh full-random states per take
        # defeat content-addressed dedup by construction). The legacy
        # sub-trial below keeps the pre-CAS measurement comparable.
        state = make_state(total_bytes, seed=31)
        with ts_knobs.enable_cas():
            mgr = ts.CheckpointManager(
                root, keep_last_n=1, incremental=True
            )
            for i in range(takes):
                if i > 0 and not _have_budget(f"steady{i}", per_take_est):
                    break
                if i > 0:
                    state = mutate_state_fraction(state, i)
                knob_traj.append(ts_knobs.tunable_snapshot())
                t0 = time.perf_counter()
                mgr.save(i, {"state": ts.PyTreeState(state)})
                times.append(time.perf_counter() - t0)
                # Which write-path variant served this take (vectorized /
                # direct / fused / buffered bytes): alongside the knob
                # trajectory, what lets a knob flip be correlated with
                # the efficiency move it caused.
                rep = _telemetry.last_report("take", path=mgr.step_path(i))
                write_paths.append(
                    rep.write_path if rep is not None else None
                )
                probe(f"after steady {i}")
                effs.append(
                    (gib / times[-1]) / max(probes[-2], probes[-1])
                )
                _log(
                    f"bench: steady take {i}: {times[-1]:.2f} s, "
                    f"efficiency {effs[-1]:.3f}x of bracket"
                )
        del state
        # Legacy sub-trial: the pre-honesty-fix shape (fresh full-random
        # state per take, no CAS, no incremental) so the BENCH_r* series
        # keeps a directly comparable point across the methodology
        # change.
        legacy_root = os.path.join(workdir, "steady_legacy")
        with ts_knobs.disable_cas():
            legacy_mgr = ts.CheckpointManager(legacy_root, keep_last_n=1)
            for i in range(min(2, takes)):
                if not _have_budget(f"steady legacy{i}", per_take_est):
                    break
                lstate = make_state(total_bytes, seed=131 + i)
                t0 = time.perf_counter()
                legacy_mgr.save(i, {"state": ts.PyTreeState(lstate)})
                legacy_times.append(time.perf_counter() - t0)
                del lstate
                _log(
                    f"bench: steady legacy take {i}: "
                    f"{legacy_times[-1]:.2f} s"
                )
        decisions = []
        st = tuner_state_mod.load_state(root)
        if st is not None:
            decisions = [
                {
                    "step": d.get("step"),
                    "action": d["decision"].get("action"),
                    "tunable": d["decision"].get("tunable"),
                    "reason": d["decision"].get("reason"),
                }
                for d in st.decisions
            ]
        RESULT["steady_state"] = {
            "autotune": autotune_on,
            "cas": True,
            "incremental": True,
            "legacy": {
                "takes": len(legacy_times),
                "take_times_s": [round(t, 2) for t in legacy_times],
            },
            "takes": len(times),
            "take_times_s": [round(t, 2) for t in times],
            "per_take_efficiency": [round(e, 3) for e in effs],
            "d2h_probes": [round(p, 3) for p in probes],
            "final_efficiency": round(effs[-1], 3) if effs else None,
            "knob_trajectory": knob_traj,
            "write_path_per_take": write_paths,
            "decisions": decisions,
            # Run-level accounting from the leg's ledger: the fraction
            # of THIS multi-take run's wall time that checkpointing
            # ate, and the storage spend per retained step — BENCH_r06+
            # carries run-level numbers, not just per-op medians.
            "goodput": _ledger_goodput(root),
            # The same ledger judged against the declared SLOs: did
            # the steady-state loop keep its promises, and how fast
            # was it spending error budget at the end.
            "slo": _slo_summary(root),
        }
        if effs:
            RESULT["steady_state_final_efficiency"] = round(effs[-1], 3)
    except Exception as e:  # noqa: BLE001 - context leg, fail-soft
        _log(f"bench: steady-state leg failed: {e!r}")
    finally:
        # The tuned vector must not leak into later probes/legs or a
        # reused process: the leg measures the loop, not the residue.
        reset_overrides()
    _emit_partial("steady_state")


DOC_BLOCK_RE = re.compile(
    r"<!-- BENCH_SIGNAL_OF_RECORD.*?-->\s*```json\s*\{.*?\}\s*```",
    re.DOTALL,
)


def write_signal_of_record(record: dict) -> None:
    """Rewrite BENCH.md's signal-of-record block in place (single source
    of truth: the block is generated from the measured record, never
    hand-maintained; tools/check_bench_docs.py verifies it against the
    newest parsed driver-captured BENCH_r*.json)."""
    bench_md = Path(__file__).resolve().parent / "BENCH.md"
    try:
        text = bench_md.read_text()
        block = (
            "<!-- BENCH_SIGNAL_OF_RECORD: generated by bench.py; verified "
            "against the newest BENCH_r*.json -->\n```json\n"
            + json.dumps(record, indent=2)
            + "\n```"
        )
        new_text, n = DOC_BLOCK_RE.subn(lambda _: block, text, count=1)
        if n != 1:
            raise RuntimeError("no BENCH_SIGNAL_OF_RECORD block found")
        if new_text != text:
            # Atomic replace: this also runs from the SIGTERM handler,
            # and a truncated committed BENCH.md would be worse than a
            # stale block.
            tmp = bench_md.with_suffix(".md.tmp")
            tmp.write_text(new_text)
            os.replace(tmp, bench_md)
            _log("bench: BENCH.md signal-of-record block updated")
    except Exception as e:  # noqa: BLE001 - docs update must not kill output
        _log(f"bench: BENCH.md update failed: {e!r}")


def sync_docs() -> int:
    """--sync-docs: regenerate BENCH.md's block from the newest parsed
    BENCH_r*.json (no benchmarking). The record is located by the
    *verifier's* own ``newest_record`` so the writer and the checker can
    never disagree about which record is the signal of record."""
    sys.path.insert(0, str(Path(__file__).resolve().parent / "tools"))
    from check_bench_docs import newest_record

    record, path = newest_record()
    if record is None:
        _log(
            "bench: no BENCH_r*.json with a non-null parsed record "
            "(none present, or every round timed out); nothing to sync"
        )
        return 1
    write_signal_of_record(record)
    _log(f"bench: synced BENCH.md from {path.name}")
    return 0


def main() -> None:
    _install_handlers()
    _log(f"bench: wall budget {BUDGET_S:.0f}s (TS_BENCH_BUDGET_S to override)")

    # ---- Leg 1: link measurement (sets every later cost estimate) ----
    quick = probe_d2h(1, chunk_mib=16)
    tunneled = quick <= 0.5
    d2h_single = quick if tunneled else probe_d2h(1, chunk_mib=256)
    chunk0 = _scaled_chunk_mib(max(quick, 0.005), 4)
    conc = probe_d2h(4, chunk_mib=chunk0)
    ceiling_before = max(d2h_single, conc)
    link_est = ceiling_before
    _log(
        f"bench: raw D2H single-stream = {d2h_single:.3f} GB/s, "
        f"concurrent (4x{chunk0} MiB) = {conc:.3f} GB/s"
    )
    RESULT["d2h_single_gbps"] = round(d2h_single, 3)
    RESULT["tunneled"] = tunneled
    _emit_partial("link_probe")

    gb_env = os.environ.get("TS_BENCH_GB")
    gb = float(gb_env) if gb_env is not None else 4.0
    if gb_env is None and tunneled:
        # Tunnel-limited link: the save is pure D2H wall time, so extra
        # gigabytes add minutes without changing any reported ratio.
        gb = 1.0
        _log("bench: tunneled D2H detected; defaulting to 1 GiB state")
    total_bytes = int(gb * (1 << 30))
    gib_planned = total_bytes / (1 << 30)
    est_take_s = gib_planned / max(link_est, 1e-3) * 1.2 + 10

    # ---- Leg 2: CPU-mesh subprocess legs (before the take loop) ----
    run_subprocess_legs()

    # ---- Leg 3: timed takes, bracketed by matched scaled probes ----
    _log(f"bench: materializing ~{gb:.1f} GiB of bf16 state on {jax.devices()[0]}")
    state = make_state(total_bytes, seed=0)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    gib = nbytes / (1 << 30)

    workdir = tempfile.mkdtemp(prefix="ts_bench_", dir="/tmp")
    incr_elapsed = None
    take_times = []
    matched_probes = []
    take_phases = []
    restore_times = []
    h2d_probes = []
    try:
        # Warm-up on a small state: first-take costs (event loop, thread
        # pools, XLA transfer program) should not pollute the measurement.
        warm = {"x": jnp.ones((1024, 1024), jnp.bfloat16)}
        ts.Snapshot.take(os.path.join(workdir, "warm"), {"s": ts.PyTreeState(warm)})

        # Headline: median of N PLAIN takes — comparable to the reference
        # baseline and earlier rounds (no digest recording in the timed
        # path). Every trial snapshots a FRESH state: jax caches host
        # copies per array, and re-taking cached arrays would time a
        # memcpy instead of the device link. Every take is BRACKETED by
        # PATTERN-MATCHED ceiling probes (same stream count as the take's
        # large leaves, volume scaled to the link): each trial's
        # efficiency is achieved / max(probe_before, probe_after) —
        # probes are lower bounds of attainable, and the bracket's max is
        # the tightest estimate for that trial's time window. The probe
        # after take i doubles as the probe before take i+1.
        trials_env = os.environ.get("TS_BENCH_TRIALS")
        if trials_env is not None:
            trials = int(trials_env)
        else:
            budget_for_takes = 0.45 * max(_remaining() - RESERVE_S, 0)
            trials = max(
                1,
                min(
                    5 if tunneled else 3,
                    int(budget_for_takes / (est_take_s + PROBE_TARGET_S)),
                ),
            )
        _log(
            f"bench: {trials} take trials (est {est_take_s:.0f}s each, "
            f"{_remaining():.0f}s budget left)"
        )
        dest_template = {k: (v.shape, v.dtype) for k, v in state.items()}
        trial_state = state
        state = None  # one state on device at a time: 1x HBM, not 2x
        n_blocks = max(1, total_bytes // (16384 * 8192 * 2))
        probe_streams = min(4, n_blocks)

        def matched_probe(tag: str) -> None:
            # Each probe re-estimates the link for the next one's sizing
            # (the tunnel drifts 2-4x minute-to-minute; a chunk sized for
            # a stale fast estimate would cost several times the target).
            nonlocal link_est
            chunk = _scaled_chunk_mib(link_est, probe_streams)
            mc = probe_d2h(probe_streams, chunk_mib=chunk)
            matched_probes.append(mc)
            link_est = mc
            _log(
                f"bench: matched ceiling probe {tag} "
                f"({probe_streams}x{chunk} MiB): {mc:.3f} GB/s"
            )

        # Flight-recorder trace export ON for the timed takes: a trial
        # that trips the in-take stall heuristic embeds its own span
        # evidence in the record (the recorder always runs; this knob
        # only adds one small JSON dump per take — noise against the
        # GiB-scale writes being timed).
        os.environ.setdefault("TORCHSNAPSHOT_TPU_TRACE", "1")
        stall_trace_info = {}
        matched_probe("before take 0")
        for i in range(trials):
            if i > 0 and not _have_budget(
                f"take{i}", est_take_s + PROBE_TARGET_S
            ):
                break
            path = os.path.join(workdir, f"snap{i}")
            ts_scheduler.reset_phase_timings()
            t0 = time.perf_counter()
            ts.Snapshot.take(path, {"state": ts.PyTreeState(trial_state)})
            take_times.append(time.perf_counter() - t0)
            take_phases.append(ts_scheduler.last_phase_timings())
            _log(
                f"bench: take {i}: {take_times[-1]:.2f} s "
                f"(phases {take_phases[-1]})"
            )
            matched_probe(f"after take {i}")
            # Stall self-diagnosis runs NOW, not after the loop: the
            # snap dir (and its .trace-take-rank0.json) is deleted
            # before the next trial, so the top spans must be read
            # while the evidence exists. The diagnosis itself is the
            # shared checkpoint doctor's — the same rule production
            # callers get — so bench and doctor can never disagree
            # about what "stalled" means.
            a, b = matched_probes[i], matched_probes[i + 1]
            trial_verdicts = ts_doctor.diagnose_take_trial(
                take_times[-1], gib, a, b, phases=take_phases[-1]
            )
            if any(
                v.rule == ts_names.RULE_IN_TAKE_STALL for v in trial_verdicts
            ):
                # Resolve through the sink's own path logic: with
                # TORCHSNAPSHOT_TPU_TRACE_DIR set, the export went there,
                # not next to the snapshot.
                from torchsnapshot_tpu.telemetry.trace import (
                    longest_spans,
                    trace_path_for,
                )

                trace_file = trace_path_for(path, "take", 0)
                info = {"trace_file": trace_file}
                try:
                    info["top_spans"] = longest_spans(trace_file, 3)
                except Exception as e:  # noqa: BLE001 - diagnosis is
                    # advisory; the stall flag itself must survive
                    info["top_spans_error"] = repr(e)
                stall_trace_info[i] = info
            # Partial records carry the raw series as it lands — a kill
            # mid-loop still leaves every completed trial in the record.
            RESULT["take_times_s"] = [round(t, 2) for t in take_times]
            RESULT["d2h_matched_probes"] = [
                round(c, 3) for c in matched_probes
            ]
            _emit_partial(f"take{i}")
            if i < trials - 1:
                shutil.rmtree(path, ignore_errors=True)
                trial_state = None
                trial_state = make_state(total_bytes, seed=i + 1)
        state = trial_state  # last snap's source; later phases reuse it
        last_snap = os.path.join(workdir, f"snap{len(take_times) - 1}")
        save_med_s = statistics.median(take_times)
        save_gbps, save_range = _median_range([gib / t for t in take_times])

        # Per-trial ratio: take i divided by the better of its bracketing
        # probes. A ratio > 1 means the link outran both probes during
        # the take — the pipeline is not the limit there. The stall and
        # stability thresholds are the checkpoint doctor's
        # (diagnose_take_trial): a stable bracket with ratio below the
        # doctor's stall ratio is flagged in_take_stall — the slowdown
        # happened INSIDE the take (writeback storm, tunnel hiccup, GC),
        # and the phase timestamps say where the wall went. JSON keys
        # are unchanged for BENCH_r* comparability; each diagnostic
        # additionally embeds the doctor's verdict ids.
        denom = statistics.median(matched_probes)
        # warmup=1: the first take pays one-time costs (event loop,
        # thread pools, XLA transfer program, staging-pool creation)
        # that say nothing about steady-state pipeline efficiency; its
        # raw ratio stays in efficiency_ratios.
        brackets, ratios, efficiency, link_unstable = _bracketed_efficiency(
            take_times, matched_probes, gib, warmup=1
        )
        diagnostics = []
        for i, t in enumerate(take_times):
            a, b = matched_probes[i], matched_probes[i + 1]
            phases = take_phases[i] or {}
            trial_verdicts = ts_doctor.diagnose_take_trial(
                t, gib, a, b, phases=phases
            )
            verdict_ids = [v.rule for v in trial_verdicts]
            diag = {
                "take_s": round(t, 2),
                "bracket_gbps": [round(a, 3), round(b, 3)],
                "ratio": round(ratios[i], 3) if i < len(ratios) else None,
                "in_take_stall": ts_names.RULE_IN_TAKE_STALL in verdict_ids,
                "verdicts": verdict_ids,
                "staging_done_s": phases.get("staging"),
                "writing_done_s": phases.get("writing"),
            }
            # Flight-recorder evidence captured at trial time: the trace
            # file path and its top-3 longest spans make a stalled
            # BENCH_r*.json self-explaining.
            diag.update(stall_trace_info.get(i, {}))
            diagnostics.append(diag)
        _log(
            f"bench: matched-probe series "
            f"{[round(c, 3) for c in matched_probes]} GB/s "
            f"(median {denom:.3f}), per-trial bracketed efficiency ratios "
            f"{[round(r, 2) for r in ratios]}, link_unstable={link_unstable}"
        )
        _log(
            f"bench: wrote {gib:.2f} GiB, median {save_med_s:.2f} s "
            f"({save_gbps:.2f} GB/s, {efficiency:.2f}x of attainable D2H)"
        )
        RESULT.update(
            {
                "value": save_gbps,
                "vs_baseline": round(save_gbps / REFERENCE_SINGLE_ACCEL_GBPS, 3),
                "save_gbps_range": save_range,
                "pipeline_efficiency": round(efficiency, 3),
                "d2h_ceiling_gbps": round(denom, 3),
                "size_gib": round(gib, 2),
                "take_times_s": [round(t, 2) for t in take_times],
                "d2h_matched_probes": [round(c, 3) for c in matched_probes],
                "efficiency_ratios": [round(r, 3) for r in ratios],
                "efficiency_warmup_takes": 1 if len(ratios) > 1 else 0,
                "link_unstable": link_unstable,
                "take_diagnostics": diagnostics,
            }
        )
        _emit_partial("save")

        # ---- Leg 4: timed restores, bracketed by matched H2D probes ----
        # Same epistemics as save: achieved GB/s over the better of two
        # temporally-adjacent pattern-matched H2D probes. Destinations
        # are device-allocated (jnp.zeros — no wasteful host->device
        # push of zeros just to build a dest). os.sync() first: the
        # takes left ~size_gib of dirty pages, and background writeback
        # on this one-core box otherwise bleeds into the restore timings
        # (measured 10x inflation). Reference analog of the isolated
        # read path: benchmarks/load_tensor/main.py:24-61.
        est_restore_s = gib / max(link_est, 1e-3) * 1.2 + 5
        restore_trials = 2 if tunneled else 3
        h2d_est = link_est

        def h2d_probe(tag: str) -> None:
            nonlocal h2d_est
            chunk = _scaled_chunk_mib(h2d_est, probe_streams)
            r = probe_h2d(probe_streams, chunk_mib=chunk)
            h2d_probes.append(r)
            h2d_est = r
            _log(
                f"bench: matched H2D probe {tag} "
                f"({probe_streams}x{chunk} MiB): {r:.3f} GB/s"
            )

        try:
            snap = ts.Snapshot(last_snap)
            os.sync()
            h2d_probe("before restore 0")
            for i in range(restore_trials):
                if not _have_budget(
                    f"restore{i}", est_restore_s + PROBE_TARGET_S
                ):
                    break
                dest = ts.PyTreeState(
                    {
                        k: jnp.zeros(shape, dtype)
                        for k, (shape, dtype) in dest_template.items()
                    }
                )
                jax.block_until_ready(dest.tree)
                os.sync()
                t0 = time.perf_counter()
                snap.restore({"state": dest})
                jax.block_until_ready(dest.tree)
                restore_times.append(time.perf_counter() - t0)
                _log(f"bench: restore {i}: {restore_times[-1]:.2f} s")
                del dest
                h2d_probe(f"after restore {i}")
                RESULT["restore_times_s"] = [
                    round(t, 2) for t in restore_times
                ]
                RESULT["h2d_matched_probes"] = [
                    round(r, 3) for r in h2d_probes
                ]
                _emit_partial(f"restore{i}")
        except Exception as e:  # noqa: BLE001
            _log(f"bench: restore measurement failed: {e!r}")

        if restore_times:
            med, rng = _median_range([gib / t for t in restore_times])
            RESULT["restore_gbps"] = med
            RESULT["restore_gbps_range"] = rng
            RESULT["restore_times_s"] = [round(t, 2) for t in restore_times]
            # Read amplification of the last timed restore (reshard-on-
            # read ranged reads should keep fetched ~= needed; the
            # doctor's restore-read-amplified rule fires past 1.5x).
            try:
                from torchsnapshot_tpu import telemetry as _telemetry

                rep = _telemetry.last_report("restore", path=last_snap)
                if rep is not None and rep.bytes_needed:
                    RESULT["restore_bytes_needed"] = rep.bytes_needed
                    RESULT["restore_bytes_fetched"] = rep.bytes_fetched
                    RESULT["restore_read_amplification"] = round(
                        (rep.bytes_fetched or 0) / rep.bytes_needed, 3
                    )
            except Exception as e:  # noqa: BLE001 - context metric only
                _log(f"bench: restore amplification read failed: {e!r}")
            if len(h2d_probes) > len(restore_times):
                _, _, r_eff, r_unstable = _bracketed_efficiency(
                    restore_times, h2d_probes, gib
                )
                RESULT["restore_efficiency"] = round(r_eff, 3)
                RESULT["h2d_matched_probes"] = [
                    round(r, 3) for r in h2d_probes
                ]
                RESULT["restore_link_unstable"] = r_unstable
                _log(
                    f"bench: restore efficiency "
                    f"{RESULT['restore_efficiency']}x of attainable H2D "
                    f"(probes {[round(r, 3) for r in h2d_probes]}, "
                    f"link_unstable={RESULT['restore_link_unstable']})"
                )
            _emit_partial("restore")

        # ---- Leg 4b: COLD restore — fresh process, no prior D2H ----
        # The restore-after-restart scenario (BASELINE "restore-to-step0";
        # the reference's load benchmark is likewise a standalone
        # process). On this tunnel it also sidesteps a measured
        # environment artifact: a process's FIRST device→host copy
        # collapses its H2D bandwidth ~40x for the rest of its lifetime
        # (1.3 → 0.03 GB/s, irreversible), so the in-process restores
        # above — timed after the takes — measure that artifact, not the
        # restore path. Both numbers ship: cold is the hardware-limit
        # figure, in-process the tunnel's worst-case rollback.
        if _have_budget("cold_restore", gib / 0.2 + 60):
            row = _subprocess_json(
                "cold-restore",
                ("benchmarks", "cold_restore.py"),
                ["--snap", last_snap, "--trials", "2", "--json"],
                timeout=300,
                env=dict(os.environ),
            )
            if row is not None:
                for k, v in row.items():
                    if k.startswith("cold_restore"):
                        RESULT[k] = v
                _log(
                    f"bench: cold restore {row.get('cold_restore_gbps')} GB/s "
                    f"({row.get('cold_restore_efficiency')}x of attainable "
                    f"H2D, backend {row.get('cold_restore_backend')}) vs "
                    f"in-process {RESULT.get('restore_gbps', 'n/a')} GB/s"
                )
            _emit_partial("cold_restore")

        # ---- Leg 5: incremental unchanged-state save (context) ----
        # Needs a digest-recorded base (untimed) + a warm-up for the
        # one-time digest-program compile. Fail-soft, budget-gated.
        if _have_budget("incremental", est_take_s + 25):
            try:
                base = os.path.join(workdir, "snap_base")
                ts.Snapshot.take(
                    base, {"state": ts.PyTreeState(state)}, record_digests=True
                )
                ts.Snapshot.take(
                    os.path.join(workdir, "snap_incr_warm"),
                    {"state": ts.PyTreeState(state)},
                    incremental_base=base,
                )
                t0 = time.perf_counter()
                ts.Snapshot.take(
                    os.path.join(workdir, "snap_incr"),
                    {"state": ts.PyTreeState(state)},
                    incremental_base=base,
                )
                incr_elapsed = time.perf_counter() - t0
                _log(
                    f"bench: incremental save (unchanged state) "
                    f"{incr_elapsed:.2f} s vs full {save_med_s:.2f} s "
                    f"({save_med_s / incr_elapsed:.0f}x)"
                )
                RESULT["incremental_unchanged_save_s"] = round(incr_elapsed, 3)
                RESULT["incremental_speedup"] = round(
                    save_med_s / incr_elapsed, 1
                )
            except Exception as e:  # noqa: BLE001
                _log(f"bench: incremental context measurement failed: {e!r}")
            _emit_partial("incremental")

        # ---- Leg 5b: zero-pack write-path microbench (context) ----
        write_path_leg(workdir)

        # Release the last trial state before the async-stall state
        # materializes: 1x HBM peak throughout.
        state = None

        # ---- Leg 6: on-TPU async-take phase split (context) ----
        # Fresh state again — a cached host copy would fake a near-zero
        # stall on links where staging IS the D2H. (cpu_mesh_stall_ms,
        # recorded earlier, is the non-degenerate overlap story.)
        # Three timestamps, one per phase of the device-snapshot async
        # path (docs/async.md): async_visible_s = return-to-caller (the
        # training-blocked span — the headline the deferral attacks),
        # async_staged_s = background D2H + serialize done
        # (wait(phase="staged") — what async_stall_ms measured in
        # rounds <= 5, when return == staging-done), async_total_s =
        # committed. async_stall_ms keeps measuring the staging-done
        # offset for cross-round comparability; the *stall* story is
        # async_visible_s.
        if _have_budget("async_stall", est_take_s * 1.3):
            try:
                async_state = make_state(total_bytes, seed=11)
                t0 = time.perf_counter()
                pending = ts.Snapshot.async_take(
                    os.path.join(workdir, "snap_async"),
                    {"state": ts.PyTreeState(async_state)},
                )
                visible_s = time.perf_counter() - t0
                pending.wait(phase="staged")
                staged_s = time.perf_counter() - t0
                pending.wait()
                async_total_s = time.perf_counter() - t0
                _log(
                    f"bench: async take visible {visible_s:.3f} s, "
                    f"staged {staged_s:.2f} s, committed "
                    f"{async_total_s:.2f} s"
                )
                RESULT["async_visible_s"] = round(visible_s, 3)
                RESULT["async_stall_ms"] = round(staged_s * 1000, 1)
                RESULT["async_total_s"] = round(async_total_s, 2)
                RESULT["async_phase_split"] = {
                    "visible_s": round(visible_s, 3),
                    "staged_s": round(staged_s, 3),
                    "committed_s": round(async_total_s, 3),
                }
                del async_state
            except Exception as e:  # noqa: BLE001
                _log(f"bench: async stall measurement failed: {e!r}")
            _emit_partial("async_stall")

        # ---- Leg 7: steady-state multi-take autotune convergence ----
        steady_state_leg(
            workdir, total_bytes, gib, probe_streams, link_est, est_take_s
        )

        # ---- Leg 8: preemption recovery cost (ledger-accounted) ----
        preemption_leg(workdir, total_bytes, est_take_s)
        RESULT["goodput"] = {
            "steady_state": (RESULT.get("steady_state") or {}).get(
                "goodput", {}
            ),
            "preemption": (RESULT.get("preemption") or {}).get(
                "goodput", {}
            ),
        }

    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    # Re-probe the generic ceiling after the timed work (context field;
    # the efficiency denominator is the matched interleaved probes).
    ceiling_after = probe_d2h(4, chunk_mib=_scaled_chunk_mib(link_est, 4))
    RESULT["d2h_ceiling_before_after"] = [
        round(ceiling_before, 3),
        round(ceiling_after, 3),
    ]
    _emit_final(True)


if __name__ == "__main__":
    if "--sync-docs" in sys.argv[1:]:
        sys.exit(sync_docs())
    if "--json-out" in sys.argv[1:]:
        idx = sys.argv.index("--json-out")
        if idx + 1 >= len(sys.argv):
            _log("bench: --json-out requires a path argument")
            sys.exit(2)
        _JSON_OUT = sys.argv[idx + 1]
    main()
