"""Headline benchmark: checkpoint save throughput (GB/s) from TPU HBM to
local FS, the analog of the reference's DDP benchmark
(benchmarks/ddp/README.md: 20 GB model, 1 node x 1 GPU -> ~13.91 s,
~1.4 GB/s on local FS — BASELINE.md).

Prints ONE JSON line:
    {"metric": "checkpoint_save_throughput", "value": N, "unit": "GB/s",
     "vs_baseline": N}

vs_baseline is the ratio against the reference's single-accelerator
local-FS number (1.4 GB/s). Size configurable via TS_BENCH_GB (default 1).
"""

import json
import os
import shutil
import sys
import tempfile
import time

import jax
import jax.numpy as jnp

import torchsnapshot_tpu as ts

REFERENCE_SINGLE_ACCEL_GBPS = 20.0 / 13.91  # benchmarks/ddp/README.md:17


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_state(total_bytes: int) -> dict:
    """A pytree of bf16 arrays totaling ~total_bytes on device, shaped like
    transformer params (a few large 2-d weights + long 1-d tails)."""
    key = jax.random.PRNGKey(0)
    arrays = {}
    # 256 MiB bf16 blocks: (16384, 8192) * 2 bytes
    block_bytes = 16384 * 8192 * 2
    n_blocks = max(1, total_bytes // block_bytes)
    for i in range(n_blocks):
        key, sub = jax.random.split(key)
        arrays[f"w{i}"] = jax.random.normal(
            sub, (16384, 8192), dtype=jnp.bfloat16
        )
    arrays["bias"] = jnp.ones((65536,), dtype=jnp.float32)
    jax.block_until_ready(arrays)
    return arrays


def main() -> None:
    gb = float(os.environ.get("TS_BENCH_GB", "1"))
    total_bytes = int(gb * (1 << 30))
    _log(f"bench: materializing ~{gb:.1f} GiB of bf16 state on {jax.devices()[0]}")
    state = make_state(total_bytes)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))

    # Context line: raw single-stream D2H bandwidth. On tunneled devices
    # (axon dev setup) this caps checkpoint throughput far below what the
    # pipeline achieves on locally-attached TPU hosts.
    probe = jax.random.normal(jax.random.PRNGKey(1), (4096, 4096), jnp.bfloat16)
    jax.block_until_ready(probe)
    t0 = time.perf_counter()
    import numpy as np

    np.asarray(probe)
    d2h = probe.nbytes / (1 << 30) / (time.perf_counter() - t0)
    _log(f"bench: raw single-stream D2H = {d2h:.3f} GB/s")

    workdir = tempfile.mkdtemp(prefix="ts_bench_", dir="/tmp")
    try:
        # Warm-up on a small state: first-take costs (event loop, thread
        # pools, XLA transfer program) should not pollute the measurement.
        warm = {"x": jnp.ones((1024, 1024), jnp.bfloat16)}
        ts.Snapshot.take(os.path.join(workdir, "warm"), {"s": ts.PyTreeState(warm)})

        path = os.path.join(workdir, "snap")
        start = time.perf_counter()
        ts.Snapshot.take(path, {"state": ts.PyTreeState(state)})
        elapsed = time.perf_counter() - start
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    gbps = nbytes / (1 << 30) / elapsed
    _log(
        f"bench: wrote {nbytes / (1 << 30):.2f} GiB in {elapsed:.2f} s "
        f"({gbps:.2f} GB/s)"
    )
    print(
        json.dumps(
            {
                "metric": "checkpoint_save_throughput",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / REFERENCE_SINGLE_ACCEL_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
