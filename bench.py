"""Headline benchmark: checkpoint save throughput (GB/s) from TPU HBM to
local FS, the analog of the reference's DDP benchmark
(benchmarks/ddp/README.md: 20 GB model, 1 node x 1 GPU -> ~13.91 s,
~1.4 GB/s on local FS — BASELINE.md).

Prints ONE JSON line:
    {"metric": "checkpoint_save_throughput", "value": N, "unit": "GB/s",
     "vs_baseline": N, "pipeline_efficiency": N,
     "d2h_ceiling_gbps": N, "d2h_single_gbps": N, "size_gib": N}

vs_baseline is the ratio against the reference's single-accelerator
local-FS number (1.4 GB/s). ``pipeline_efficiency`` is the achieved save
throughput divided by the *attainable* device→host bandwidth on this
machine (the concurrent-stream D2H ceiling measured in-process), so the
number is meaningful even when the device link itself is slow (tunneled
dev TPUs): 1.0 means the checkpoint pipeline is perfectly hidden behind
the D2H copy it cannot avoid. Size configurable via TS_BENCH_GB
(default 4).
"""

import json
import os
import shutil
import sys
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

import torchsnapshot_tpu as ts

REFERENCE_SINGLE_ACCEL_GBPS = 20.0 / 13.91  # benchmarks/ddp/README.md:17


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_state(total_bytes: int) -> dict:
    """A pytree of bf16 arrays totaling ~total_bytes on device, shaped like
    transformer params (a few large 2-d weights + long 1-d tails)."""
    key = jax.random.PRNGKey(0)
    arrays = {}
    # 256 MiB bf16 blocks: (16384, 8192) * 2 bytes
    block_bytes = 16384 * 8192 * 2
    n_blocks = max(1, total_bytes // block_bytes)
    for i in range(n_blocks):
        key, sub = jax.random.split(key)
        arrays[f"w{i}"] = jax.random.normal(
            sub, (16384, 8192), dtype=jnp.bfloat16
        )
    arrays["bias"] = jnp.ones((65536,), dtype=jnp.float32)
    jax.block_until_ready(arrays)
    return arrays


def probe_d2h(n_streams: int, chunk_mib: int = 32) -> float:
    """Measured D2H GB/s with ``n_streams`` concurrent async copies.

    ``copy_to_host_async`` on every array first, then materialize: the
    transfers overlap inside the runtime, so this measures the *attainable*
    device→host bandwidth — the checkpoint pipeline's physical ceiling —
    rather than the single-stream latency-bound rate.
    """
    side = int((chunk_mib * (1 << 20) // 2) ** 0.5)  # bf16 square
    keys = jax.random.split(jax.random.PRNGKey(1), n_streams)
    arrs = [jax.random.normal(k, (side, side), jnp.bfloat16) for k in keys]
    jax.block_until_ready(arrs)
    total = sum(a.nbytes for a in arrs)
    t0 = time.perf_counter()
    for a in arrs:
        a.copy_to_host_async()
    hosts = [np.asarray(a) for a in arrs]
    elapsed = time.perf_counter() - t0
    del hosts
    return total / (1 << 30) / elapsed


def main() -> None:
    # Attainable D2H bandwidth: single stream (latency-bound context line)
    # and the best concurrent-stream rate (the pipeline's physical ceiling).
    d2h_single = probe_d2h(1)
    ceiling = d2h_single
    if d2h_single > 0.5:
        # Locally-attached device: cheap 32 MiB probes are accurate.
        plan = [(2, 32), (4, 32), (8, 32)]
    else:
        # Tunneled dev device (~MB/s): per-transfer overhead dominates
        # small probes, so match the pipeline's actual transfer size
        # (256 MiB leaves) or the ceiling comes out *below* what the
        # pipeline demonstrably achieves.
        plan = [(1, 256), (4, 64)]
    for n, mib in plan:
        r = probe_d2h(n, chunk_mib=mib)
        _log(f"bench: D2H x{n} streams of {mib} MiB = {r:.3f} GB/s")
        ceiling = max(ceiling, r)
    _log(
        f"bench: raw D2H single-stream = {d2h_single:.3f} GB/s, "
        f"concurrent ceiling = {ceiling:.3f} GB/s"
    )

    gb_env = os.environ.get("TS_BENCH_GB")
    gb = float(gb_env) if gb_env is not None else 4.0
    if gb_env is None and ceiling < 0.1:
        # Tunnel-limited link: the save is pure D2H wall time, so extra
        # gigabytes add minutes without changing any reported ratio.
        gb = 1.0
        _log("bench: tunneled D2H detected; defaulting to 1 GiB state")
    total_bytes = int(gb * (1 << 30))
    _log(f"bench: materializing ~{gb:.1f} GiB of bf16 state on {jax.devices()[0]}")
    state = make_state(total_bytes)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))

    incr_elapsed = None
    workdir = tempfile.mkdtemp(prefix="ts_bench_", dir="/tmp")
    try:
        # Warm-up on a small state: first-take costs (event loop, thread
        # pools, XLA transfer program) should not pollute the measurement.
        warm = {"x": jnp.ones((1024, 1024), jnp.bfloat16)}
        ts.Snapshot.take(os.path.join(workdir, "warm"), {"s": ts.PyTreeState(warm)})

        # Headline: a PLAIN take — comparable to the reference baseline
        # and earlier rounds (no digest recording in the timed path).
        path = os.path.join(workdir, "snap")
        start = time.perf_counter()
        ts.Snapshot.take(path, {"state": ts.PyTreeState(state)})
        elapsed = time.perf_counter() - start

        # Context lines: incremental save of the SAME state (all chunks
        # unchanged -> manifest refs only, no D2H, no data writes) — the
        # best case of incremental checkpointing. Needs a digest-recorded
        # base (untimed) + a warm-up for the one-time digest-program
        # compile. Fail-soft: a failure here must never break the
        # headline metric.
        try:
            base = os.path.join(workdir, "snap_base")
            ts.Snapshot.take(
                base, {"state": ts.PyTreeState(state)}, record_digests=True
            )
            ts.Snapshot.take(
                os.path.join(workdir, "snap_incr_warm"),
                {"state": ts.PyTreeState(state)},
                incremental_base=base,
            )
            start = time.perf_counter()
            ts.Snapshot.take(
                os.path.join(workdir, "snap_incr"),
                {"state": ts.PyTreeState(state)},
                incremental_base=base,
            )
            incr_elapsed = time.perf_counter() - start
            _log(
                f"bench: incremental save (unchanged state) {incr_elapsed:.2f} s "
                f"vs full {elapsed:.2f} s ({elapsed / incr_elapsed:.0f}x)"
            )
        except Exception as e:  # noqa: BLE001
            _log(f"bench: incremental context measurement failed: {e!r}")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    gbps = nbytes / (1 << 30) / elapsed
    efficiency = gbps / ceiling if ceiling > 0 else 0.0
    _log(
        f"bench: wrote {nbytes / (1 << 30):.2f} GiB in {elapsed:.2f} s "
        f"({gbps:.2f} GB/s, {efficiency:.2f}x of D2H ceiling)"
    )
    result = {
        "metric": "checkpoint_save_throughput",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / REFERENCE_SINGLE_ACCEL_GBPS, 3),
        "pipeline_efficiency": round(efficiency, 3),
        "d2h_ceiling_gbps": round(ceiling, 3),
        "d2h_single_gbps": round(d2h_single, 3),
        "size_gib": round(nbytes / (1 << 30), 2),
    }
    if incr_elapsed is not None:
        result["incremental_unchanged_save_s"] = round(incr_elapsed, 3)
        result["incremental_speedup"] = round(elapsed / incr_elapsed, 1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
