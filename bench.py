"""Headline benchmark: checkpoint save throughput (GB/s) from TPU HBM to
local FS, the analog of the reference's DDP benchmark
(benchmarks/ddp/README.md: 20 GB model, 1 node x 1 GPU -> ~13.91 s,
~1.4 GB/s on local FS — BASELINE.md).

Prints ONE JSON line with the north stars (BASELINE.md):

- save GB/s: median of 5 timed takes with [min, max] range (the dev
  tunnel's D2H fluctuates 2-4x between runs; a single trial can't
  support a committed ratio), and pipeline_efficiency = median of the
  per-trial take/probe ratios, where each take is BRACKETED by
  temporally-adjacent PATTERN-MATCHED attainable-D2H probes (same
  stream count and transfer size, one before and one after) and
  divided by the better of the two — each probe is a lower bound of
  attainable, so the bracket's max is the tightest attainable estimate
  for that trial's time window. ``link_unstable`` is set when adjacent
  probes disagree by >1.5x (the link drifted faster than the bracket
  can cancel); the raw probe/take series ship in the record either way.
- restore GB/s: median of 3 timed restores into device-committed
  destinations (storage reads + H2D placement), checksums on.
- async-take stall: wall time until async_take returns (staging done,
  training would resume) vs time to durable commit — on this tunneled
  chip plus, fail-soft, ``cpu_mesh_stall_ms``: the same split for the
  sharded-transformer workload on an 8-device CPU mesh, where staging
  is NOT the D2H and the stall is the real overlap story.
- orbax head-to-head (fail-soft): interleaved A/B on the CPU mesh,
  ``orbax_save_ratio`` / ``orbax_restore_ratio`` = orbax median / ours
  (>1 = we are faster), our checksums ON.

Context fields: incremental unchanged-state save, and the CPU-backend
protocol-overhead scaling rows (per-rank bytes written must halve at 2
ranks; protocol wall stays ~flat — benchmarks/replicated_save/
protocol_overhead.py), both fail-soft.

After measuring, the result is also written into BENCH.md's
BENCH_SIGNAL_OF_RECORD block (single source of truth — the committed
doc cannot drift from the newest record; ``tools/check_bench_docs.py``
verifies). ``python bench.py --sync-docs`` rewrites the block from the
newest ``BENCH_r*.json`` without running any benchmark.

Size configurable via TS_BENCH_GB (default 4; 1 on tunneled links).
TS_BENCH_TRIALS overrides the take-trial count.
TS_BENCH_SKIP_PROTOCOL=1 skips all subprocess legs.
"""

import json
import os
import re
import shutil
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

import torchsnapshot_tpu as ts

REFERENCE_SINGLE_ACCEL_GBPS = 20.0 / 13.91  # benchmarks/ddp/README.md:17


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_state(total_bytes: int, seed: int = 0) -> dict:
    """A pytree of bf16 arrays totaling ~total_bytes on device, shaped like
    transformer params (a few large 2-d weights + long 1-d tails).

    Each timed take gets a FRESH state (distinct seed): jax caches an
    array's host copy after its first D2H, so re-taking the same arrays
    measures a memcpy, not the device link."""
    key = jax.random.PRNGKey(seed)
    arrays = {}
    # 256 MiB bf16 blocks: (16384, 8192) * 2 bytes
    block_bytes = 16384 * 8192 * 2
    n_blocks = max(1, total_bytes // block_bytes)
    for i in range(n_blocks):
        key, sub = jax.random.split(key)
        arrays[f"w{i}"] = jax.random.normal(
            sub, (16384, 8192), dtype=jnp.bfloat16
        )
    arrays["bias"] = jnp.ones((65536,), dtype=jnp.float32)
    jax.block_until_ready(arrays)
    return arrays


def probe_d2h(n_streams: int, chunk_mib: int = 32) -> float:
    """Measured D2H GB/s with ``n_streams`` concurrent async copies.

    ``copy_to_host_async`` on every array first, then materialize: the
    transfers overlap inside the runtime, so this measures the *attainable*
    device→host bandwidth — the checkpoint pipeline's physical ceiling —
    rather than the single-stream latency-bound rate.
    """
    side = int((chunk_mib * (1 << 20) // 2) ** 0.5)  # bf16 square
    keys = jax.random.split(jax.random.PRNGKey(1), n_streams)
    arrs = [jax.random.normal(k, (side, side), jnp.bfloat16) for k in keys]
    jax.block_until_ready(arrs)
    total = sum(a.nbytes for a in arrs)
    t0 = time.perf_counter()
    for a in arrs:
        a.copy_to_host_async()
    hosts = [np.asarray(a) for a in arrs]
    elapsed = time.perf_counter() - t0
    del hosts
    return total / (1 << 30) / elapsed


def probe_ceiling(tunneled: bool) -> float:
    """Best concurrent-stream D2H rate over the probe plan."""
    if tunneled:
        # Per-transfer overhead dominates small probes on ~MB/s links;
        # match the pipeline's actual transfer size.
        plan = [(1, 256), (4, 64)]
    else:
        plan = [(2, 32), (4, 32), (8, 32)]
    best = 0.0
    for n, mib in plan:
        r = probe_d2h(n, chunk_mib=mib)
        _log(f"bench: D2H x{n} streams of {mib} MiB = {r:.3f} GB/s")
        best = max(best, r)
    return best


def _median_range(samples):
    return round(statistics.median(samples), 3), [
        round(min(samples), 3),
        round(max(samples), 3),
    ]


def _cpu_mesh_env() -> dict:
    """Env for a CPU-backend subprocess leg: 8 virtual devices so the
    leg exercises real GSPMD shardings regardless of this host's chip."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TS_BENCH_GB", None)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
        env["XLA_FLAGS"] = flags
    return env


def _subprocess_json(label: str, script_parts, args, timeout: float):
    """Run a benchmark script on the CPU backend; parse its final stdout
    line as JSON. Fail-soft: every leg is a context metric — a broken leg
    logs and returns None instead of killing the headline record."""
    if os.environ.get("TS_BENCH_SKIP_PROTOCOL") == "1":
        return None
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), *script_parts
    )
    try:
        proc = subprocess.run(
            [sys.executable, script, *args],
            env=_cpu_mesh_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip()[-500:])
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 - context metric only
        _log(f"bench: {label} leg failed: {e!r}")
        return None


def protocol_overhead_rows():
    """CPU-backend multi-process protocol scaling (fail-soft)."""
    return _subprocess_json(
        "protocol-overhead",
        ("benchmarks", "replicated_save", "protocol_overhead.py"),
        ["--gb", "0.125"],
        timeout=900,
    )


def cpu_mesh_stall_row():
    """North star: async-take stall on the sharded-transformer workload,
    8-device CPU mesh — the regime where staging is NOT the device link
    and the stall measures the pipeline's real overlap (fail-soft)."""
    return _subprocess_json(
        "cpu-mesh-stall",
        ("benchmarks", "sharded_transformer", "main.py"),
        ["--d-model", "512", "--layers", "8", "--async-take", "--json"],
        timeout=900,
    )


def orbax_row():
    """North star: head-to-head vs the TPU incumbent, interleaved A/B on
    the CPU mesh, our checksums ON (fail-soft)."""
    return _subprocess_json(
        "orbax-compare",
        ("benchmarks", "orbax_compare", "main.py"),
        ["--gb", "1", "--trials", "3", "--json"],
        timeout=1800,
    )


DOC_BLOCK_RE = re.compile(
    r"<!-- BENCH_SIGNAL_OF_RECORD.*?-->\s*```json\s*\{.*?\}\s*```",
    re.DOTALL,
)


def write_signal_of_record(record: dict) -> None:
    """Rewrite BENCH.md's signal-of-record block in place (single source
    of truth: the block is generated from the measured record, never
    hand-maintained; tools/check_bench_docs.py verifies it against the
    newest driver-captured BENCH_r*.json)."""
    bench_md = Path(__file__).resolve().parent / "BENCH.md"
    try:
        text = bench_md.read_text()
        block = (
            "<!-- BENCH_SIGNAL_OF_RECORD: generated by bench.py; verified "
            "against the newest BENCH_r*.json -->\n```json\n"
            + json.dumps(record, indent=2)
            + "\n```"
        )
        new_text, n = DOC_BLOCK_RE.subn(lambda _: block, text, count=1)
        if n != 1:
            raise RuntimeError("no BENCH_SIGNAL_OF_RECORD block found")
        if new_text != text:
            bench_md.write_text(new_text)
            _log("bench: BENCH.md signal-of-record block updated")
    except Exception as e:  # noqa: BLE001 - docs update must not kill output
        _log(f"bench: BENCH.md update failed: {e!r}")


def sync_docs() -> int:
    """--sync-docs: regenerate BENCH.md's block from the newest
    BENCH_r*.json (no benchmarking). The record is located by the
    *verifier's* own ``newest_record`` so the writer and the checker can
    never disagree about which record is the signal of record."""
    sys.path.insert(0, str(Path(__file__).resolve().parent / "tools"))
    from check_bench_docs import newest_record

    record, path = newest_record()
    if record is None:
        _log(
            "bench: no BENCH_r*.json with a non-null parsed record "
            "(none present, or every round timed out); nothing to sync"
        )
        return 1
    write_signal_of_record(record)
    _log(f"bench: synced BENCH.md from {path.name}")
    return 0


def main() -> None:
    d2h_single = probe_d2h(1)
    tunneled = d2h_single <= 0.5
    ceiling_before = max(d2h_single, probe_ceiling(tunneled))
    _log(
        f"bench: raw D2H single-stream = {d2h_single:.3f} GB/s, "
        f"concurrent ceiling = {ceiling_before:.3f} GB/s"
    )

    gb_env = os.environ.get("TS_BENCH_GB")
    gb = float(gb_env) if gb_env is not None else 4.0
    if gb_env is None and tunneled:
        # Tunnel-limited link: the save is pure D2H wall time, so extra
        # gigabytes add minutes without changing any reported ratio.
        gb = 1.0
        _log("bench: tunneled D2H detected; defaulting to 1 GiB state")
    total_bytes = int(gb * (1 << 30))
    _log(f"bench: materializing ~{gb:.1f} GiB of bf16 state on {jax.devices()[0]}")
    state = make_state(total_bytes, seed=0)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(state))
    gib = nbytes / (1 << 30)

    workdir = tempfile.mkdtemp(prefix="ts_bench_", dir="/tmp")
    incr_elapsed = None
    stall_s = async_total_s = None
    try:
        # Warm-up on a small state: first-take costs (event loop, thread
        # pools, XLA transfer program) should not pollute the measurement.
        warm = {"x": jnp.ones((1024, 1024), jnp.bfloat16)}
        ts.Snapshot.take(os.path.join(workdir, "warm"), {"s": ts.PyTreeState(warm)})

        # Headline: median of N PLAIN takes — comparable to the reference
        # baseline and earlier rounds (no digest recording in the timed
        # path). Every trial snapshots a FRESH state: jax caches host
        # copies per array, and re-taking cached arrays would time a
        # memcpy instead of the device link. On tunneled links every take
        # is BRACKETED by PATTERN-MATCHED ceiling probes (same stream
        # count and transfer size as the take's leaves): the link drifts
        # 2x+ minute-to-minute, so each trial's efficiency is achieved /
        # max(probe_before, probe_after) — probes are lower bounds of
        # attainable, and the bracket's max is the tightest estimate for
        # that trial's time window. The probe after take i doubles as the
        # probe before take i+1.
        trials = int(
            os.environ.get("TS_BENCH_TRIALS", "5" if tunneled else "3")
        )
        dest_template = {k: (v.shape, v.dtype) for k, v in state.items()}
        take_times = []
        matched_probes = []
        trial_state = state
        state = None  # one state on device at a time: 1x HBM, not 2x
        n_blocks = max(1, total_bytes // (16384 * 8192 * 2))
        probe_streams = min(4, n_blocks)

        def matched_probe(tag: str) -> None:
            mc = probe_d2h(probe_streams, chunk_mib=256)
            matched_probes.append(mc)
            _log(
                f"bench: matched ceiling probe {tag} "
                f"({probe_streams}x256 MiB): {mc:.3f} GB/s"
            )

        if tunneled:
            matched_probe("before take 0")
        for i in range(trials):
            path = os.path.join(workdir, f"snap{i}")
            t0 = time.perf_counter()
            ts.Snapshot.take(path, {"state": ts.PyTreeState(trial_state)})
            take_times.append(time.perf_counter() - t0)
            _log(f"bench: take {i}: {take_times[-1]:.2f} s")
            if tunneled:
                matched_probe(f"after take {i}")
            if i < trials - 1:
                shutil.rmtree(path, ignore_errors=True)
                trial_state = None
                trial_state = make_state(total_bytes, seed=i + 1)
        state = trial_state  # last snap's source; later phases reuse it
        last_snap = os.path.join(workdir, f"snap{trials - 1}")
        save_med_s = statistics.median(take_times)
        save_gbps, save_range = _median_range([gib / t for t in take_times])

        # Timed restores (median of 3): storage reads + streaming H2D
        # placement into device-committed destinations, checksums on.
        # os.sync() first — the takes above left ~size_gib of dirty pages,
        # and background writeback on this one-core box otherwise bleeds
        # into the restore timings (measured 10x inflation).
        restore_times = []
        try:
            dev = jax.devices()[0]
            snap = ts.Snapshot(last_snap)
            for i in range(3):
                dest = ts.PyTreeState(
                    {
                        k: jax.device_put(np.zeros(shape, dtype), dev)
                        for k, (shape, dtype) in dest_template.items()
                    }
                )
                jax.block_until_ready(dest.tree)
                os.sync()
                t0 = time.perf_counter()
                snap.restore({"state": dest})
                jax.block_until_ready(dest.tree)
                restore_times.append(time.perf_counter() - t0)
                _log(f"bench: restore {i}: {restore_times[-1]:.2f} s")
                del dest
        except Exception as e:  # noqa: BLE001
            _log(f"bench: restore measurement failed: {e!r}")

        # Incremental save of the SAME state (all chunks unchanged ->
        # manifest refs only, no D2H, no data writes). Needs a
        # digest-recorded base (untimed) + a warm-up for the one-time
        # digest-program compile. Fail-soft.
        try:
            base = os.path.join(workdir, "snap_base")
            ts.Snapshot.take(
                base, {"state": ts.PyTreeState(state)}, record_digests=True
            )
            ts.Snapshot.take(
                os.path.join(workdir, "snap_incr_warm"),
                {"state": ts.PyTreeState(state)},
                incremental_base=base,
            )
            t0 = time.perf_counter()
            ts.Snapshot.take(
                os.path.join(workdir, "snap_incr"),
                {"state": ts.PyTreeState(state)},
                incremental_base=base,
            )
            incr_elapsed = time.perf_counter() - t0
            _log(
                f"bench: incremental save (unchanged state) {incr_elapsed:.2f} s "
                f"vs full {save_med_s:.2f} s ({save_med_s / incr_elapsed:.0f}x)"
            )
        except Exception as e:  # noqa: BLE001
            _log(f"bench: incremental context measurement failed: {e!r}")
        # Release the last trial state before the async-stall state
        # materializes: 1x HBM peak throughout.
        state = None

        # Async-take stall split: time to staging-done (training resumes)
        # vs time to durable commit. Fresh state again — a cached host
        # copy would fake a near-zero stall on links where staging IS the
        # D2H.
        try:
            async_state = make_state(total_bytes, seed=11)
            t0 = time.perf_counter()
            pending = ts.Snapshot.async_take(
                os.path.join(workdir, "snap_async"),
                {"state": ts.PyTreeState(async_state)},
            )
            stall_s = time.perf_counter() - t0
            pending.wait()
            async_total_s = time.perf_counter() - t0
            _log(
                f"bench: async take stall {stall_s:.2f} s of "
                f"{async_total_s:.2f} s total"
            )
            del async_state
        except Exception as e:  # noqa: BLE001
            _log(f"bench: async stall measurement failed: {e!r}")

    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    # Re-probe the generic ceiling after the timed work (context field;
    # the efficiency denominator is the matched interleaved probes when
    # available).
    ceiling_after = max(probe_d2h(1), probe_ceiling(tunneled))
    ceiling = max(ceiling_before, ceiling_after)
    link_unstable = False
    if matched_probes:
        # Per-trial ratio: take i divided by the better of its bracketing
        # probes (probe i before, probe i+1 after). Probes are lower
        # bounds of attainable, so the bracket's max is the tightest
        # attainable estimate covering that trial's time window; pairing
        # in time cancels intra-run link drift (observed 2.6x within one
        # run). A ratio > 1 means the link outran both probes during the
        # take — the pipeline is not the limit there.
        denom = statistics.median(matched_probes)
        brackets = [
            max(matched_probes[i], matched_probes[i + 1])
            for i in range(len(take_times))
        ]
        ratios = [
            (gib / t) / b for t, b in zip(take_times, brackets) if b > 0
        ]
        efficiency = statistics.median(ratios) if ratios else 0.0
        link_unstable = any(
            max(a, b) / min(a, b) > 1.5
            for a, b in zip(matched_probes, matched_probes[1:])
            if min(a, b) > 0
        )
        _log(
            f"bench: matched-probe series "
            f"{[round(c, 3) for c in matched_probes]} GB/s "
            f"(median {denom:.3f}), per-trial bracketed efficiency ratios "
            f"{[round(r, 2) for r in ratios]}, link_unstable="
            f"{link_unstable} (generic probes: before "
            f"{ceiling_before:.3f} / after {ceiling_after:.3f})"
        )
    else:
        denom = ceiling
        ratios = []
        efficiency = save_gbps / denom if denom > 0 else 0.0
        _log(
            f"bench: ceiling before {ceiling_before:.3f} / after "
            f"{ceiling_after:.3f} GB/s -> using {ceiling:.3f}"
        )
    _log(
        f"bench: wrote {gib:.2f} GiB, median {save_med_s:.2f} s "
        f"({save_gbps:.2f} GB/s, {efficiency:.2f}x of attainable D2H)"
    )
    result = {
        "metric": "checkpoint_save_throughput",
        "value": save_gbps,
        "unit": "GB/s",
        "vs_baseline": round(save_gbps / REFERENCE_SINGLE_ACCEL_GBPS, 3),
        "save_gbps_range": save_range,
        "pipeline_efficiency": round(efficiency, 3),
        "d2h_ceiling_gbps": round(denom, 3),
        "d2h_ceiling_before_after": [
            round(ceiling_before, 3),
            round(ceiling_after, 3),
        ],
        "d2h_single_gbps": round(d2h_single, 3),
        "size_gib": round(gib, 2),
        "take_times_s": [round(t, 2) for t in take_times],
    }
    if matched_probes:
        result["d2h_matched_probes"] = [round(c, 3) for c in matched_probes]
        result["efficiency_ratios"] = [round(r, 3) for r in ratios]
        result["link_unstable"] = link_unstable
    if restore_times:
        med, rng = _median_range([gib / t for t in restore_times])
        result["restore_gbps"] = med
        result["restore_gbps_range"] = rng
    if stall_s is not None and async_total_s is not None:
        result["async_stall_ms"] = round(stall_s * 1000, 1)
        result["async_total_s"] = round(async_total_s, 2)
    if incr_elapsed is not None:
        result["incremental_unchanged_save_s"] = round(incr_elapsed, 3)
        result["incremental_speedup"] = round(save_med_s / incr_elapsed, 1)
    proto = protocol_overhead_rows()
    if proto is not None:
        result["protocol_overhead"] = proto
    mesh_row = cpu_mesh_stall_row()
    if mesh_row is not None and "stall_ms" in mesh_row:
        result["cpu_mesh_stall_ms"] = mesh_row["stall_ms"]
        result["cpu_mesh_save_total_s"] = mesh_row.get("save_total_s")
        result["cpu_mesh_state_gib"] = mesh_row.get("state_gib")
        _log(
            f"bench: cpu-mesh async stall {mesh_row['stall_ms']} ms of "
            f"{mesh_row.get('save_total_s')} s total "
            f"({mesh_row.get('state_gib')} GiB sharded train state)"
        )
    orbax = orbax_row()
    if orbax is not None:
        result["orbax_save_ratio"] = orbax.get("orbax_save_ratio")
        result["orbax_restore_ratio"] = orbax.get("orbax_restore_ratio")
        result["orbax"] = orbax
        _log(
            f"bench: orbax head-to-head (1 GiB, CPU mesh, checksums on): "
            f"save ratio {orbax.get('orbax_save_ratio')}x, restore ratio "
            f"{orbax.get('orbax_restore_ratio')}x (orbax/ours, >1 = ours "
            f"faster)"
        )
    # Regenerate BENCH.md's block only for a *default-config* run (what
    # the driver executes): a smoke run with TS_BENCH_* overrides must
    # not clobber the committed signal of record with numbers that will
    # never appear in a BENCH_r*.json (use --sync-docs to restore it).
    overrides = [
        k
        for k in ("TS_BENCH_GB", "TS_BENCH_TRIALS", "TS_BENCH_SKIP_PROTOCOL")
        if os.environ.get(k)
    ]
    if overrides:
        _log(
            f"bench: {'/'.join(overrides)} set — leaving BENCH.md's "
            f"signal-of-record block untouched (non-default run)"
        )
    else:
        write_signal_of_record(result)
    print(json.dumps(result))


if __name__ == "__main__":
    if "--sync-docs" in sys.argv[1:]:
        sys.exit(sync_docs())
    main()
