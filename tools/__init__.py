"""Repo lint/check tooling. ``tools.snaplint`` is the AST analysis
framework; the ``check_*.py`` scripts are standalone entry points (the
name/marker checkers are thin shims over snaplint rules)."""
