#!/usr/bin/env python
"""Default-lane bench differential: did the newest round regress?

Thin shim over ``telemetry/critpath.py``'s bench differential, aimed at
CI (the docs-consistency job) and pre-merge hygiene: the newest parsed
``BENCH_r*.json`` is judged against the rolling baseline of its
predecessors with the per-leg directions and tolerance floors declared
in ``critpath.BENCH_LEGS`` (sized to the measured round-to-round link
drift of this box, so pure drift stays quiet while a real slowdown
fires). Nonzero exit on regression. Stdlib + repo only; run from
anywhere:

    python tools/bench_diff.py
"""

import importlib
import sys
import types
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))
sys.path.insert(0, str(ROOT))

from check_bench_docs import scan_records  # noqa: E402


def _critpath():
    """telemetry/critpath.py, importable in the minimal CI environment:
    the top-level package's eager imports pull in jax (absent in the
    docs-consistency job), but the telemetry subpackage is stdlib-only —
    a stub parent package lets it load without executing
    ``torchsnapshot_tpu/__init__.py``."""
    try:
        from torchsnapshot_tpu.telemetry import critpath

        return critpath
    except ImportError:
        if "torchsnapshot_tpu" not in sys.modules:
            stub = types.ModuleType("torchsnapshot_tpu")
            stub.__path__ = [str(ROOT / "torchsnapshot_tpu")]
            sys.modules["torchsnapshot_tpu"] = stub
        return importlib.import_module(
            "torchsnapshot_tpu.telemetry.critpath"
        )


def main(root: Path = ROOT) -> int:
    critpath = _critpath()

    parsed = [
        (path.name, record)
        for _, path, record in sorted(scan_records(root))
        if record is not None
    ]
    if len(parsed) < 2:
        print(
            "bench_diff: fewer than two parsed BENCH_r*.json records; "
            "nothing to compare"
        )
        return 0
    newest_label = parsed[-1][0]
    previous_label = parsed[-2][0]
    print(
        f"bench_diff: {newest_label} vs baseline of "
        f"{len(parsed) - 1} earlier parsed records "
        f"(newest predecessor {previous_label})"
    )
    rows = critpath.bench_regressions(parsed)
    for verdict in critpath.bench_verdicts(rows):
        print(verdict.format())
    if rows:
        print(f"bench_diff: {len(rows)} regressed leg(s) in {newest_label}")
        return 2
    print(f"bench_diff: no leg of {newest_label} regressed beyond tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
