#!/usr/bin/env python
"""snapshot-stats: per-step tables from a checkpoint-telemetry event log.

Thin repo-tools wrapper over ``torchsnapshot_tpu.telemetry.stats`` (also
reachable as ``python -m torchsnapshot_tpu.telemetry``) so BENCH drivers
and operators shelling in from the repo root consume the same renderer::

    python tools/snapshot_stats.py /ckpts/.telemetry.jsonl
    python tools/snapshot_stats.py events.jsonl --kind take
    python tools/snapshot_stats.py events.jsonl --path-contains step_00
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from torchsnapshot_tpu.telemetry.stats import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
