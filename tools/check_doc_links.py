#!/usr/bin/env python
"""Docs link check: every relative markdown link in README.md and docs/
must point at an existing file (the docs-build lane's cheap core —
reference ships a sphinx docs build; these docs are plain markdown).
Stdlib-only.

    python tools/check_doc_links.py
"""

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
LINK_RE = re.compile(r"\[[^\]]*\]\(([^)\s]+)\)")


def check(md: Path) -> list:
    errors = []
    for target in LINK_RE.findall(md.read_text()):
        if target.startswith(("http://", "https://", "mailto:", "#")):
            continue
        path = target.split("#", 1)[0]
        if not path:
            continue
        resolved = (md.parent / path).resolve()
        if not resolved.exists():
            errors.append(f"{md.relative_to(ROOT)}: broken link -> {target}")
    return errors


def main() -> int:
    files = [ROOT / "README.md", *sorted((ROOT / "docs").rglob("*.md"))]
    files += [p for p in (ROOT / "benchmarks").rglob("*.md")]
    errors = []
    for md in files:
        if md.exists():
            errors.extend(check(md))
    for e in errors:
        print(e)
    if not errors:
        print(f"check_doc_links: {len(files)} files, all relative links resolve")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
