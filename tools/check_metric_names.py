#!/usr/bin/env python
"""Metric-name lint for the telemetry registry — thin shim.

The implementation moved into the snaplint framework
(``tools/snaplint/rules/names_lint.py``, rule ``metric-name-literal``);
this entry point survives so existing invocations and CI lanes keep
working:

    python tools/check_metric_names.py

Prefer the framework run, which applies every rule at once:

    python -m tools.snaplint torchsnapshot_tpu
"""

import sys
from pathlib import Path

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.snaplint.rules.names_lint import (  # noqa: E402
    check_metric_call_sites as check_call_sites,
    check_metric_names_file as check_names_file,
)

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "torchsnapshot_tpu"
NAMES_FILE = PACKAGE / "telemetry" / "names.py"


def check(package: Path = PACKAGE, names_file: Path = NAMES_FILE):
    return check_names_file(names_file) + check_call_sites(
        package, names_file
    )


def main() -> int:
    errors = check()
    for e in errors:
        print(e)
    if not errors:
        print(
            "check_metric_names: metric names are snake_case, registered "
            "exactly once in telemetry/names.py, and call sites use the "
            "constants (rule metric-name-literal via tools.snaplint)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
