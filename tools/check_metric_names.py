#!/usr/bin/env python
"""Metric-name lint for the telemetry registry.

The exposition namespace (dashboards, alerts, the Prometheus text file)
only stays stable if metric names are declared in exactly one place.
This check enforces, statically (AST, stdlib-only — same shape as
``check_tiered_markers.py``):

- ``torchsnapshot_tpu/telemetry/names.py`` declares every metric name as
  a module-level string constant: snake_case value, no constant assigned
  twice, no value declared twice (registered exactly once);
- no other file under ``torchsnapshot_tpu/`` passes a string literal as
  the metric name to ``counter_inc``/``gauge_set``/``histogram_observe``
  — call sites must reference the ``names.py`` constants, so renames are
  one-line and greppable.

    python tools/check_metric_names.py
"""

import ast
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "torchsnapshot_tpu"
NAMES_FILE = PACKAGE / "telemetry" / "names.py"

_SNAKE_CASE = re.compile(r"^[a-z][a-z0-9_]*$")
# Flight-recorder span/instant names (SPAN_/INSTANT_ constants) use a
# colon-case "layer:operation" convention; tools/check_span_names.py
# owns their call-site rules, but declaration hygiene (declared once,
# well-formed) is enforced here alongside the metrics.
_COLON_CASE = re.compile(r"^[a-z][a-z0-9_]*(:[a-z][a-z0-9_]*)+$")
_SPAN_PREFIXES = ("SPAN_", "INSTANT_")
_REGISTRY_METHODS = {"counter_inc", "gauge_set", "histogram_observe"}


def check_names_file(path: Path):
    """Errors in the declaration file: malformed values (snake_case for
    metrics, colon-case for SPAN_/INSTANT_ trace names), duplicate
    constants, duplicate values."""
    errors = []
    if not path.exists():
        return [f"{path.name}: missing (metric names must be declared here)"]
    tree = ast.parse(path.read_text())
    seen_targets = {}
    seen_values = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if not isinstance(target, ast.Name):
                continue
            if not isinstance(node.value, ast.Constant) or not isinstance(
                node.value.value, str
            ):
                errors.append(
                    f"{path.name}:{node.lineno}: {target.id} is not a "
                    f"string literal"
                )
                continue
            value = node.value.value
            if target.id.startswith(_SPAN_PREFIXES):
                if not _COLON_CASE.match(value):
                    errors.append(
                        f"{path.name}:{node.lineno}: {value!r} is not "
                        f"colon-case (span/instant names look like "
                        f"'layer:operation')"
                    )
            elif not _SNAKE_CASE.match(value):
                errors.append(
                    f"{path.name}:{node.lineno}: {value!r} is not "
                    f"snake_case"
                )
            if target.id in seen_targets:
                errors.append(
                    f"{path.name}:{node.lineno}: constant {target.id} "
                    f"assigned twice (first at line "
                    f"{seen_targets[target.id]})"
                )
            seen_targets[target.id] = node.lineno
            if value in seen_values:
                errors.append(
                    f"{path.name}:{node.lineno}: metric {value!r} "
                    f"registered twice (first at line {seen_values[value]})"
                )
            seen_values[value] = node.lineno
    if not seen_values and not errors:
        errors.append(f"{path.name}: no metric names declared")
    return errors


def check_call_sites(package: Path, names_file: Path):
    """Errors at registry call sites: string-literal metric names
    outside names.py."""
    errors = []
    for py in sorted(package.rglob("*.py")):
        if py == names_file:
            continue
        try:
            tree = ast.parse(py.read_text())
        except SyntaxError as e:
            errors.append(f"{py.relative_to(package.parent)}: {e}")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            method = func.attr if isinstance(func, ast.Attribute) else None
            if method not in _REGISTRY_METHODS or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                errors.append(
                    f"{py.relative_to(package.parent)}:{node.lineno}: "
                    f"literal metric name {first.value!r} in {method}() — "
                    f"use a telemetry/names.py constant"
                )
    return errors


def check(package: Path = PACKAGE, names_file: Path = NAMES_FILE):
    return check_names_file(names_file) + check_call_sites(
        package, names_file
    )


def main() -> int:
    errors = check()
    for e in errors:
        print(e)
    if not errors:
        print(
            "check_metric_names: metric names are snake_case, registered "
            "exactly once in telemetry/names.py, and call sites use the "
            "constants"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
