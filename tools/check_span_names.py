#!/usr/bin/env python
"""Span-name lint for the flight recorder.

The trace timeline (Perfetto queries, the merge CLI's straggler tables,
the watchdog's stall attribution) keys off span/instant names exactly
like dashboards key off metric names, so the same single-registration
rule applies. This check enforces, statically (AST, stdlib-only — same
shape as ``check_metric_names.py``, which owns the declaration-file
hygiene for the SPAN_/INSTANT_ constants):

- ``torchsnapshot_tpu/telemetry/names.py`` declares at least one
  ``SPAN_``/``INSTANT_`` constant, each a colon-case string
  (``layer:operation``), no constant or value declared twice;
- no file under ``torchsnapshot_tpu/`` passes a string literal as the
  name to ``trace_annotation(...)`` or to the recorder's
  ``span(...)``/``instant(...)``/``begin(...)`` — call sites must
  reference the ``names.py`` constants, so renames are one-line and
  timelines never fork spellings. ``telemetry/trace.py`` itself (which
  receives names as parameters) is exempt.

    python tools/check_span_names.py
"""

import ast
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "torchsnapshot_tpu"
NAMES_FILE = PACKAGE / "telemetry" / "names.py"
# The recorder implementation passes caller-supplied names through its
# own span()/instant() machinery; it declares nothing itself.
EXEMPT = {PACKAGE / "telemetry" / "trace.py"}

_COLON_CASE = re.compile(r"^[a-z][a-z0-9_]*(:[a-z][a-z0-9_]*)+$")
_SPAN_PREFIXES = ("SPAN_", "INSTANT_")
_TRACE_CALLABLES = {"trace_annotation", "span", "instant", "begin"}


def check_names_file(path: Path):
    """Errors in the declaration file: no span constants at all,
    non-colon-case values, duplicate constants/values."""
    if not path.exists():
        return [f"{path.name}: missing (span names must be declared here)"]
    errors = []
    seen_targets = {}
    seen_values = {}
    tree = ast.parse(path.read_text())
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if not isinstance(target, ast.Name) or not target.id.startswith(
                _SPAN_PREFIXES
            ):
                continue
            if not isinstance(node.value, ast.Constant) or not isinstance(
                node.value.value, str
            ):
                errors.append(
                    f"{path.name}:{node.lineno}: {target.id} is not a "
                    f"string literal"
                )
                continue
            value = node.value.value
            if not _COLON_CASE.match(value):
                errors.append(
                    f"{path.name}:{node.lineno}: {value!r} is not "
                    f"colon-case ('layer:operation')"
                )
            if target.id in seen_targets:
                errors.append(
                    f"{path.name}:{node.lineno}: constant {target.id} "
                    f"assigned twice (first at line "
                    f"{seen_targets[target.id]})"
                )
            seen_targets[target.id] = node.lineno
            if value in seen_values:
                errors.append(
                    f"{path.name}:{node.lineno}: span {value!r} "
                    f"registered twice (first at line {seen_values[value]})"
                )
            seen_values[value] = node.lineno
    if not seen_values and not errors:
        errors.append(f"{path.name}: no span/instant names declared")
    return errors


def _called_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def check_call_sites(package: Path, exempt=None):
    """Errors at trace call sites: string-literal span names passed to
    trace_annotation/span/instant/begin."""
    exempt = set(exempt or EXEMPT)
    errors = []
    for py in sorted(package.rglob("*.py")):
        if py in exempt:
            continue
        try:
            tree = ast.parse(py.read_text())
        except SyntaxError as e:
            errors.append(f"{py.relative_to(package.parent)}: {e}")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _called_name(node.func) not in _TRACE_CALLABLES:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                errors.append(
                    f"{py.relative_to(package.parent)}:{node.lineno}: "
                    f"literal span name {first.value!r} in "
                    f"{_called_name(node.func)}() — use a "
                    f"telemetry/names.py constant"
                )
    return errors


def check(package: Path = PACKAGE, names_file: Path = NAMES_FILE, exempt=None):
    return check_names_file(names_file) + check_call_sites(package, exempt)


def main() -> int:
    errors = check()
    for e in errors:
        print(e)
    if not errors:
        print(
            "check_span_names: span/instant names are colon-case, "
            "registered exactly once in telemetry/names.py, and call "
            "sites use the constants"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
