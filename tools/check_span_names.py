#!/usr/bin/env python
"""Span-name lint for the flight recorder — thin shim.

The implementation moved into the snaplint framework
(``tools/snaplint/rules/names_lint.py``, rule ``span-name-literal``);
this entry point survives so existing invocations and CI lanes keep
working:

    python tools/check_span_names.py

Prefer the framework run, which applies every rule at once:

    python -m tools.snaplint torchsnapshot_tpu
"""

import sys
from pathlib import Path

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.snaplint.rules.names_lint import (  # noqa: E402
    check_span_call_sites,
    check_span_names_file as check_names_file,
)

ROOT = Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "torchsnapshot_tpu"
NAMES_FILE = PACKAGE / "telemetry" / "names.py"
# The recorder implementation passes caller-supplied names through its
# own span()/instant() machinery; it declares nothing itself.
EXEMPT = {PACKAGE / "telemetry" / "trace.py"}


def check_call_sites(package: Path, exempt=None):
    return check_span_call_sites(
        package, exempt=EXEMPT if exempt is None else exempt
    )


def check(package: Path = PACKAGE, names_file: Path = NAMES_FILE, exempt=None):
    return check_names_file(names_file) + check_call_sites(package, exempt)


def main() -> int:
    errors = check()
    for e in errors:
        print(e)
    if not errors:
        print(
            "check_span_names: span/instant names are colon-case, "
            "registered exactly once in telemetry/names.py, and call "
            "sites use the constants (rule span-name-literal via "
            "tools.snaplint)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
