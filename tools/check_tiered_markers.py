#!/usr/bin/env python
"""Marker-lane check for the tiered-checkpointing tests — thin shim.

The implementation moved into the snaplint framework
(``tools/snaplint/rules/tiered_markers.py``, rule
``tiered-test-markers``); this entry point survives so existing
invocations and CI lanes keep working:

    python tools/check_tiered_markers.py

Prefer the framework run, which applies every rule at once:

    python -m tools.snaplint torchsnapshot_tpu
"""

import sys
from pathlib import Path

_REPO = str(Path(__file__).resolve().parent.parent)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.snaplint.rules.tiered_markers import (  # noqa: E402
    _has_slow_marker,  # noqa: F401  (kept for import compatibility)
    check,
)

ROOT = Path(__file__).resolve().parent.parent
TIERED_TESTS = ROOT / "tests" / "test_tiered.py"


def main() -> int:
    errors = check(TIERED_TESTS)
    for e in errors:
        print(e)
    if not errors:
        print(
            "check_tiered_markers: tiered tests are lane-correct "
            "(fast-lane tests present; end-to-end marked slow) "
            "(rule tiered-test-markers via tools.snaplint)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
