#!/usr/bin/env python
"""Marker-lane check for the tiered-checkpointing tests.

The tiered crash-consistency and latency properties are tier-1 signal:
they must be collected in the default ``-m 'not slow'`` lane, while the
end-to-end mirror sweep stays out of it. This check enforces both
statically (AST, stdlib-only), so a stray module-level ``slow`` mark —
or an unmarked end-to-end test creeping into the fast lane — fails CI
instead of silently reshaping the lane:

- ``tests/test_tiered.py`` exists and defines at least one test
  function WITHOUT ``@pytest.mark.slow`` (the tier-1 lane collects it);
- every test whose name marks it end-to-end (``end_to_end`` in the
  name) carries ``@pytest.mark.slow``;
- the module applies no module-level ``pytestmark`` slow marking (which
  would empty the fast lane wholesale).

    python tools/check_tiered_markers.py
"""

import ast
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
TIERED_TESTS = ROOT / "tests" / "test_tiered.py"


def _has_slow_marker(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if (
            isinstance(target, ast.Attribute)
            and target.attr == "slow"
            and isinstance(target.value, ast.Attribute)
            and target.value.attr == "mark"
        ):
            return True
    return False


def check(path: Path = TIERED_TESTS):
    errors = []
    if not path.exists():
        return [f"{path.name}: missing (tiered tests are tier-1 signal)"]
    tree = ast.parse(path.read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            errors.append(
                f"{path.name}: module-level pytestmark would reshape the "
                f"tier-1 lane; mark individual tests instead"
            )
    tests = [
        n
        for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name.startswith("test_")
    ]
    if not tests:
        errors.append(f"{path.name}: no test functions found")
    fast = [t for t in tests if not _has_slow_marker(t)]
    if not fast:
        errors.append(
            f"{path.name}: every test is marked slow — nothing reaches the "
            f"default -m 'not slow' lane"
        )
    for t in tests:
        if "end_to_end" in t.name and not _has_slow_marker(t):
            errors.append(
                f"{path.name}: {t.name} is end-to-end but not marked slow"
            )
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(e)
    if not errors:
        print(
            "check_tiered_markers: tiered tests are lane-correct "
            "(fast-lane tests present; end-to-end marked slow)"
        )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
