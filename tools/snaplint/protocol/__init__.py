"""Protocol model checker: a cross-module model of the coordination
plane (store key families, RPC ops, wire-context scopes, durable-write
orderings, crash points) plus the rules that run on it.

``model.py`` extracts the model from the package's ASTs; ``rules.py``
registers the protocol rule family in the shared snaplint registry.
``PROTOCOL_RULE_NAMES`` is the family list the CLI's ``--protocol``
lane selects.
"""

from .rules import PROTOCOL_RULE_NAMES  # noqa: F401
