"""Extract the coordination-plane protocol model from the package ASTs.

The model is what the protocol rules share: every store key op with its
normalized key *template*, every RPC op site (client request / server
handler / ``wire.propagate`` scope / raw frame call), every
``crashpoint`` site, and the ordered durable-write sequences per
function. One build per :class:`~tools.snaplint.core.Project`, cached
on the project object.

Key templates
-------------
A key expression normalizes to a ``/``-separated template whose
unresolvable parts are the placeholder ``{*}``:

- ``f"{OBS_PREFIX}/{role}/{ident}"``            -> ``__obs/{*}/{*}``
  (module constants resolve; locals resolve through one intraprocedural
  pass; everything else is a placeholder)
- ``head_key(topic)``                           -> ``__cdn/{*}/head``
  (single-``return`` key helpers inline cross-module, parameters bound
  to the call site's normalized arguments)
- ``self._key("flag")``                         -> ``__preemption/{*}/flag``
  (``self.X`` resolves through the enclosing class's attribute
  assignments; ``self._key`` resolves to the enclosing class's method)
- ``"{}/chunk".format(n)`` / ``"%s/c" % n``     -> ``{*}/chunk`` etc.

Two templates *unify* segment-wise (equal literal, or either side a
placeholder) — that is how a ``multi_delete`` is matched against the
``set`` family it tears down. A delete whose keys cannot be normalized
at all (an accumulated list threaded through callbacks) is recorded as
an *opaque* delete: it conservatively excuses set-families in its own
module, because static analysis cannot prove what it covers.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import (
    Container,
    Dict,
    FrozenSet,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from .. import scopes
from ..core import ModuleInfo, Project, load_module_cached

PLACEHOLDER = "{*}"
PACKAGE_PREFIX = "torchsnapshot_tpu/"
NAMES_RELPATH = "torchsnapshot_tpu/telemetry/names.py"

# Store primitives, by role.
SET_OPS = {"set", "multi_set", "add"}
DELETE_OPS = {"delete", "multi_delete"}
READ_OPS = {"try_get", "multi_get", "scan"}
BLOCKING_OPS = {"get", "wait_any"}
STORE_OPS = SET_OPS | DELETE_OPS | READ_OPS | BLOCKING_OPS

_FORMAT_FIELD_RE = re.compile(r"\{[^{}]*\}")
_PRINTF_FIELD_RE = re.compile(r"%[sdrif]")
_MULTI_PLACEHOLDER_RE = re.compile(r"(\{\*\})+")

_INLINE_DEPTH = 4  # key-helper inlining recursion bound


@dataclass
class KeySite:
    """One store primitive call on one key template."""

    relpath: str
    line: int
    col: int
    op: str  # the Store method name
    template: str
    func: str  # enclosing function qualname ("" at module level)
    rank_guarded: bool = False
    knob_guarded: bool = False

    @property
    def role(self) -> str:
        if self.op in SET_OPS:
            return "set"
        if self.op in DELETE_OPS:
            return "delete"
        if self.op in BLOCKING_OPS:
            return "wait"
        return "read"


@dataclass
class RpcSite:
    relpath: str
    line: int
    role: str  # "request" | "handler" | "propagate"
    op: str  # the RPC_* constant name


@dataclass
class FrameSite:
    relpath: str
    line: int
    kind: str  # "send" | "recv"
    func: str
    in_propagate: bool  # lexically inside a ``with *.propagate(...)``
    adopts_context: bool  # enclosing function reads the received context


@dataclass
class CrashSite:
    relpath: str
    line: int
    const: str  # the CRASH_* constant name


@dataclass
class WriteSeq:
    """Ordered durable store writes within one function, plus the crash
    points threaded through it — the commit-ordering rule's unit."""

    relpath: str
    func: str
    writes: List[KeySite] = field(default_factory=list)
    crash_lines: List[int] = field(default_factory=list)


@dataclass
class ProtocolModel:
    key_sites: List[KeySite] = field(default_factory=list)
    opaque_deletes: List[KeySite] = field(default_factory=list)
    rpc_sites: List[RpcSite] = field(default_factory=list)
    frame_sites: List[FrameSite] = field(default_factory=list)
    crash_sites: List[CrashSite] = field(default_factory=list)
    write_seqs: List[WriteSeq] = field(default_factory=list)
    declared_crashpoints: Dict[str, int] = field(default_factory=dict)
    declared_rpc_ops: Dict[str, int] = field(default_factory=dict)

    # -- derived views ----------------------------------------------------

    def families(self) -> Dict[str, List[KeySite]]:
        """Key sites grouped by exact template."""
        out: Dict[str, List[KeySite]] = {}
        for site in self.key_sites:
            out.setdefault(site.template, []).append(site)
        return out

    def namespaces(self) -> List[str]:
        """Reserved dunder namespaces (first template segment)."""
        seen: Set[str] = set()
        for site in self.key_sites:
            head = site.template.split("/", 1)[0]
            if head.startswith("__") and PLACEHOLDER not in head:
                seen.add(head)
        return sorted(seen)

    def as_dict(self) -> Dict:
        """The ``--protocol-dump`` inventory: one entry per key family
        (who sets/reads/waits/deletes, under which guards), the RPC op
        table, and the crash-point registry."""
        fam_rows = []
        for template in sorted(self.families()):
            sites = self.families()[template]
            row: Dict = {"template": template, "ops": {}}
            for site in sites:
                row["ops"].setdefault(site.role, []).append(
                    {
                        "path": site.relpath,
                        "line": site.line,
                        "op": site.op,
                        "rank_guarded": site.rank_guarded,
                        "knob_guarded": site.knob_guarded,
                    }
                )
            fam_rows.append(row)
        rpc_rows: Dict[str, Dict[str, List]] = {}
        for site in self.rpc_sites:
            rpc_rows.setdefault(site.op, {}).setdefault(site.role, []).append(
                f"{site.relpath}:{site.line}"
            )
        return {
            "version": 1,
            "namespaces": self.namespaces(),
            "key_families": fam_rows,
            "opaque_deletes": [
                f"{s.relpath}:{s.line}" for s in self.opaque_deletes
            ],
            "rpc_ops": rpc_rows,
            "declared_rpc_ops": sorted(self.declared_rpc_ops),
            "crashpoints": {
                const: sorted(
                    f"{s.relpath}:{s.line}"
                    for s in self.crash_sites
                    if s.const == const
                )
                for const in sorted(self.declared_crashpoints)
            },
        }


# ---------------------------------------------------------------------------
# Template machinery


def collapse(template: str) -> str:
    return _MULTI_PLACEHOLDER_RE.sub(PLACEHOLDER, template)


def segments(template: str) -> List[str]:
    return [
        PLACEHOLDER if PLACEHOLDER in seg else seg
        for seg in collapse(template).split("/")
    ]


def unifies(a: str, b: str) -> bool:
    """Do two templates describe the same key family? Segment-wise:
    equal literals, or either side a placeholder."""
    sa, sb = segments(a), segments(b)
    if len(sa) != len(sb):
        return False
    return all(
        x == y or x == PLACEHOLDER or y == PLACEHOLDER
        for x, y in zip(sa, sb)
    )


def is_opaque(template: str) -> bool:
    """No literal content survived normalization."""
    return all(seg == PLACEHOLDER for seg in segments(template))


class _Env:
    """Name-resolution context for one call site: locals of the
    enclosing function, explicit parameter bindings (helper inlining),
    module constants, ``self.X`` class attributes, and the key-helper
    tables."""

    def __init__(
        self,
        extractor: "_Extractor",
        module: ModuleInfo,
        local_templates: Dict[str, str],
        bindings: Optional[Dict[str, str]] = None,
        cls: Optional[str] = None,
    ) -> None:
        self.extractor = extractor
        self.module = module
        self.local_templates = local_templates
        self.bindings = bindings or {}
        self.cls = cls


def _normalize(expr: ast.AST, env: _Env, depth: int = 0) -> str:
    """Best-effort key template for ``expr`` (always returns a string;
    unresolvable parts become placeholders)."""
    if depth > _INLINE_DEPTH:
        return PLACEHOLDER
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, str):
            return expr.value
        return PLACEHOLDER
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for part in expr.values:
            if isinstance(part, ast.FormattedValue):
                parts.append(_normalize(part.value, env, depth + 1))
            else:
                parts.append(_normalize(part, env, depth + 1))
        return collapse("".join(parts))
    if isinstance(expr, ast.Name):
        if expr.id in env.bindings:
            return env.bindings[expr.id]
        if expr.id in env.local_templates:
            return env.local_templates[expr.id]
        const = env.extractor.module_consts.get(env.module.relpath, {}).get(
            expr.id
        )
        if const is not None:
            return const
        return PLACEHOLDER
    if isinstance(expr, ast.Attribute):
        chain = scopes.attr_chain(expr)
        if len(chain) == 2 and chain[0] == "self" and env.cls:
            attr = env.extractor.class_attrs.get(
                (env.module.relpath, env.cls), {}
            ).get(chain[1])
            if attr is not None:
                return attr
        if len(chain) == 2:
            # MODULE.CONST through an import is rare for keys; try the
            # bare constant name in any package module as a fallback.
            const = env.extractor.global_consts.get(chain[1])
            if const is not None:
                return const
        return PLACEHOLDER
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return collapse(
            _normalize(expr.left, env, depth + 1)
            + _normalize(expr.right, env, depth + 1)
        )
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mod):
        left = _normalize(expr.left, env, depth + 1)
        return collapse(_PRINTF_FIELD_RE.sub(PLACEHOLDER, left))
    if isinstance(expr, ast.Call):
        chain = scopes.call_chain(expr)
        terminal = chain[-1] if chain else None
        # The receiver of ``.format`` is often a string literal, where
        # attr_chain (and thus ``terminal``) is empty — match on the
        # attribute itself.
        if isinstance(expr.func, ast.Attribute) and expr.func.attr == "format":
            base = _normalize(expr.func.value, env, depth + 1)
            return collapse(_FORMAT_FIELD_RE.sub(PLACEHOLDER, base))
        if terminal == "str" and expr.args:
            return _normalize(expr.args[0], env, depth + 1)
        helper = env.extractor.resolve_helper(env, chain)
        if helper is not None:
            h_module, h_fn, h_cls = helper
            params = [
                a.arg
                for a in h_fn.args.args
                if a.arg not in ("self", "cls")
            ]
            bound: Dict[str, str] = {}
            for i, param in enumerate(params):
                if i < len(expr.args):
                    bound[param] = _normalize(expr.args[i], env, depth + 1)
                else:
                    bound[param] = PLACEHOLDER
            for kw in expr.keywords:
                if kw.arg:
                    bound[kw.arg] = _normalize(kw.value, env, depth + 1)
            ret = env.extractor.helper_return(h_fn)
            if ret is not None:
                h_env = _Env(
                    env.extractor,
                    h_module,
                    {},
                    bindings=bound,
                    cls=h_cls,
                )
                return _normalize(ret, h_env, depth + 1)
        return PLACEHOLDER
    return PLACEHOLDER


def _key_args(call: ast.Call, op: str) -> Tuple[List[ast.AST], bool]:
    """The key expression(s) of a store-op call, plus whether the arg
    shape itself was resolvable (a Name arg is resolved later)."""
    if not call.args:
        return [], False
    return [call.args[0]], True


def _iter_container_keys(
    expr: ast.AST, env: _Env, fn: Optional[ast.AST]
) -> Tuple[List[str], bool]:
    """Key templates flowing into a list/dict argument (``multi_set``
    items, ``multi_get``/``multi_delete`` key lists). Returns
    ``(templates, resolved)`` — ``resolved`` False means the container
    could not be traced (an opaque batch)."""
    if isinstance(expr, ast.Dict):
        return [_normalize(k, env) for k in expr.keys if k is not None], True
    if isinstance(expr, ast.DictComp):
        return [_normalize(expr.key, env)], True
    if isinstance(expr, (ast.List, ast.Tuple, ast.Set)):
        out: List[str] = []
        ok = True
        for elt in expr.elts:
            if isinstance(elt, ast.Starred):
                sub, sub_ok = _iter_container_keys(elt.value, env, fn)
                out.extend(sub)
                ok = ok and sub_ok
            else:
                out.append(_normalize(elt, env))
        return out, ok
    if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return [_normalize(expr.elt, env)], True
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left, lok = _iter_container_keys(expr.left, env, fn)
        right, rok = _iter_container_keys(expr.right, env, fn)
        return left + right, lok and rok
    if isinstance(expr, ast.Call):
        chain = scopes.call_chain(expr)
        if chain and chain[-1] in ("list", "sorted", "set", "tuple") and expr.args:
            return _iter_container_keys(expr.args[0], env, fn)
        return [], False
    if isinstance(expr, ast.Name) and fn is not None:
        # Resolve the container through local dataflow: literal/comp
        # assignments, ``name.append(...)`` and ``name[key] = ...``.
        templates: List[str] = []
        resolved = False
        opaque_flow = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == expr.id:
                        sub, ok = _iter_container_keys(node.value, env, fn)
                        if isinstance(node.value, (ast.List, ast.Dict,
                                                   ast.ListComp, ast.DictComp,
                                                   ast.SetComp, ast.BinOp,
                                                   ast.Tuple, ast.Set,
                                                   ast.GeneratorExp, ast.Call)):
                            templates.extend(sub)
                            resolved = resolved or ok
                            opaque_flow = opaque_flow or not ok
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == expr.id
                    ):
                        templates.append(_normalize(tgt.slice, env))
                        resolved = True
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("append", "add", "extend")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == expr.id
                and node.args
            ):
                tpl = _normalize(node.args[0], env)
                templates.append(tpl)
                resolved = True
                if is_opaque(tpl):
                    opaque_flow = True
        if opaque_flow:
            return templates, False
        return templates, resolved
    return [], False


# ---------------------------------------------------------------------------
# Extraction


def _is_store_receiver(
    chain: List[str], store_params: Container[str] = ()
) -> bool:
    """Does the call receiver look like a coordination store? Matches
    ``store.set`` / ``self._store.multi_set`` / ``cas_store.delete``,
    plus any receiver named in ``store_params`` (parameters of the
    enclosing function annotated ``Store`` — the bootstrap helpers call
    theirs ``base``/``kv``); excludes bare ``self.try_get`` (a Store
    subclass's own primitive implementation) and unrelated dicts
    (``d.get``)."""
    if len(chain) < 2:
        return False
    receiver = chain[:-1]
    if receiver[0] in store_params:
        return True
    return any("store" in part.lower() for part in receiver)


def _store_annotated_params(
    fn: Optional[ast.AST],
) -> FrozenSet[str]:
    """Names of ``fn``'s parameters whose annotation mentions Store."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return frozenset()
    names = set()
    args = fn.args
    for arg in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
        ann = arg.annotation
        if ann is not None and "Store" in ast.dump(ann):
            names.add(arg.arg)
    return frozenset(names)


def _qualname(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Tuple[str, Optional[str]]:
    """(dotted function qualname, enclosing class name) for a node."""
    names: List[str] = []
    cls: Optional[str] = None
    for anc in scopes.ancestors(node, parents):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.append(anc.name)
        elif isinstance(anc, ast.ClassDef):
            if cls is None:
                cls = anc.name
            names.append(anc.name)
    return ".".join(reversed(names)), cls


def _outermost_function(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.AST]:
    """The outermost enclosing def — nested helpers/closures attribute
    their sites to the top-level function for sequencing purposes."""
    out = None
    for anc in scopes.ancestors(node, parents):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out = anc
    return out


class _Extractor:
    def __init__(self, modules: Sequence[ModuleInfo]) -> None:
        self.modules = list(modules)
        # relpath -> {NAME: template} for module-level string constants
        self.module_consts: Dict[str, Dict[str, str]] = {}
        # bare constant name -> template (cross-module fallback; only
        # kept when unambiguous)
        self.global_consts: Dict[str, str] = {}
        # (relpath, class) -> {attr: template}
        self.class_attrs: Dict[Tuple[str, str], Dict[str, str]] = {}
        # key helpers: functions/methods whose last statement returns a
        # string expression. name -> [(module, fn_node, class or None)]
        self.helpers: Dict[str, List[Tuple[ModuleInfo, ast.AST, Optional[str]]]] = {}
        self.model = ProtocolModel()

    # -- symbol tables ----------------------------------------------------

    def _collect_tables(self) -> None:
        ambiguous: Set[str] = set()
        for module in self.modules:
            consts: Dict[str, str] = {}
            for node in module.tree.body:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant
                ):
                    if isinstance(node.value.value, str):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                consts[tgt.id] = node.value.value
            self.module_consts[module.relpath] = consts
            for name, value in consts.items():
                if name in self.global_consts and self.global_consts[name] != value:
                    ambiguous.add(name)
                self.global_consts.setdefault(name, value)
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _, cls = _qualname(node, module.parents)
                    if self.helper_return(node) is not None:
                        self.helpers.setdefault(node.name, []).append(
                            (module, node, cls)
                        )
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        chain = scopes.attr_chain(tgt)
                        if len(chain) == 2 and chain[0] == "self":
                            _, cls = _qualname(node, module.parents)
                            if cls is None:
                                continue
                            attrs = self.class_attrs.setdefault(
                                (module.relpath, cls), {}
                            )
                            if chain[1] not in attrs:
                                env = _Env(self, module, {}, cls=cls)
                                tpl = _normalize(node.value, env)
                                if not is_opaque(tpl):
                                    attrs[chain[1]] = tpl
        for name in ambiguous:
            self.global_consts.pop(name, None)

    @staticmethod
    def helper_return(fn: ast.AST) -> Optional[ast.AST]:
        """The returned expression of a single-return key helper."""
        body = getattr(fn, "body", [])
        rets = [n for n in body if isinstance(n, ast.Return)]
        if len(rets) == 1 and rets[0].value is not None:
            val = rets[0].value
            if isinstance(
                val, (ast.JoinedStr, ast.Constant, ast.BinOp, ast.Name, ast.Call)
            ):
                return val
        return None

    def resolve_helper(
        self, env: _Env, chain: List[str]
    ) -> Optional[Tuple[ModuleInfo, ast.AST, Optional[str]]]:
        """Resolve a call chain to a key-helper def: ``self._key`` binds
        to the enclosing class's method; a bare/imported name binds to
        the project-wide def when unambiguous."""
        if not chain:
            return None
        name = chain[-1]
        candidates = self.helpers.get(name, [])
        if not candidates:
            return None
        if len(chain) == 2 and chain[0] == "self" and env.cls:
            for module, fn, cls in candidates:
                if cls == env.cls and module.relpath == env.module.relpath:
                    return module, fn, cls
            return None
        free = [c for c in candidates if c[2] is None]
        same_module = [c for c in free if c[0].relpath == env.module.relpath]
        if same_module:
            return same_module[0]
        if len(free) == 1:
            return free[0]
        return None

    # -- per-module extraction --------------------------------------------

    def _local_templates(
        self, fn: Optional[ast.AST], module: ModuleInfo, cls: Optional[str]
    ) -> Dict[str, str]:
        """One pass of simple-assignment resolution inside ``fn`` (two
        rounds, so ``p = f"{prefix}/fanout"; k = f"{p}/needs"`` chains)."""
        scope = fn if fn is not None else module.tree
        out: Dict[str, str] = {}
        for _ in range(2):
            env = _Env(self, module, out, cls=cls)
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name):
                        tpl = _normalize(node.value, env)
                        if not is_opaque(tpl):
                            out[tgt.id] = tpl
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    if isinstance(node.target, ast.Name):
                        tpl = _normalize(node.value, env)
                        if not is_opaque(tpl):
                            out[node.target.id] = tpl
        return out

    def _guards(
        self,
        node: ast.AST,
        module: ModuleInfo,
        fn: Optional[ast.AST],
        taint_cache: Dict,
        knob_names: Set[str],
    ) -> Tuple[bool, bool]:
        scope = fn if fn is not None else module.tree
        if scope not in taint_cache:
            taint_cache[scope] = scopes.tainted_names(scope, knob_names)
        knob_taint, rank_taint = taint_cache[scope]
        rank_guarded = knob_guarded = False
        for test, _guard in scopes.guard_tests(node, module.parents, stop_at=fn):
            if scopes.expr_rank_tainted(test, rank_taint):
                rank_guarded = True
            if scopes.expr_knob_tainted(test, knob_taint, knob_names):
                knob_guarded = True
        return rank_guarded, knob_guarded

    def _extract_module(self, module: ModuleInfo) -> None:
        parents = module.parents
        knob_names = scopes.knob_import_names(module.tree)
        taint_cache: Dict = {}
        local_cache: Dict = {}
        seqs: Dict[Tuple[str, str], WriteSeq] = {}

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = scopes.call_chain(node)
            terminal = chain[-1] if chain else None

            # crashpoint(names.CRASH_*) / _crashpoint(...) / arm(...)
            if terminal in ("crashpoint", "_crashpoint") and node.args:
                arg_chain = scopes.attr_chain(node.args[0])
                const = arg_chain[-1] if arg_chain else None
                if isinstance(node.args[0], ast.Name):
                    const = node.args[0].id
                if const and const.startswith("CRASH_"):
                    self.model.crash_sites.append(
                        CrashSite(module.relpath, node.lineno, const)
                    )
                outer = _outermost_function(node, parents)
                if outer is not None:
                    qn, _ = _qualname(node, parents)
                    key = (module.relpath, outer.name)
                    seq = seqs.setdefault(
                        key, WriteSeq(module.relpath, outer.name)
                    )
                    seq.crash_lines.append(node.lineno)

            # RPC sites: *.request(RPC_*) / *.propagate(RPC_*) and
            # handler comparisons are collected in a separate walk below.
            if terminal in ("request", "propagate") and node.args:
                arg_chain = scopes.attr_chain(node.args[0])
                const = arg_chain[-1] if arg_chain else None
                if const and const.startswith("RPC_"):
                    self.model.rpc_sites.append(
                        RpcSite(
                            module.relpath,
                            node.lineno,
                            "propagate" if terminal == "propagate" else "request",
                            const,
                        )
                    )

            # send_frame / recv_frame coverage
            if terminal in ("send_frame", "recv_frame", "_send_msg", "_recv_msg"):
                fn = scopes.enclosing_function(node, parents)
                qn, _cls = _qualname(node, parents)
                in_prop = False
                for anc in scopes.ancestors(node, parents):
                    if isinstance(anc, ast.With):
                        for ctx in scopes.with_context_exprs(anc):
                            for sub in ast.walk(ctx):
                                if isinstance(sub, ast.Call):
                                    c = scopes.call_chain(sub)
                                    if c and c[-1] == "propagate":
                                        in_prop = True
                    if anc is fn:
                        break
                adopts = False
                scope = fn if fn is not None else module.tree
                for sub in ast.walk(scope):
                    if isinstance(sub, ast.Call):
                        c = scopes.call_chain(sub)
                        if c and c[-1] in (
                            "last_received_context",
                            "set_received_context",
                        ):
                            adopts = True
                self.model.frame_sites.append(
                    FrameSite(
                        module.relpath,
                        node.lineno,
                        "send" if terminal in ("send_frame", "_send_msg") else "recv",
                        qn,
                        in_prop,
                        adopts,
                    )
                )

            # Store key ops
            if (
                terminal in STORE_OPS
                and isinstance(node.func, ast.Attribute)
                and _is_store_receiver(
                    chain,
                    _store_annotated_params(
                        scopes.enclosing_function(node, parents)
                    ),
                )
            ):
                fn = scopes.enclosing_function(node, parents)
                qn, cls = _qualname(node, parents)
                cache_key = id(fn) if fn is not None else id(module.tree)
                if cache_key not in local_cache:
                    outer = _outermost_function(node, parents)
                    local_cache[cache_key] = self._local_templates(
                        outer if outer is not None else fn, module, cls
                    )
                env = _Env(self, module, local_cache[cache_key], cls=cls)
                outer = _outermost_function(node, parents)
                templates: List[str] = []
                resolved = True
                if terminal in (
                    "multi_set",
                    "multi_get",
                    "multi_delete",
                    "wait_any",
                ):
                    if node.args:
                        templates, resolved = _iter_container_keys(
                            node.args[0], env, outer
                        )
                    else:
                        resolved = False
                elif terminal == "scan":
                    if node.args:
                        templates = [
                            collapse(
                                _normalize(node.args[0], env).rstrip("/")
                                + "/"
                                + PLACEHOLDER
                            )
                        ]
                elif node.args:
                    templates = [_normalize(node.args[0], env)]
                else:
                    resolved = False
                rank_g, knob_g = self._guards(
                    node, module, fn, taint_cache, knob_names
                )
                for tpl in templates:
                    site = KeySite(
                        relpath=module.relpath,
                        line=node.lineno,
                        col=node.col_offset,
                        op=terminal,
                        template=collapse(tpl),
                        func=qn,
                        rank_guarded=rank_g,
                        knob_guarded=knob_g,
                    )
                    self.model.key_sites.append(site)
                    if terminal in SET_OPS and terminal != "add":
                        outer2 = _outermost_function(node, parents)
                        if outer2 is not None:
                            key = (module.relpath, outer2.name)
                            seq = seqs.setdefault(
                                key, WriteSeq(module.relpath, outer2.name)
                            )
                            seq.writes.append(site)
                if terminal in DELETE_OPS and (
                    not resolved or all(is_opaque(t) for t in templates)
                ):
                    self.model.opaque_deletes.append(
                        KeySite(
                            relpath=module.relpath,
                            line=node.lineno,
                            col=node.col_offset,
                            op=terminal,
                            template=PLACEHOLDER,
                            func=qn,
                        )
                    )

        # handler comparisons: ``cmd == names.RPC_*``
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                for operand in operands:
                    op_chain = scopes.attr_chain(operand)
                    const = op_chain[-1] if op_chain else None
                    if const and const.startswith("RPC_"):
                        others = [o for o in operands if o is not operand]
                        if any(
                            isinstance(o, ast.Name)
                            or isinstance(o, ast.Attribute)
                            for o in others
                        ):
                            self.model.rpc_sites.append(
                                RpcSite(
                                    module.relpath, node.lineno, "handler", const
                                )
                            )

        self.model.write_seqs.extend(
            seqs[k] for k in sorted(seqs, key=lambda k: (k[0], k[1]))
        )

    def _collect_declarations(self) -> None:
        for module in self.modules:
            if module.relpath != NAMES_RELPATH:
                continue
            for node in module.tree.body:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant
                ):
                    for tgt in node.targets:
                        if not isinstance(tgt, ast.Name):
                            continue
                        if tgt.id.startswith("CRASH_"):
                            self.model.declared_crashpoints[tgt.id] = node.lineno
                        elif tgt.id.startswith("RPC_"):
                            self.model.declared_rpc_ops[tgt.id] = node.lineno

    def build(self) -> ProtocolModel:
        self._collect_tables()
        for module in self.modules:
            self._extract_module(module)
        self._collect_declarations()
        self.model.key_sites.sort(key=lambda s: (s.relpath, s.line, s.col))
        self.model.rpc_sites.sort(key=lambda s: (s.relpath, s.line))
        return self.model


def package_modules(project: Project) -> List[ModuleInfo]:
    """Every package module — the loaded ones, plus a disk fallback so
    the cross-module model holds even on a partial-path run (the
    names-lint discipline). Uses the shared parse cache."""
    modules = {
        m.relpath: m
        for m in project.modules
        if m.relpath.startswith(PACKAGE_PREFIX)
    }
    pkg_root = project.root / "torchsnapshot_tpu"
    if pkg_root.is_dir():
        for path in sorted(pkg_root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.resolve().relative_to(project.root.resolve()).as_posix()
            if rel in modules:
                continue
            try:
                modules[rel] = load_module_cached(path, project.root)
            except (OSError, SyntaxError):
                continue
    return [modules[k] for k in sorted(modules)]


def get_model(project: Project) -> ProtocolModel:
    """Build (or reuse) the protocol model for this project — one
    extraction shared by every protocol rule in the run."""
    cached = getattr(project, "_protocol_model", None)
    if cached is not None:
        return cached
    model = _Extractor(package_modules(project)).build()
    project._protocol_model = model  # type: ignore[attr-defined]
    return model
