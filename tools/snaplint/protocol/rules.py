"""The protocol rule family: checkers over the extracted coordination
model (see ``model.py`` for the templates/sites they consume).

All six are *project-level* rules in the names-lint discipline: they
judge cross-module invariants against the whole package (with the disk
fallback), so a partial-path run still sees the full protocol surface.
Inline suppressions apply at the reported site; the shipped baseline
for this family is empty and must stay empty.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from .. import scopes
from ..core import Finding, Project, Rule, register
from . import model as m

# The rule names the CLI's ``--protocol`` lane selects.
PROTOCOL_RULE_NAMES = [
    "store-key-leak",
    "rank-asymmetric-protocol",
    "wait-without-error-poll",
    "rpc-unpaired",
    "commit-ordering",
    "store-namespace-docs",
]

SCALING_DOC_RELPATH = "docs/scaling.md"

# Modules whose rank-conditional traffic IS the protocol they implement.
_IMPL_EXEMPT = (
    "torchsnapshot_tpu/dist_store.py",
    "torchsnapshot_tpu/pg_wrapper.py",
)


def _in_package(relpath: str) -> bool:
    return relpath.startswith(m.PACKAGE_PREFIX)


# ---------------------------------------------------------------------------
# store-key-leak


@register
class StoreKeyLeak(Rule):
    name = "store-key-leak"
    description = (
        "store key family set on some path but deleted on none — a "
        "coordination-store leak at scale (registry namespaces need an "
        "inline justification)"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        mdl = m.get_model(project)
        deletes = [s for s in mdl.key_sites if s.role == "delete"]
        opaque_modules = {s.relpath for s in mdl.opaque_deletes}
        reported: Set[str] = set()
        for site in mdl.key_sites:
            if site.role != "set":
                continue
            tpl = site.template
            if m.is_opaque(tpl):
                continue  # nothing to judge: the key never normalized
            if tpl in reported:
                continue
            if any(
                m.unifies(tpl, d.template) and not m.is_opaque(d.template)
                for d in deletes
            ):
                continue
            if site.relpath in opaque_modules:
                # A delete whose key list could not be traced lives in
                # this module; static analysis cannot prove it does NOT
                # cover this family. Conservative: no finding.
                continue
            reported.add(tpl)
            yield Finding(
                rule=self.name,
                path=site.relpath,
                line=site.line,
                col=site.col,
                message=(
                    f"store key family '{tpl}' is written here but no "
                    f"delete in the project covers it — every write "
                    f"grows the coordination store forever at scale; "
                    f"tear the family down (multi_delete/counter "
                    f"cleanup) or mark the registry semantics with an "
                    f"inline justification"
                ),
            )


# ---------------------------------------------------------------------------
# rank-asymmetric-protocol


def _collective_call(node: ast.Call) -> Optional[str]:
    from ..rules.collective_under_conditional import (
        COLLECTIVE_METHODS,
        _NON_COLLECTIVE_ROOTS,
    )

    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr not in COLLECTIVE_METHODS:
        return None
    chain = scopes.attr_chain(func)
    if chain and chain[0] in _NON_COLLECTIVE_ROOTS:
        return None
    return func.attr


@register
class RankAsymmetricProtocol(Rule):
    name = "rank-asymmetric-protocol"
    description = (
        "rank/knob asymmetry across function boundaries: a knob-guarded "
        "set whose waiters are unguarded, or a collective reachable "
        "through a call chain under non-laundered per-rank state"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        mdl = m.get_model(project)

        # Part 1 — key families whose every writer is knob-guarded while
        # some blocking wait for the family is not: a knob skewed across
        # ranks strands the waiters for the full store timeout.
        families = mdl.families()
        for tpl in sorted(families):
            sites = families[tpl]
            sets = [s for s in sites if s.role == "set"]
            waits = [s for s in sites if s.role == "wait"]
            if not sets or not waits:
                continue
            if all(s.knob_guarded for s in sets):
                for wait in waits:
                    if not wait.knob_guarded:
                        yield Finding(
                            rule=self.name,
                            path=wait.relpath,
                            line=wait.line,
                            col=wait.col,
                            message=(
                                f"blocking wait for store key family "
                                f"'{tpl}' is unguarded, but every write "
                                f"of the family sits under a knob/env "
                                f"guard (e.g. "
                                f"{sets[0].relpath}:{sets[0].line}) — a "
                                f"knob skewed across ranks strands this "
                                f"wait for the full store timeout"
                            ),
                        )
                        break

        # Part 2 — the PR 8 taint, extended across function boundaries:
        # a call chain that reaches a collective, invoked under a
        # non-laundered rank/knob guard. (Direct guarded collectives are
        # collective-under-conditional's finding; this rule owns the
        # indirect case it cannot see.) The call graph is name-based, so
        # it only admits names defined EXACTLY ONCE in the package —
        # `get`/`set`/`close` live on dozens of classes and a bare-name
        # edge through them would convict half the codebase. Same
        # uniqueness discipline as the model's key-helper inlining.
        from ..rules.collective_under_conditional import COLLECTIVE_METHODS

        modules = {mod.relpath: mod for mod in m.package_modules(project)}
        defs: Dict[str, List[str]] = {}
        for relpath, mod in modules.items():
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.setdefault(node.name, []).append(relpath)
        unique = {
            name
            for name, where in defs.items()
            if len(where) == 1
            and not where[0].endswith(_IMPL_EXEMPT)
            and name not in COLLECTIVE_METHODS
        }
        contains: Set[str] = set()  # unique functions directly holding one
        calls: Dict[str, Set[str]] = {}  # unique fn -> unique callee names
        for relpath, mod in modules.items():
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if node.name not in unique:
                    continue
                callees: Set[str] = calls.setdefault(node.name, set())
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        if _collective_call(sub) is not None:
                            contains.add(node.name)
                        chain = scopes.call_chain(sub)
                        if chain and chain[-1] in unique:
                            callees.add(chain[-1])
        # Transitive closure, bounded by the function-name graph size.
        reaches = set(contains)
        changed = True
        while changed:
            changed = False
            for name, callees in calls.items():
                if name not in reaches and callees & reaches:
                    reaches.add(name)
                    changed = True

        for relpath, mod in modules.items():
            if relpath.endswith(_IMPL_EXEMPT):
                continue
            knob_names = scopes.knob_import_names(mod.tree)
            taint_cache: Dict = {}
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = scopes.call_chain(node)
                if not chain:
                    continue
                callee = chain[-1]
                if callee not in reaches or callee in COLLECTIVE_METHODS:
                    continue
                fn = scopes.enclosing_function(node, mod.parents)
                scope = fn if fn is not None else mod.tree
                if scope not in taint_cache:
                    taint_cache[scope] = scopes.tainted_names(
                        scope, knob_names
                    )
                knob_taint, rank_taint = taint_cache[scope]
                for test, guard in scopes.guard_tests(
                    node, mod.parents, stop_at=fn
                ):
                    kinds = []
                    if scopes.expr_knob_tainted(test, knob_taint, knob_names):
                        kinds.append("knob/env")
                    if scopes.expr_rank_tainted(test, rank_taint):
                        kinds.append("rank")
                    if kinds:
                        yield Finding(
                            rule=self.name,
                            path=relpath,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"call to {callee}() — which reaches a "
                                f"cross-rank collective — is guarded by "
                                f"a {'/'.join(kinds)}-dependent test "
                                f"(line {guard.lineno}); a skewed guard "
                                f"strands the rendezvous inside the "
                                f"callee"
                            ),
                        )
                        break


# ---------------------------------------------------------------------------
# wait-without-error-poll


@register
class WaitWithoutErrorPoll(Rule):
    name = "wait-without-error-poll"
    description = (
        "hand-rolled store wait loop that neither polls its round's "
        "error key nor rides _PollPacer — peers cannot fail it fast"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        for mod in m.package_modules(project):
            for loop in ast.walk(mod.tree):
                if not isinstance(loop, ast.While):
                    continue
                store_reads: List[ast.Call] = []
                sleeps: List[List[str]] = []
                reads_error = False
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = scopes.call_chain(node)
                    terminal = chain[-1] if chain else None
                    if (
                        terminal in ("try_get", "multi_get", "get")
                        and isinstance(node.func, ast.Attribute)
                        and m._is_store_receiver(chain)
                    ):
                        store_reads.append(node)
                        for arg in ast.walk(node):
                            if (
                                isinstance(arg, ast.Constant)
                                and isinstance(arg.value, str)
                                and (
                                    arg.value == "error"
                                    or arg.value.endswith("/error")
                                )
                            ):
                                reads_error = True
                            if (
                                isinstance(arg, ast.Name)
                                and "error" in arg.id.lower()
                            ):
                                reads_error = True
                            if (
                                isinstance(arg, ast.JoinedStr)
                                and any(
                                    isinstance(p, ast.Constant)
                                    and isinstance(p.value, str)
                                    and "error" in p.value
                                    for p in arg.values
                                )
                            ):
                                reads_error = True
                    if terminal == "sleep":
                        sleeps.append(chain)
                if not store_reads or not sleeps:
                    continue
                if reads_error:
                    continue
                # A pacer ride: any sleep whose receiver is not the
                # ``time`` module is the shared exponential-backoff
                # pacer (``pacer.sleep`` / ``self._pacer.sleep``).
                if any(chain[:-1] != ["time"] for chain in sleeps):
                    continue
                yield Finding(
                    rule=self.name,
                    path=mod.relpath,
                    line=loop.lineno,
                    col=loop.col_offset,
                    message=(
                        "store wait loop polls with a fixed time.sleep "
                        "and never reads its round's error key — a peer "
                        "that failed cannot fail this waiter fast "
                        "(multi_get the error key with the data keys, "
                        "or ride _PollPacer; see the PR 8 fail-fast "
                        "discipline in docs/scaling.md)"
                    ),
                )


# ---------------------------------------------------------------------------
# rpc-unpaired


@register
class RpcUnpaired(Rule):
    name = "rpc-unpaired"
    description = (
        "RPC op with a client and no server handler (or vice versa), or "
        "a raw frame call outside any wire.propagate scope — invisible "
        "to the wire observatory"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        mdl = m.get_model(project)
        by_role: Dict[str, Dict[str, List[m.RpcSite]]] = {}
        for site in mdl.rpc_sites:
            if not _in_package(site.relpath):
                continue
            by_role.setdefault(site.op, {}).setdefault(site.role, []).append(
                site
            )

        # Pairing applies to the request/response families — ops that
        # appear in a dispatch comparison or a ``.request()`` call.
        # One-sided round scopes (RPC_FANOUT_*, RPC_CDN_*) and the
        # store's cmd-int wire ops (RPC_STORE_*, mapped through
        # _store_rpc_ids) have no handler-comparison shape to pair.
        for op in sorted(by_role):
            roles = by_role[op]
            requests = roles.get("request", [])
            handlers = roles.get("handler", [])
            if requests and not handlers:
                site = requests[0]
                yield Finding(
                    rule=self.name,
                    path=site.relpath,
                    line=site.line,
                    message=(
                        f"client sends RPC op {op} but no server "
                        f"dispatch handles it — the request can only "
                        f"fail at the peer"
                    ),
                )
            elif handlers and not requests:
                site = handlers[0]
                yield Finding(
                    rule=self.name,
                    path=site.relpath,
                    line=site.line,
                    message=(
                        f"server dispatch handles RPC op {op} but no "
                        f"client call site sends it — dead protocol "
                        f"surface (add the client wrapper or retire "
                        f"the handler)"
                    ),
                )

        # Frame-coverage: every raw send_frame/recv_frame outside the
        # framing layer itself must either sit inside a
        # ``with wire.propagate(...)`` scope (client side) or adopt the
        # received context (server side) — otherwise its traffic
        # vanishes from the wire observatory's merged traces.
        for site in mdl.frame_sites:
            if not _in_package(site.relpath):
                continue
            if site.relpath in _IMPL_EXEMPT or site.relpath.endswith(
                "dist_store.py"
            ):
                continue
            if site.in_propagate or site.adopts_context:
                continue
            yield Finding(
                rule=self.name,
                path=site.relpath,
                line=site.line,
                message=(
                    f"raw {site.kind}_frame call in {site.func or '<module>'} "
                    f"is outside any wire.propagate scope and never adopts "
                    f"the received wire context — this RPC is invisible to "
                    f"the wire observatory"
                ),
            )


# ---------------------------------------------------------------------------
# commit-ordering


_MARKER_SEGMENTS = {"head"}
_MARKER_SUBSTRINGS = ("commit", "marker")


def _is_marker(template: str) -> bool:
    segs = m.segments(template)
    last_literal = next(
        (s for s in reversed(segs) if s != m.PLACEHOLDER), None
    )
    if last_literal is None:
        return False
    return last_literal in _MARKER_SEGMENTS or any(
        sub in last_literal for sub in _MARKER_SUBSTRINGS
    )


def _namespace_root(template: str) -> Optional[str]:
    head = m.segments(template)[0]
    return None if head == m.PLACEHOLDER else head


@register
class CommitOrdering(Rule):
    name = "commit-ordering"
    description = (
        "durable marker/head write statically reachable before its "
        "payload writes, a marker-last sequence with no declared crash "
        "point, or a declared CRASH_* id threaded through no code path"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        mdl = m.get_model(project)
        for seq in mdl.write_seqs:
            if not _in_package(seq.relpath):
                continue
            markers = [w for w in seq.writes if _is_marker(w.template)]
            payloads = [w for w in seq.writes if not _is_marker(w.template)]
            for marker in markers:
                ns = _namespace_root(marker.template)
                related = [
                    p
                    for p in payloads
                    if ns is not None and _namespace_root(p.template) == ns
                ]
                late = [p for p in related if p.line > marker.line]
                if late:
                    yield Finding(
                        rule=self.name,
                        path=marker.relpath,
                        line=marker.line,
                        col=marker.col,
                        message=(
                            f"durable marker '{marker.template}' is "
                            f"written before payload "
                            f"'{late[0].template}' (line {late[0].line}) "
                            f"in {seq.func}() — a kill between the "
                            f"writes publishes a marker whose payload "
                            f"does not exist; write the payload first"
                        ),
                    )
                    continue
                early = [p for p in related if p.line < marker.line]
                if early and not any(
                    early[-1].line <= cl <= marker.line
                    for cl in seq.crash_lines
                ):
                    yield Finding(
                        rule=self.name,
                        path=marker.relpath,
                        line=marker.line,
                        col=marker.col,
                        message=(
                            f"marker-last sequence in {seq.func}() "
                            f"(payload '{early[-1].template}' then "
                            f"marker '{marker.template}') has no "
                            f"crashpoint() between the writes — the "
                            f"chaos matrix cannot kill the torn-state "
                            f"window; declare a CRASH_* id and thread "
                            f"it (docs/chaos.md)"
                        ),
                    )

        # Registry cross-check: every declared CRASH_* id must be
        # threaded through at least one crashpoint() site — a declared
        # point no code path hits is a crash-matrix row that can never
        # fire, which reads as coverage that does not exist.
        threaded = {s.const for s in mdl.crash_sites}
        for const in sorted(mdl.declared_crashpoints):
            if const not in threaded:
                yield Finding(
                    rule=self.name,
                    path=m.NAMES_RELPATH,
                    line=mdl.declared_crashpoints[const],
                    message=(
                        f"declared crash point {const} is threaded "
                        f"through no crashpoint() call site — the crash "
                        f"matrix sweeps a row that can never fire; "
                        f"thread it or retire the declaration"
                    ),
                )


# ---------------------------------------------------------------------------
# store-namespace-docs (drive-by: the dump doubles as an inventory)


_DOC_NAMESPACE_RE = re.compile(r"`(__[a-z_]+)/")


@register
class StoreNamespaceDocs(Rule):
    name = "store-namespace-docs"
    description = (
        "the store-key namespace table in docs/scaling.md must match "
        "the namespaces the protocol model extracts from the code"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        doc_path = project.root / SCALING_DOC_RELPATH
        if not doc_path.exists():
            return
        mdl = m.get_model(project)
        extracted = set(mdl.namespaces())
        if not extracted:
            return  # partial fixture layouts: nothing to sync
        text = doc_path.read_text()
        documented: Dict[str, int] = {}
        for lineno, line in enumerate(text.splitlines(), start=1):
            if not line.lstrip().startswith("|"):
                continue
            for match in _DOC_NAMESPACE_RE.finditer(line):
                documented.setdefault(match.group(1), lineno)
        for ns in sorted(extracted - set(documented)):
            yield Finding(
                rule=self.name,
                path=SCALING_DOC_RELPATH,
                line=1,
                message=(
                    f"store namespace '{ns}/' is used in the code but "
                    f"missing from the namespace table in "
                    f"docs/scaling.md (regenerate with "
                    f"python -m tools.snaplint --protocol-dump)"
                ),
            )
        for ns in sorted(set(documented) - extracted):
            yield Finding(
                rule=self.name,
                path=SCALING_DOC_RELPATH,
                line=documented[ns],
                message=(
                    f"namespace table documents '{ns}/' but the "
                    f"protocol model extracts no such namespace — "
                    f"stale row (regenerate with "
                    f"python -m tools.snaplint --protocol-dump)"
                ),
            )
