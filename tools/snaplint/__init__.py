"""snaplint: AST-based concurrency & correctness analysis for the
checkpoint stack.

One shared pass (module loader, scope/taint tracking, rule registry,
inline suppressions, baseline file) with codebase-specific rules — the
structural invariants TorchSnapshot's hardest bugs violate:

- ``collective-under-conditional`` — a dist-store collective reachable
  only under a knob/env/rank guard strands the cross-rank rendezvous
  when the guard's value skews across ranks (the PR 2 SnapshotReport
  gather bug class).
- ``async-blocking-call`` — ``time.sleep`` / no-timeout ``.result()`` /
  subprocess calls inside ``async def`` bodies stall the event loop the
  whole overlapped DtoH/IO scheduler runs on.
- ``span-and-budget-balance`` — a flight-recorder ``begin`` or
  ``MemoryBudget.acquire`` whose matching ``end``/``release`` is not on
  every exception path leaks an open span (false watchdog stalls) or
  budget bytes (pipeline deadlock).
- ``knob-env-literal`` — ``TORCHSNAPSHOT_TPU_*`` env reads outside
  ``knobs.py`` fork the knob surface and dodge the test override
  context managers.
- ``executor-thread-leak`` — a ``ThreadPoolExecutor``/``Thread`` with
  no shutdown/join on exception paths (and no daemon flag) leaks OS
  threads per failed checkpoint.

The pre-existing metric-name, span-name, and tiered-marker checkers are
rules in the same registry (their ``tools/check_*.py`` entry points are
kept as thin shims).

Run over the package::

    python -m tools.snaplint torchsnapshot_tpu

Suppress a single finding with a trailing (or preceding-line) comment::

    risky_call()  # snaplint: disable=collective-under-conditional

Accept existing findings wholesale with a baseline::

    python -m tools.snaplint torchsnapshot_tpu --write-baseline

Exit status is non-zero only on findings not in the baseline.
"""

from .core import (  # noqa: F401
    Analyzer,
    Finding,
    ModuleInfo,
    Project,
    Rule,
    all_rules,
    register,
)
