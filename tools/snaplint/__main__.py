"""CLI driver: ``python -m tools.snaplint [paths...]``.

Exit status 1 only on findings not covered by the baseline file
(default ``tools/snaplint/baseline.json`` when present — the shipped
baseline is empty: the tree is clean and must stay clean).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import (
    Analyzer,
    all_rules,
    load_baseline,
    write_baseline,
)

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.snaplint",
        description=(
            "AST-based concurrency & correctness analysis for the "
            "checkpoint stack"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["torchsnapshot_tpu"],
        help="files/directories to analyze (default: torchsnapshot_tpu)",
    )
    parser.add_argument(
        "--root",
        default=str(REPO_ROOT),
        help="repo root for relative paths (default: this repo)",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule names to run (default: all)",
    )
    parser.add_argument(
        "--disable",
        help="comma-separated rule names to skip",
    )
    parser.add_argument(
        "--baseline",
        default=str(DEFAULT_BASELINE),
        help="baseline JSON of accepted findings "
        "(default: tools/snaplint/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: every finding fails the run",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--protocol",
        action="store_true",
        help="run only the protocol rule family (coordination-plane model)",
    )
    parser.add_argument(
        "--protocol-dump",
        action="store_true",
        help="print the extracted protocol model as JSON and exit "
        "(no rules run)",
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=1,
        metavar="N",
        help="fan the rules out across N forked worker processes "
        "(default: 1, serial)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {cls.description}")
        return 0

    root = Path(args.root)
    select = args.select.split(",") if args.select else None
    if args.protocol:
        from .protocol import PROTOCOL_RULE_NAMES

        select = list(PROTOCOL_RULE_NAMES)
    disable = args.disable.split(",") if args.disable else None
    try:
        analyzer = Analyzer(root=root, select=select, disable=disable)
    except ValueError as e:
        print(f"snaplint: {e}", file=sys.stderr)
        return 2

    paths = [
        (root / p) if not Path(p).is_absolute() else Path(p)
        for p in args.paths
    ]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(
            f"snaplint: no such path(s): "
            f"{', '.join(str(m) for m in missing)}",
            file=sys.stderr,
        )
        return 2

    if args.protocol_dump:
        from .core import load_project
        from .protocol import model as protocol_model

        project = load_project(paths, root)
        print(
            json.dumps(
                protocol_model.get_model(project).as_dict(), indent=2
            )
        )
        return 0

    baseline = (
        [] if args.no_baseline else load_baseline(Path(args.baseline))
    )
    result = analyzer.run(paths, baseline=baseline, jobs=max(1, args.jobs))

    if args.write_baseline:
        write_baseline(Path(args.baseline), result.findings)
        print(
            f"snaplint: wrote {len(result.findings)} finding(s) to "
            f"{args.baseline}"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "new_findings": [
                        f.as_dict() for f in result.new_findings
                    ],
                    "baselined": len(result.findings)
                    - len(result.new_findings),
                    "suppressed": len(result.suppressed),
                },
                indent=2,
            )
        )
        return result.exit_code

    for f in result.new_findings:
        print(f.render())
    baselined = len(result.findings) - len(result.new_findings)
    if result.new_findings:
        print(
            f"snaplint: {len(result.new_findings)} new finding(s) "
            f"({baselined} baselined, {len(result.suppressed)} suppressed)"
        )
    else:
        print(
            f"snaplint: clean — {len(analyzer.rules)} rule(s) over "
            f"{len(result.project.modules)} file(s) "
            f"({baselined} baselined, {len(result.suppressed)} suppressed)"
        )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
