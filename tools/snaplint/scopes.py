"""Scope, guard, and taint helpers shared by the snaplint rules.

The rules reason about three structural questions:

- what function (sync or async) encloses a node,
- which ``if``/``while`` tests guard its reachability, and
- whether an expression's value derives from a knob/env read or from
  the process's rank (one intraprocedural taint fixpoint over simple
  assignments — enough for the repo idiom ``enabled = knobs.is_x()``
  / ``if enabled: ...``).

All of it is conservative and local: taint does not flow across
function boundaries, and guarded *early returns* are not modeled (a
``if knob: return`` above an unconditional collective is the same bug
class but needs a CFG; the rule docs call this out).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# Call targets whose result depends on this process's rank.
_RANK_CALLS = {"get_rank", "process_index"}

# Call targets whose RESULT is rank-uniform by construction: a
# broadcast/agreement collective returns rank 0's (or src's) value on
# every rank, so a guard over it can never skew a later rendezvous —
# even when the broadcast's *argument* was a knob read or rank-local.
# This is the blessed idiom for gating collective work on a knob
# (``if pg.agree_object(knobs.is_x()): ...``); taint never escapes the
# agreement call's subtree.
_AGREEMENT_CALLS = {"broadcast_object", "agree_object", "broadcast"}


def _walk_unlaundered(expr: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that skips subtrees rooted at agreement collectives
    (their results — and therefore their arguments — are laundered)."""
    stack: List[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            chain = call_chain(node)
            if chain and chain[-1] in _AGREEMENT_CALLS:
                continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Iterator[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def enclosing_function(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.AST]:
    """Innermost enclosing function def (or None at module level)."""
    for anc in ancestors(node, parents):
        if isinstance(anc, FunctionNode):
            return anc
    return None


def attr_chain(expr: ast.AST) -> List[str]:
    """``os.environ.get`` -> ["os", "environ", "get"]; empty when the
    expression roots in something other than a plain name (a call's
    result, a subscript, ...)."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return list(reversed(parts))
    return []


def call_chain(call: ast.Call) -> List[str]:
    return attr_chain(call.func)


def guard_tests(
    node: ast.AST,
    parents: Dict[ast.AST, ast.AST],
    stop_at: Optional[ast.AST] = None,
) -> List[Tuple[ast.expr, ast.AST]]:
    """The (test expression, guard node) pairs controlling ``node``'s
    reachability, innermost first, up to ``stop_at`` (typically the
    enclosing function). Both branches of an ``if`` count: the else
    branch of a knob guard is exactly as knob-dependent as the body."""
    out: List[Tuple[ast.expr, ast.AST]] = []
    child = node
    for anc in ancestors(node, parents):
        if anc is stop_at or isinstance(anc, FunctionNode):
            break
        if isinstance(anc, (ast.If, ast.While)) and child is not anc.test:
            out.append((anc.test, anc))
        elif isinstance(anc, ast.IfExp) and child is not anc.test:
            out.append((anc.test, anc))
        child = anc
    return out


def knob_import_names(tree: ast.Module) -> Set[str]:
    """Names imported from a ``knobs`` module (``from .knobs import
    is_batching_enabled``): calls to them are knob taint sources even
    without the ``knobs.`` prefix."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "knobs" or node.module.endswith(".knobs"):
                names.update(a.asname or a.name for a in node.names)
    return names


def _expr_has_env_read(expr: ast.AST) -> bool:
    for node in _walk_unlaundered(expr):
        chain = []
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
        elif isinstance(node, ast.Name):
            chain = [node.id]
        elif isinstance(node, ast.Call):
            chain = call_chain(node)
        if "environ" in chain or "getenv" in chain:
            return True
    return False


def expr_knob_tainted(
    expr: ast.AST,
    tainted: Optional[Set[str]] = None,
    knob_names: Optional[Set[str]] = None,
) -> bool:
    """Does ``expr`` derive from a knob accessor or an env read?"""
    tainted = tainted or set()
    knob_names = knob_names or set()
    if _expr_has_env_read(expr):
        return True
    for node in _walk_unlaundered(expr):
        if isinstance(node, ast.Call):
            chain = call_chain(node)
            if "knobs" in chain:
                return True
            if len(chain) == 1 and chain[0] in knob_names:
                return True
        elif isinstance(node, ast.Name) and node.id in tainted:
            return True
    return False


def expr_rank_tainted(
    expr: ast.AST, tainted: Optional[Set[str]] = None
) -> bool:
    """Does ``expr`` depend on this process's rank? Matches terminal
    identifiers named/containing ``rank`` (``rank``, ``self.rank``,
    ``local_rank``) and rank-returning calls (``get_rank()``,
    ``jax.process_index()``)."""
    tainted = tainted or set()
    for node in _walk_unlaundered(expr):
        terminal = None
        if isinstance(node, ast.Call):
            chain = call_chain(node)
            terminal = chain[-1] if chain else None
            if terminal in _RANK_CALLS:
                return True
        elif isinstance(node, ast.Attribute):
            terminal = node.attr
        elif isinstance(node, ast.Name):
            terminal = node.id
            if terminal in tainted:
                return True
        if terminal is not None and "rank" in terminal.lower():
            return True
    return False


def _assign_targets(node: ast.AST) -> List[str]:
    targets: List[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) and node.value:
        targets = [node.target]
    elif isinstance(node, ast.NamedExpr):
        targets = [node.target]
    return [t.id for t in targets if isinstance(t, ast.Name)]


def tainted_names(
    scope: ast.AST,
    knob_names: Optional[Set[str]] = None,
) -> Tuple[Set[str], Set[str]]:
    """(knob-tainted, rank-tainted) local names in ``scope`` (a function
    or module node): a small fixpoint over simple assignments so
    ``a = knobs.is_x(); b = a; if b: ...`` still classifies."""
    knob_taint: Set[str] = set()
    rank_taint: Set[str] = set()
    assigns = [
        n
        for n in ast.walk(scope)
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.NamedExpr))
        and getattr(n, "value", None) is not None
    ]
    changed = True
    while changed:
        changed = False
        for node in assigns:
            names = _assign_targets(node)
            if not names:
                continue
            if expr_knob_tainted(node.value, knob_taint, knob_names):
                new = set(names) - knob_taint
                if new:
                    knob_taint.update(new)
                    changed = True
            if expr_rank_tainted(node.value, rank_taint):
                new = set(names) - rank_taint
                if new:
                    rank_taint.update(new)
                    changed = True
    return knob_taint, rank_taint


def in_finally(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    """Is ``node`` inside some ``try``'s ``finally`` suite?"""
    child = node
    for anc in ancestors(node, parents):
        if isinstance(anc, (ast.Try,)) and _in_block(child, anc.finalbody):
            return True
        if isinstance(anc, FunctionNode):
            return False
        child = anc
    return False


def in_except_handler(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> bool:
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.ExceptHandler):
            return True
        if isinstance(anc, FunctionNode):
            return False
    return False


def _in_block(node: ast.AST, block: List[ast.stmt]) -> bool:
    for stmt in block:
        if node is stmt or any(node is d for d in ast.walk(stmt)):
            return True
    return False


def with_context_exprs(node: ast.With) -> List[ast.expr]:
    return [item.context_expr for item in node.items]
