"""snaplint framework: findings, rule registry, module loader,
suppressions, baseline, and the analyzer driver.

Everything is stdlib-only (``ast`` + ``json``) so the analyzer runs in
any lane — including ones where jax itself cannot import.
"""

from __future__ import annotations

import ast
import json
import re
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Type

# A suppression names the rule(s) it silences on its own line or the
# line directly above the finding:  # snaplint: disable=rule-a,rule-b
_SUPPRESS_RE = re.compile(r"#\s*snaplint:\s*disable=([A-Za-z0-9_\-, ]+)")

# Messages may reference other lines ("guard (line 42)", "first at line
# 17"); those drift with unrelated edits just like the finding's own
# line, so they are normalized out of the baseline key.
_LINE_REF_RE = re.compile(r"\bline \d+\b")

PARSE_ERROR_RULE = "parse-error"


@dataclass(frozen=True)
class Finding:
    """One rule violation at one location."""

    rule: str
    path: str  # repo-root-relative POSIX path when possible
    line: int
    message: str
    col: int = 0

    def key(self) -> str:
        """Baseline identity: line numbers — the finding's own AND any
        referenced in the message — are excluded so unrelated edits
        above a grandfathered finding don't churn the baseline."""
        normalized = _LINE_REF_RE.sub("line _", self.message)
        return f"{self.rule}::{self.path}::{normalized}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def as_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class ModuleInfo:
    """A parsed source file shared by every rule (one parse per file)."""

    path: Path
    relpath: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    _parents: Optional[dict] = field(default=None, repr=False)

    @property
    def parents(self) -> dict:
        """Child -> parent AST map, built once and shared by every rule
        (four structural rules walking 69 files must not each rebuild
        it)."""
        if self._parents is None:
            from . import scopes

            self._parents = scopes.parent_map(self.tree)
        return self._parents

    @classmethod
    def load(cls, path: Path, root: Path) -> "ModuleInfo":
        source = path.read_text()
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        tree = ast.parse(source, filename=str(path))
        return cls(
            path=path,
            relpath=rel,
            source=source,
            tree=tree,
            lines=source.splitlines(),
        )

    def suppressed_rules(self, line: int) -> set:
        """Rules disabled at 1-indexed ``line`` (same line or the line
        above)."""
        out: set = set()
        for lineno in (line, line - 1):
            if 1 <= lineno <= len(self.lines):
                m = _SUPPRESS_RE.search(self.lines[lineno - 1])
                if m:
                    out.update(
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    )
        return out


@dataclass
class Project:
    """What a rule sees: the repo root plus every loaded module."""

    root: Path
    modules: List[ModuleInfo]
    parse_errors: List[Finding] = field(default_factory=list)

    def module(self, relpath: str) -> Optional[ModuleInfo]:
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


class Rule:
    """Base rule. Subclasses set ``name``/``description`` and override
    ``check_module`` (called once per file) and/or ``check_project``
    (called once per run, for cross-file invariants)."""

    name: str = ""
    description: str = ""

    def check_module(
        self, module: ModuleInfo, project: Project
    ) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(rule_cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule to the global registry."""
    if not rule_cls.name:
        raise ValueError(f"{rule_cls.__name__} has no rule name")
    if rule_cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {rule_cls.name!r}")
    _REGISTRY[rule_cls.name] = rule_cls
    return rule_cls


def all_rules() -> Dict[str, Type[Rule]]:
    # Importing the rules package populates the registry exactly once.
    from . import rules  # noqa: F401

    return dict(_REGISTRY)


# One parsed-AST cache per process, shared by every consumer that loads
# modules — the project loader, the analyzer's suppression side-loads,
# the names-lint disk fallback, and the protocol model's package sweep.
# Keyed by (resolved path, root), validated by (mtime_ns, size) so an
# edited file re-parses while a 13-rule run over 100+ files parses each
# file exactly once.
_MODULE_CACHE: Dict[tuple, tuple] = {}


def load_module_cached(path: Path, root: Path) -> ModuleInfo:
    resolved = Path(path).resolve()
    stat = resolved.stat()
    cache_key = (str(resolved), str(Path(root).resolve()))
    stamp = (stat.st_mtime_ns, stat.st_size)
    hit = _MODULE_CACHE.get(cache_key)
    if hit is not None and hit[0] == stamp:
        return hit[1]
    module = ModuleInfo.load(path, root)
    _MODULE_CACHE[cache_key] = (stamp, module)
    return module


def load_project(paths: Sequence[Path], root: Path) -> Project:
    """Parse every ``.py`` under ``paths`` once; syntax errors become
    ``parse-error`` findings rather than aborting the run."""
    files: List[Path] = []
    seen: set = set()
    for p in paths:
        p = Path(p)
        candidates: List[Path]
        if p.is_dir():
            candidates = [
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            ]
        elif p.suffix == ".py":
            candidates = [p]
        else:
            candidates = []
        for f in candidates:
            resolved = f.resolve()
            if resolved not in seen:  # overlapping args load a file once
                seen.add(resolved)
                files.append(f)
    modules: List[ModuleInfo] = []
    parse_errors: List[Finding] = []
    for f in files:
        try:
            modules.append(load_module_cached(f, root))
        except SyntaxError as e:
            try:
                rel = f.resolve().relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            parse_errors.append(
                Finding(
                    rule=PARSE_ERROR_RULE,
                    path=rel,
                    line=e.lineno or 1,
                    message=f"syntax error: {e.msg}",
                )
            )
    return Project(root=root, modules=modules, parse_errors=parse_errors)


def load_baseline(path: Optional[Path]) -> List[str]:
    if path is None or not Path(path).exists():
        return []
    data = json.loads(Path(path).read_text())
    return list(data.get("findings", []))


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    # Duplicates are kept on purpose: the baseline is a multiset, so a
    # grandfathered finding excuses exactly ONE occurrence of its key —
    # a new identical violation in the same file still fails the run.
    payload = {
        "version": 1,
        "findings": sorted(f.key() for f in findings),
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


class Analyzer:
    """Load → run rules → suppress → baseline-filter."""

    def __init__(
        self,
        root: Path,
        select: Optional[Sequence[str]] = None,
        disable: Optional[Sequence[str]] = None,
    ) -> None:
        self.root = Path(root)
        rules = all_rules()
        unknown = [
            r for r in list(select or []) + list(disable or []) if r not in rules
        ]
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
        names = list(select) if select else list(rules)
        names = [n for n in names if n not in set(disable or ())]
        self.rules: List[Rule] = [rules[n]() for n in names]

    def run(
        self,
        paths: Sequence[Path],
        baseline: Optional[Sequence[str]] = None,
        jobs: int = 1,
    ) -> "RunResult":
        project = load_project(paths, self.root)
        raw: List[Finding] = list(project.parse_errors)
        raw.extend(self._run_rules(project, jobs))
        raw.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

        kept: List[Finding] = []
        suppressed: List[Finding] = []
        side_loaded: Dict[str, Optional[ModuleInfo]] = {}
        for f in raw:
            module = project.module(f.path)
            if module is None:
                # Project-level rules (the names/marker lints) can
                # report on files outside the scanned paths; load those
                # on demand so their inline suppressions still apply.
                if f.path not in side_loaded:
                    candidate = self.root / f.path
                    try:
                        side_loaded[f.path] = load_module_cached(
                            candidate, self.root
                        )
                    except (OSError, SyntaxError):
                        side_loaded[f.path] = None
                module = side_loaded[f.path]
            rules_off = (
                module.suppressed_rules(f.line) if module is not None else set()
            )
            if f.rule in rules_off or "all" in rules_off:
                suppressed.append(f)
            else:
                kept.append(f)

        # Multiset matching: each baseline entry excuses one occurrence
        # of its key, so a second identical violation in the same file
        # is NOT masked by a single grandfathered entry.
        allowance = Counter(baseline or ())
        new: List[Finding] = []
        for f in kept:
            key = f.key()
            if allowance[key] > 0:
                allowance[key] -= 1
            else:
                new.append(f)
        return RunResult(
            findings=kept,
            new_findings=new,
            suppressed=suppressed,
            project=project,
        )

    def _run_rules(self, project: Project, jobs: int) -> List[Finding]:
        """Run every selected rule over the loaded project, optionally
        fanning the *rules* out across ``jobs`` forked workers. Findings
        are identical to the serial path by construction: the same rule
        set runs over the same shared trees, and the caller sorts the
        merged list with the same key either way."""
        rule_names = [r.name for r in self.rules]
        if jobs > 1 and len(rule_names) > 1:
            chunks = [rule_names[i::jobs] for i in range(jobs)]
            chunks = [c for c in chunks if c]
            try:
                import multiprocessing as mp

                # fork is what makes this cheap: workers inherit the
                # parsed project copy-on-write instead of re-parsing or
                # pickling ASTs. Elsewhere (spawn-only platforms), fall
                # back to serial rather than pay a slower parallel path.
                ctx = mp.get_context("fork")
                global _WORKER_PROJECT
                _WORKER_PROJECT = project
                try:
                    with ctx.Pool(processes=len(chunks)) as pool:
                        parts = pool.map(_run_rule_chunk, chunks)
                finally:
                    _WORKER_PROJECT = None
                return [f for part in parts for f in part]
            except (ImportError, ValueError, OSError):
                pass
        findings: List[Finding] = []
        for rule in self.rules:
            for module in project.modules:
                findings.extend(rule.check_module(module, project))
            findings.extend(rule.check_project(project))
        return findings


_WORKER_PROJECT: Optional[Project] = None


def _run_rule_chunk(rule_names: Sequence[str]) -> List["Finding"]:
    """Worker body for ``--jobs``: run a subset of rules over the
    fork-inherited project."""
    project = _WORKER_PROJECT
    assert project is not None
    rules = all_rules()
    findings: List[Finding] = []
    for name in rule_names:
        rule = rules[name]()
        for module in project.modules:
            findings.extend(rule.check_module(module, project))
        findings.extend(rule.check_project(project))
    return findings


@dataclass
class RunResult:
    findings: List[Finding]  # after suppression, before baseline
    new_findings: List[Finding]  # after baseline filter: these fail the run
    suppressed: List[Finding]
    project: Project

    @property
    def exit_code(self) -> int:
        return 1 if self.new_findings else 0
