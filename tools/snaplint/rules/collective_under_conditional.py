"""collective-under-conditional: a dist-store collective reachable only
under a knob/env/rank guard.

Every collective (``gather``/``exchange``/``broadcast``/``scatter``/
``barrier``/``arrive``/``depart``/PGWrapper object collectives) is a
cross-rank rendezvous: EVERY rank must reach it or the participants
poll out the full store timeout. A knob or env guard can skew across
ranks (one worker restarted with a different environment), and a rank
guard around a collective is wrong by construction — so any such call
whose reachability depends on one is flagged. This is the PR 2 bug
class: a knob-gated SnapshotReport gather stranded the rendezvous until
the gather was made unconditional.

Not modeled (see docs/static-analysis.md): a guarded *early return*
above an unconditional collective (same bug, needs a CFG), and guards
whose skew is provably uniform (``world_size > 1`` is fine and is not
flagged — world size is not rank/knob taint).

Laundered taint: the result of an agreement collective
(``broadcast_object`` / ``agree_object`` / ``broadcast``) is
rank-uniform by construction — every rank gets rank 0's (or src's)
value — so guards over it cannot skew, even when the broadcast's
argument was a knob read. ``if pg.agree_object(knobs.is_x()): ...`` is
the blessed idiom for knob-gating collective work (the fan-out restore
path's owner-election/broadcast code rides it) and is not flagged.

The modules that *implement* the collectives (``dist_store.py``,
``pg_wrapper.py``) are exempt: rank-conditional key traffic inside a
collective's own implementation is its protocol, not a bug.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, ModuleInfo, Project, Rule, register
from .. import scopes

COLLECTIVE_METHODS = {
    "gather",
    "exchange",
    "broadcast",
    "scatter",
    "barrier",
    "arrive",
    "depart",
    "all_gather_object",
    "broadcast_object",
    "gather_object",
    "scatter_object",
}

# Receivers whose same-named methods are NOT cross-rank collectives.
_NON_COLLECTIVE_ROOTS = {"asyncio", "mp", "multiprocessing", "np", "numpy"}

EXEMPT_SUFFIXES = (
    "torchsnapshot_tpu/dist_store.py",
    "torchsnapshot_tpu/pg_wrapper.py",
)


@register
class CollectiveUnderConditional(Rule):
    name = "collective-under-conditional"
    description = (
        "dist-store collective reachable only under a knob/env/rank guard "
        "(cross-rank rendezvous can strand when the guard skews)"
    )

    def check_module(
        self, module: ModuleInfo, project: Project
    ) -> Iterable[Finding]:
        if module.relpath.endswith(EXEMPT_SUFFIXES):
            return
        parents = module.parents
        knob_names = scopes.knob_import_names(module.tree)
        taint_cache = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                not isinstance(func, ast.Attribute)
                or func.attr not in COLLECTIVE_METHODS
            ):
                continue
            chain = scopes.attr_chain(func)
            if chain and chain[0] in _NON_COLLECTIVE_ROOTS:
                continue
            fn = scopes.enclosing_function(node, parents)
            scope = fn if fn is not None else module.tree
            if scope not in taint_cache:
                taint_cache[scope] = scopes.tainted_names(scope, knob_names)
            knob_taint, rank_taint = taint_cache[scope]
            for test, guard in scopes.guard_tests(node, parents, stop_at=fn):
                kinds = []
                if scopes.expr_knob_tainted(test, knob_taint, knob_names):
                    kinds.append("knob/env")
                if scopes.expr_rank_tainted(test, rank_taint):
                    kinds.append("rank")
                if kinds:
                    recv = ".".join(chain) if chain else f"<expr>.{func.attr}"
                    yield Finding(
                        rule=self.name,
                        path=module.relpath,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"collective {recv}() is reachable only under a "
                            f"{'/'.join(kinds)}-dependent guard (line "
                            f"{guard.lineno}); a skewed guard strands the "
                            f"cross-rank rendezvous — make the collective "
                            f"unconditional or gate only its payload"
                        ),
                    )
                    break  # one finding per call is enough
