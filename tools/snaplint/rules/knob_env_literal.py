"""knob-env-literal: ``TORCHSNAPSHOT_TPU_*`` env reads outside knobs.py.

``knobs.py`` is the single home for the knob surface: lazy re-reads,
documented defaults, and the ``override_*`` context managers tests rely
on. An env read elsewhere forks that surface — the knob works in
production but silently ignores the test override (or vice versa), and
renames miss it. Flags ``os.environ[...]`` / ``.get`` / ``in
os.environ`` / ``os.getenv`` whose key is a ``TORCHSNAPSHOT_TPU_``
literal or a module-level constant bound to one.

The rule also covers the tuner's programmatic override layer: TUNABLE
knobs resolve env > ``knobs.set_tuner_override`` > default, so an env
read keyed by one of knobs.py's ``_*_ENV`` name constants (e.g.
``os.environ.get(knobs._STAGING_THREADS_ENV)``) outside knobs.py is
flagged too — it would read the env half of the chain and silently
ignore an applied autotuner value. Go through the knob's getter.

Writes (``os.environ[...] = ...``) are not flagged: the override
context managers in conftest-adjacent code legitimately set knob vars
for subprocesses.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from ..core import Finding, ModuleInfo, Project, Rule, register
from .. import scopes

PREFIX = "TORCHSNAPSHOT_TPU_"
_ENV_READ_METHODS = {"get", "pop", "setdefault", "__contains__"}
_ENV_CONST_SUFFIX = "_ENV"


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ):
            if isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.value
    return out


def _key_value(expr: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        return consts.get(expr.id)
    return None


def _knobs_env_imports(tree: ast.Module) -> Set[str]:
    """Names bound by ``from ...knobs import _X_ENV``-style imports:
    knob env-var name constants reachable as bare names."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and (node.module or "").endswith(
            "knobs"
        ):
            for alias in node.names:
                if alias.name.endswith(_ENV_CONST_SUFFIX):
                    out.add(alias.asname or alias.name)
    return out


def _knobs_module_aliases(tree: ast.Module) -> Set[str]:
    """Every local name a knobs module is reachable under:
    ``import ...knobs [as k]`` and ``from ... import knobs [as k]`` —
    an aliased import must not slip env-constant reads past the rule."""
    out: Set[str] = {"knobs"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "knobs" or alias.name.endswith(".knobs"):
                    out.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "knobs":
                    out.add(alias.asname or alias.name)
    return out


def _knobs_const_ref(
    expr: ast.AST, imported_env_names: Set[str], module_aliases: Set[str]
) -> Optional[str]:
    """A reference to one of knobs.py's ``_*_ENV`` name constants used
    as an env-read key: ``<knobs-alias>._X_ENV`` (any name the knobs
    module was imported under) or a bare name imported from a knobs
    module. Returns a display string for the message, None otherwise."""
    if isinstance(expr, ast.Attribute) and expr.attr.endswith(
        _ENV_CONST_SUFFIX
    ):
        chain = scopes.attr_chain(expr)
        if chain and (
            chain[-2:-1] == ["knobs"] or chain[0] in module_aliases
        ):
            return ".".join(chain)
    if isinstance(expr, ast.Name) and expr.id in imported_env_names:
        return expr.id
    return None


def _is_environ(expr: ast.AST) -> bool:
    chain = scopes.attr_chain(expr)
    return bool(chain) and chain[-1] == "environ"


@register
class KnobEnvLiteral(Rule):
    name = "knob-env-literal"
    description = (
        "TORCHSNAPSHOT_TPU_* env read outside knobs.py forks the knob "
        "surface (defaults, overrides, docs)"
    )

    def check_module(
        self, module: ModuleInfo, project: Project
    ) -> Iterable[Finding]:
        if module.relpath.endswith("knobs.py"):
            return
        consts = _module_str_constants(module.tree)
        imported_env_names = _knobs_env_imports(module.tree)
        module_aliases = _knobs_module_aliases(module.tree)
        for node in ast.walk(module.tree):
            key = None
            key_expr = None
            if isinstance(node, ast.Call):
                chain = scopes.call_chain(node)
                if chain and chain[-1] == "getenv" and node.args:
                    key_expr = node.args[0]
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ENV_READ_METHODS
                    and _is_environ(node.func.value)
                    and node.args
                ):
                    key_expr = node.args[0]
            elif isinstance(node, ast.Subscript) and _is_environ(node.value):
                # Reads only: a Store assignment target has ctx=Store.
                if isinstance(node.ctx, ast.Load):
                    key_expr = node.slice
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(
                    node.ops[0], (ast.In, ast.NotIn)
                ) and _is_environ(node.comparators[0]):
                    key_expr = node.left
            if key_expr is not None:
                key = _key_value(key_expr, consts)
            if key is not None and key.startswith(PREFIX):
                yield Finding(
                    rule=self.name,
                    path=module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"env var {key!r} read outside knobs.py — add a "
                        f"knobs.py accessor (plus override context "
                        f"manager) and call that instead"
                    ),
                )
            elif key_expr is not None:
                const_ref = _knobs_const_ref(
                    key_expr, imported_env_names, module_aliases
                )
                if const_ref is not None:
                    yield Finding(
                        rule=self.name,
                        path=module.relpath,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"env read keyed by knobs constant "
                            f"{const_ref} bypasses the tuner override "
                            f"layer (env > override > default) — call "
                            f"the knob's override-aware getter instead"
                        ),
                    )
