"""knob-env-literal: ``TORCHSNAPSHOT_TPU_*`` env reads outside knobs.py.

``knobs.py`` is the single home for the knob surface: lazy re-reads,
documented defaults, and the ``override_*`` context managers tests rely
on. An env read elsewhere forks that surface — the knob works in
production but silently ignores the test override (or vice versa), and
renames miss it. Flags ``os.environ[...]`` / ``.get`` / ``in
os.environ`` / ``os.getenv`` whose key is a ``TORCHSNAPSHOT_TPU_``
literal or a module-level constant bound to one.

Writes (``os.environ[...] = ...``) are not flagged: the override
context managers in conftest-adjacent code legitimately set knob vars
for subprocesses.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional

from ..core import Finding, ModuleInfo, Project, Rule, register
from .. import scopes

PREFIX = "TORCHSNAPSHOT_TPU_"
_ENV_READ_METHODS = {"get", "pop", "setdefault", "__contains__"}


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ):
            if isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.value
    return out


def _key_value(expr: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        return consts.get(expr.id)
    return None


def _is_environ(expr: ast.AST) -> bool:
    chain = scopes.attr_chain(expr)
    return bool(chain) and chain[-1] == "environ"


@register
class KnobEnvLiteral(Rule):
    name = "knob-env-literal"
    description = (
        "TORCHSNAPSHOT_TPU_* env read outside knobs.py forks the knob "
        "surface (defaults, overrides, docs)"
    )

    def check_module(
        self, module: ModuleInfo, project: Project
    ) -> Iterable[Finding]:
        if module.relpath.endswith("knobs.py"):
            return
        consts = _module_str_constants(module.tree)
        for node in ast.walk(module.tree):
            key = None
            if isinstance(node, ast.Call):
                chain = scopes.call_chain(node)
                if chain and chain[-1] == "getenv" and node.args:
                    key = _key_value(node.args[0], consts)
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ENV_READ_METHODS
                    and _is_environ(node.func.value)
                    and node.args
                ):
                    key = _key_value(node.args[0], consts)
            elif isinstance(node, ast.Subscript) and _is_environ(node.value):
                # Reads only: a Store assignment target has ctx=Store.
                if isinstance(node.ctx, ast.Load):
                    key = _key_value(node.slice, consts)
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(
                    node.ops[0], (ast.In, ast.NotIn)
                ) and _is_environ(node.comparators[0]):
                    key = _key_value(node.left, consts)
            if key is not None and key.startswith(PREFIX):
                yield Finding(
                    rule=self.name,
                    path=module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"env var {key!r} read outside knobs.py — add a "
                        f"knobs.py accessor (plus override context "
                        f"manager) and call that instead"
                    ),
                )
