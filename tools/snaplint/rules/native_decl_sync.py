"""native-decl-sync: the ctypes declarations in ``_native._declare``
and the C ABI surface of ``native/ts_io.cpp`` must name the same set of
symbols.

A symbol declared on the Python side but missing from the shared
library is a runtime segfault (ctypes resolves lazily — the first
foreign call dies, not the import); a symbol exported from C but never
declared is unusable drift that the next declaration typo can silently
shadow. Neither is a thing a test suite reliably catches (the native
lib may be unbuildable in CI), so the sync is a lint: pure text/AST,
no compiler needed.

Convention: every C-ABI function in ts_io.cpp carries the ``ts_``
prefix (helpers live in anonymous namespaces without it), and
``_declare`` assigns ``l.<symbol>.argtypes`` / ``.restype`` for each.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List

from ..core import Finding, Project, Rule, register

NATIVE_PY_RELPATH = "torchsnapshot_tpu/_native.py"
TS_IO_CPP_RELPATH = "torchsnapshot_tpu/native/ts_io.cpp"

# A C function *definition* line: one-or-more type tokens, then the
# ts_-prefixed name, then the parameter list opener. Calls never match
# (they don't start a line with a type), and helpers lack the prefix.
_CPP_DEF_RE = re.compile(
    r"(?m)^\s*(?:[A-Za-z_][A-Za-z0-9_]*\s+)+\**\s*(ts_[A-Za-z0-9_]*)\s*\("
)


def declared_symbols(native_py_source: str) -> Dict[str, int]:
    """``ts_*`` symbols the ``_declare`` function binds (name -> line),
    from ``l.<name>.argtypes`` / ``l.<name>.restype`` assignments."""
    tree = ast.parse(native_py_source)
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef) and node.name == "_declare"):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Attribute):
                continue
            # l.<name>.argtypes — the inner Attribute is l.<name>.
            if sub.attr in ("argtypes", "restype") and isinstance(
                sub.value, ast.Attribute
            ):
                name = sub.value.attr
                if name.startswith("ts_") and name not in out:
                    out[name] = sub.lineno
    return out


def exported_symbols(cpp_source: str) -> Dict[str, int]:
    """``ts_*`` function definitions in the C++ source (name -> line)."""
    out: Dict[str, int] = {}
    for m in _CPP_DEF_RE.finditer(cpp_source):
        name = m.group(1)
        if name not in out:
            out[name] = cpp_source.count("\n", 0, m.start()) + 1
    return out


def check(native_py: Path, ts_io_cpp: Path) -> List[str]:
    """Mismatch messages (empty = in sync); the shared implementation
    the Rule below and the tests drive."""
    errors: List[str] = []
    if not native_py.exists():
        return [f"{native_py.name}: missing (ctypes declarations live here)"]
    if not ts_io_cpp.exists():
        return [f"{ts_io_cpp.name}: missing (the C ABI surface lives here)"]
    declared = declared_symbols(native_py.read_text())
    exported = exported_symbols(ts_io_cpp.read_text())
    for name in sorted(set(declared) - set(exported)):
        errors.append(
            f"{native_py.name}:{declared[name]}: {name} is declared in "
            f"_declare but not defined in {ts_io_cpp.name} — the first "
            f"foreign call would segfault at runtime"
        )
    for name in sorted(set(exported) - set(declared)):
        errors.append(
            f"{ts_io_cpp.name}:{exported[name]}: {name} is exported from "
            f"the C ABI but never declared in _declare — unusable, and "
            f"drift the next signature change can hide behind"
        )
    return errors


@register
class NativeDeclSync(Rule):
    name = "native-decl-sync"
    description = (
        "every symbol _native._declare binds exists in ts_io.cpp's C ABI "
        "and vice versa (a drifted signature is a segfault, not a lint)"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        native_py = project.root / NATIVE_PY_RELPATH
        if not (project.root / "torchsnapshot_tpu").is_dir():
            return ()  # fixture run outside the real repo layout
        for err in check(native_py, project.root / TS_IO_CPP_RELPATH):
            path, line = NATIVE_PY_RELPATH, 1
            m = re.match(r"^([^:]+):(\d+): ", err)
            msg = err
            if m:
                base = m.group(1)
                line = int(m.group(2))
                msg = err[m.end():]
                if base.endswith(".cpp"):
                    path = TS_IO_CPP_RELPATH
            elif ": " in err:
                msg = err.split(": ", 1)[1]
            yield Finding(rule=self.name, path=path, line=line, message=msg)
