"""span-and-budget-balance: recorder ``begin`` / ``MemoryBudget.acquire``
must be released on every exception path.

An unclosed flight-recorder span poisons everything downstream of the
ring: the Chrome export produces crossed B/E stacks, and the stall
watchdog attributes a permanent false stall to the leaked span. Leaked
budget bytes are worse — ``MemoryBudget`` admission waits forever on
capacity that will never be released, deadlocking the next pipeline.

Accepted as balanced, for a local ``tok = <recorder>.begin(...)``:

- some ``<recorder>.end(tok)`` sits in a ``finally`` suite, or
- ``end(tok)`` appears both in an ``except`` handler and on the normal
  path (the scheduler's stage/except/re-raise idiom).

``with recorder.span(...)`` needs no analysis (the context manager is
the fix this rule pushes toward). Begin tokens stored on ``self`` are
exempt: their lifecycle belongs to the owning object (e.g.
``trace_annotation.__enter__``/``__exit__``).

For budgets: a function that both ``acquire``s and ``release``s the
same budget receiver must have a release in a ``finally``/``except``
suite. Acquire-only functions are exempt (ownership transfer to a
completion task is the pipeline's design).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from ..core import Finding, ModuleInfo, Project, Rule, register
from .. import scopes


def _receiver_key(func: ast.Attribute) -> Tuple[str, ...]:
    """Identity of the thing being acquired/released: the attr chain of
    the receiver (``self.budget.acquire`` -> ("self", "budget"))."""
    return tuple(scopes.attr_chain(func.value))


def _is_budget_receiver(key: Tuple[str, ...]) -> bool:
    return bool(key) and "budget" in key[-1].lower()


@register
class SpanBudgetBalance(Rule):
    name = "span-and-budget-balance"
    description = (
        "flight-recorder begin / MemoryBudget.acquire without a "
        "try/finally (or except+normal-path) release"
    )

    def check_module(
        self, module: ModuleInfo, project: Project
    ) -> Iterable[Finding]:
        parents = module.parents
        functions = [
            n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for fn in functions:
            yield from self._check_spans(module, fn, parents)
            yield from self._check_budget(module, fn, parents)

    # -- spans -----------------------------------------------------------

    def _check_spans(self, module, fn, parents) -> Iterable[Finding]:
        # begin() assignments to plain names, owned by THIS function
        # (nested defs analyze separately).
        begins: List[Tuple[str, ast.Call]] = []
        ends: Dict[str, List[ast.Call]] = {}
        for node in ast.walk(fn):
            if scopes.enclosing_function(node, parents) is not fn:
                continue
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                call = node.value
                if (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr == "begin"
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    begins.append((node.targets[0].id, call))
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "end"
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                ends.setdefault(node.args[0].id, []).append(node)
        for name, call in begins:
            end_calls = ends.get(name, [])
            if not end_calls:
                yield Finding(
                    rule=self.name,
                    path=module.relpath,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        f"span {name!r} begun in {fn.name}() is never "
                        f"end()ed in this function; an exception leaks an "
                        f"open span (false watchdog stalls, crossed trace "
                        f"stacks) — close it in a try/finally"
                    ),
                )
                continue
            in_fin = any(scopes.in_finally(e, parents) for e in end_calls)
            in_exc = any(
                scopes.in_except_handler(e, parents) for e in end_calls
            )
            on_normal = any(
                not scopes.in_except_handler(e, parents) for e in end_calls
            )
            if not (in_fin or (in_exc and on_normal)):
                yield Finding(
                    rule=self.name,
                    path=module.relpath,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        f"span {name!r} begun in {fn.name}() is end()ed "
                        f"only on the normal path; wrap the end() in a "
                        f"try/finally so exception paths close it too"
                    ),
                )

    # -- budget ----------------------------------------------------------

    def _check_budget(self, module, fn, parents) -> Iterable[Finding]:
        acquires: Dict[Tuple[str, ...], List[ast.Call]] = {}
        releases: Dict[Tuple[str, ...], List[ast.Call]] = {}
        for node in ast.walk(fn):
            if scopes.enclosing_function(node, parents) is not fn:
                continue
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            key = _receiver_key(node.func)
            if not _is_budget_receiver(key):
                continue
            if node.func.attr == "acquire":
                acquires.setdefault(key, []).append(node)
            elif node.func.attr == "release":
                releases.setdefault(key, []).append(node)
        for key, acq in acquires.items():
            rel = releases.get(key, [])
            if not rel:
                continue  # ownership transfer: release lives elsewhere
            protected = any(
                scopes.in_finally(r, parents)
                or scopes.in_except_handler(r, parents)
                for r in rel
            )
            if not protected:
                recv = ".".join(key)
                yield Finding(
                    rule=self.name,
                    path=module.relpath,
                    line=acq[0].lineno,
                    col=acq[0].col_offset,
                    message=(
                        f"{recv}.acquire() in {fn.name}() has releases "
                        f"only on the normal path; an exception leaks "
                        f"budget bytes and deadlocks later admission — "
                        f"release in a try/finally"
                    ),
                )
