"""tiered-test-markers: the PR 1 marker-lane checker as a snaplint rule.

The tiered crash-consistency and latency properties are tier-1 signal:
they must be collected in the default ``-m 'not slow'`` lane, while the
end-to-end mirror sweep stays out of it. The ``check`` function here is
the original from ``tools/check_tiered_markers.py`` (now a thin shim
over this module).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List

from ..core import Finding, Project, Rule, register

TIERED_TESTS_RELPATH = "tests/test_tiered.py"


def _has_slow_marker(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if (
            isinstance(target, ast.Attribute)
            and target.attr == "slow"
            and isinstance(target.value, ast.Attribute)
            and target.value.attr == "mark"
        ):
            return True
    return False


def check(path: Path) -> List[str]:
    errors = []
    if not path.exists():
        return [f"{path.name}: missing (tiered tests are tier-1 signal)"]
    tree = ast.parse(path.read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            errors.append(
                f"{path.name}: module-level pytestmark would reshape the "
                f"tier-1 lane; mark individual tests instead"
            )
    tests = [
        n
        for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name.startswith("test_")
    ]
    if not tests:
        errors.append(f"{path.name}: no test functions found")
    fast = [t for t in tests if not _has_slow_marker(t)]
    if not fast:
        errors.append(
            f"{path.name}: every test is marked slow — nothing reaches the "
            f"default -m 'not slow' lane"
        )
    for t in tests:
        if "end_to_end" in t.name and not _has_slow_marker(t):
            errors.append(
                f"{path.name}: {t.name} is end-to-end but not marked slow"
            )
    return errors


@register
class TieredTestMarkers(Rule):
    name = "tiered-test-markers"
    description = (
        "tiered tests stay lane-correct: fast-lane tests present, "
        "end-to-end sweeps marked slow"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        path = project.root / TIERED_TESTS_RELPATH
        if not (project.root / "torchsnapshot_tpu").is_dir():
            return ()  # fixture run outside the real repo layout
        for err in check(path):
            msg = err.split(": ", 1)[1] if ": " in err else err
            yield Finding(
                rule=self.name,
                path=TIERED_TESTS_RELPATH,
                line=1,
                message=msg,
            )
