"""Rule registry population: importing this package registers every
rule module. Add a new rule by dropping a module here that defines a
``@register``-decorated ``Rule`` subclass and importing it below."""

from . import (  # noqa: F401
    async_blocking_call,
    collective_under_conditional,
    executor_thread_leak,
    knob_env_literal,
    names_lint,
    native_decl_sync,
    span_budget_balance,
    tiered_markers,
)

# The protocol family lives beside the per-file rules, one package up:
# its rules consume the extracted coordination-plane model rather than
# walking single modules.
from ..protocol import rules as protocol_rules  # noqa: E402,F401
