"""Rule registry population: importing this package registers every
rule module. Add a new rule by dropping a module here that defines a
``@register``-decorated ``Rule`` subclass and importing it below."""

from . import (  # noqa: F401
    async_blocking_call,
    collective_under_conditional,
    executor_thread_leak,
    knob_env_literal,
    names_lint,
    native_decl_sync,
    span_budget_balance,
    tiered_markers,
)
