"""executor-thread-leak: executors/threads with no exception-path
cleanup.

A ``ThreadPoolExecutor`` created per checkpoint operation that is not
shut down when the operation raises leaks its worker threads (and
whatever buffers their closures pin) on every failed take — the slow
leak that turns a flaky storage backend into an OOM. Same for a
non-daemon ``threading.Thread`` that is never joined on the error path.

A local ``ex = ThreadPoolExecutor(...)`` is accepted when:

- it is used as a context manager (``with ThreadPoolExecutor(...)``),
- some ``ex.shutdown(...)`` sits in a ``finally`` suite or ``except``
  handler, or
- ownership escapes the function (returned/yielded, passed as a call
  argument, or stored into an attribute/container) — the owner's
  lifecycle is then out of local-analysis reach.

A local ``t = threading.Thread(...)`` is additionally accepted when
constructed with ``daemon=True`` (or ``t.daemon = True`` before
start): daemon threads cannot block interpreter exit. Attribute
targets (``self._thread = ...``) are exempt — object lifecycle.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..core import Finding, ModuleInfo, Project, Rule, register
from .. import scopes


def _ctor_kind(call: ast.Call) -> Optional[str]:
    chain = scopes.call_chain(call)
    if not chain:
        return None
    if chain[-1] == "ThreadPoolExecutor":
        return "executor"
    if chain[-1] == "Thread" and (len(chain) == 1 or chain[0] == "threading"):
        return "thread"
    return None


def _has_daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if (
            kw.arg == "daemon"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
        ):
            return True
    return False


def _escapes(name: str, fn: ast.AST, creating: ast.AST) -> bool:
    """Does ``name`` leave the function's hands (return/yield, call
    argument, attribute/container store)?"""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            if node.value is not None and _mentions(node.value, name):
                return True
        elif isinstance(node, ast.Call) and node is not creating:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    # Method calls ON the object (name.submit(...)) are
                    # not escapes; name as an argument to anything else
                    # is.
                    return True
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)) and (
                    isinstance(node.value, ast.Name)
                    and node.value.id == name
                ):
                    return True
    return False


def _mentions(expr: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(expr)
    )


def _cleanup_calls(
    fn: ast.AST, name: str, methods: List[str]
) -> List[ast.Call]:
    out = []
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in methods
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == name
        ):
            out.append(node)
    return out


def _daemon_set_later(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "daemon"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == name
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True
                ):
                    return True
    return False


@register
class ExecutorThreadLeak(Rule):
    name = "executor-thread-leak"
    description = (
        "ThreadPoolExecutor/Thread without shutdown/join on exception "
        "paths (and no daemon flag) leaks OS threads per failure"
    )

    def check_module(
        self, module: ModuleInfo, project: Project
    ) -> Iterable[Finding]:
        parents = module.parents
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            kind = _ctor_kind(node.value)
            if kind is None:
                continue
            if len(node.targets) != 1 or not isinstance(
                node.targets[0], ast.Name
            ):
                continue  # attribute/container target: owner-managed
            name = node.targets[0].id
            fn = scopes.enclosing_function(node, parents) or module.tree
            if kind == "thread" and (
                _has_daemon_true(node.value) or _daemon_set_later(fn, name)
            ):
                continue
            methods = ["shutdown"] if kind == "executor" else ["join"]
            cleanup = _cleanup_calls(fn, name, methods)
            protected = any(
                scopes.in_finally(c, parents)
                or scopes.in_except_handler(c, parents)
                for c in cleanup
            )
            if protected or _escapes(name, fn, node.value):
                continue
            what = (
                "ThreadPoolExecutor" if kind == "executor" else "Thread"
            )
            fix = (
                "shutdown() it in a try/finally (or use `with`)"
                if kind == "executor"
                else "join() it in a try/finally or construct with "
                "daemon=True"
            )
            yield Finding(
                rule=self.name,
                path=module.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"{what} {name!r} has no exception-path cleanup — "
                    f"{fix}"
                ),
            )
