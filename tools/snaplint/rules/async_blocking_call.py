"""async-blocking-call: synchronous blocking calls inside ``async def``.

The whole write/read pipeline multiplexes on one event loop
(``scheduler.py``); a single ``time.sleep`` or no-timeout
``Future.result()`` inside a coroutine freezes every in-flight request
(budget waits, I/O slots, the staging overlap) for its whole duration —
and unlike a slow await, nothing else runs meanwhile. Flagged inside
``async def`` bodies:

- ``time.sleep(...)`` (coroutines must ``await asyncio.sleep``),
- ``<future>.result()`` with no timeout argument (unbounded block on
  the loop thread; executor hops must be awaited via
  ``run_in_executor``),
- ``subprocess.run/call/check_call/check_output`` (block until the
  child exits),
- non-awaited ``.wait()`` / ``.join()`` (the background-drain bug
  class: device-snapshot async takes put threading primitives —
  staged/done Events, the commit thread — right next to the drain's
  coroutines, and a ``threading.Event.wait()`` or ``Thread.join()``
  inside one freezes the whole pipeline; a non-awaited
  ``asyncio.Event().wait()`` is a silently-dropped coroutine, the same
  bug in different clothes). ``"sep".join(...)`` / f-string receivers
  and ``os.path.join`` are excluded.

A sync helper *defined* inside an async function is not flagged — the
repo pattern is to hand those to an executor.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from ..core import Finding, ModuleInfo, Project, Rule, register
from .. import scopes

_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output"}


def _time_sleep_aliases(tree: ast.Module) -> Set[str]:
    """Bare names that mean ``time.sleep`` (``from time import sleep``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "sleep":
                    out.add(a.asname or "sleep")
    return out


@register
class AsyncBlockingCall(Rule):
    name = "async-blocking-call"
    description = (
        "blocking call (time.sleep / no-timeout .result() / subprocess) "
        "inside an async def body stalls the event loop"
    )

    def check_module(
        self, module: ModuleInfo, project: Project
    ) -> Iterable[Finding]:
        parents = module.parents
        sleep_aliases = _time_sleep_aliases(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = scopes.enclosing_function(node, parents)
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            chain = scopes.call_chain(node)
            reason = None
            if chain == ["time", "sleep"] or (
                len(chain) == 1 and chain[0] in sleep_aliases
            ):
                reason = (
                    "time.sleep() blocks the event loop; await "
                    "asyncio.sleep() instead"
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "result"
                and not node.args
                and not node.keywords
                and not isinstance(parents.get(node), ast.Await)
            ):
                reason = (
                    ".result() with no timeout blocks the event loop "
                    "unboundedly; await the future (or run_in_executor) "
                    "instead"
                )
            elif (
                len(chain) == 2
                and chain[0] == "subprocess"
                and chain[1] in _SUBPROCESS_BLOCKING
            ):
                reason = (
                    f"subprocess.{chain[1]}() blocks until the child "
                    f"exits; use an executor or asyncio.subprocess"
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("wait", "join")
                and not isinstance(parents.get(node), ast.Await)
                # str.join: a literal/f-string separator is string
                # building, not synchronization. (A *variable* string
                # separator can't be told apart statically; suppress
                # with a disable comment in that rare shape.)
                and not isinstance(
                    node.func.value, (ast.Constant, ast.JoinedStr)
                )
                # os.path.join / posixpath.join: path building.
                and not (chain and chain[0] in ("os", "posixpath", "ntpath"))
            ):
                reason = (
                    f"non-awaited .{node.func.attr}() inside a coroutine "
                    f"either blocks the event loop (threading "
                    f"Event/Thread) or drops an asyncio wait entirely; "
                    f"await the asyncio form or run_in_executor"
                )
            if reason is not None:
                yield Finding(
                    rule=self.name,
                    path=module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"in async def {fn.name}(): {reason}",
                )
