"""metric-name-literal / span-name-literal: the PR 2/PR 3 name checkers
as snaplint rules.

The exposition namespace (dashboards, Prometheus text files) and the
trace timeline (Perfetto queries, watchdog stall attribution) only stay
stable if every metric/span name is declared exactly once in
``telemetry/names.py`` and call sites reference the constants.

Layering: the tree-level generators (``_iter_metric_literal_sites`` /
``_iter_span_literal_sites``) are the single implementation of the
call-site checks. The legacy string-producing functions (the public
surface of ``tools/check_metric_names.py`` / ``check_span_names.py``,
now shims over this module) wrap them by parsing files from disk; the
Rule subclasses wrap them over the project's already-parsed modules —
one parse per file in the default lane, and findings carry the real
path/line so inline suppressions work.

These are *project-level* rules: the single-registration invariant
cannot be judged from one file, so they check the whole package
whenever the repo layout is present, parsing from disk only the
package files a partial-path run didn't load.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable, Iterator, List, Tuple

from ..core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    load_module_cached,
    register,
)

_SNAKE_CASE = re.compile(r"^[a-z][a-z0-9_]*$")
_COLON_CASE = re.compile(r"^[a-z][a-z0-9_]*(:[a-z][a-z0-9_]*)+$")
_KEBAB_CASE = re.compile(r"^[a-z][a-z0-9]*(-[a-z0-9]+)*$")
_SPAN_PREFIXES = ("SPAN_", "INSTANT_")
_RULE_PREFIX = "RULE_"
_EVENT_PREFIX = "EVENT_"
_CRASH_PREFIX = "CRASH_"
_SLO_PREFIX = "SLO_"
_REGISTRY_METHODS = {"counter_inc", "gauge_set", "histogram_observe"}
_TRACE_CALLABLES = {"trace_annotation", "span", "instant", "begin"}
# Doctor emit surfaces: the rule-registration decorator and the verdict
# constructor (telemetry/doctor.py). A literal id at either means the
# verdict namespace can drift from the names.py registry.
_DOCTOR_CALLABLES = {"doctor_rule", "Verdict"}
# Run-ledger post surfaces (telemetry/ledger.py): both take the event
# id as their SECOND positional argument (the root/snapshot path comes
# first) or as the ``event=`` keyword.
_LEDGER_CALLABLES = {"post_event", "post_event_for_snapshot"}
# Wire RPC op-id surfaces (telemetry/wire.py, tiered/peer.py): the
# context propagator and the peer client's request dispatcher take the
# declared op id first; ``observe_rpc`` takes it SECOND (the endpoint
# comes first) or as the ``op=`` keyword. A literal id at any of them
# means the on-the-wire op namespace — what stitched traces and
# per-op report splits key off — can drift from the names.py registry.
_RPC_FIRST_ARG_CALLABLES = {"propagate", "request"}
_RPC_SECOND_ARG_CALLABLES = {"observe_rpc"}
_RPC_PREFIX = "RPC_"
# Crash-point surfaces (chaos/crashpoints.py): the kill-point hook and
# the single-point arming helper both take the declared id first — the
# ``_crashpoint`` spelling covers the lazy-import aliases the
# production call sites use (snapshot.py's local helper, manager.py's
# ``crashpoint as _crashpoint`` import). A literal id at any of them
# means the crash-matrix registry (the CRASH_ constants the harness
# enumerates) can drift from the threaded points.
_CRASHPOINT_CALLABLES = {"crashpoint", "_crashpoint", "arm"}
# SLO declaration surface (telemetry/slo.py): every objective enters
# the engine through an ``Objective(...)`` construction whose first
# positional argument (or ``slo_id=`` keyword) is the declared id. A
# literal id there means the promised-objective namespace — breach
# events, burn gauges, the doctor's slo-burning evidence — can drift
# from the names.py registry.
_SLO_CALLABLES = {"Objective"}

NAMES_RELPATH = "torchsnapshot_tpu/telemetry/names.py"
TRACE_EXEMPT_RELPATH = "torchsnapshot_tpu/telemetry/trace.py"

_LOC_RE = re.compile(r"^(?P<path>[^:]+?\.py):(?P<line>\d+): ")


# ---------------------------------------------------------------------------
# declaration-file checks (string API shared with the shims)
# ---------------------------------------------------------------------------


def check_metric_names_file(
    path: Path,
    include_span_decls: bool = True,
    include_rule_decls: bool = True,
    include_event_decls: bool = True,
    include_crash_decls: bool = True,
    include_rpc_decls: bool = True,
    include_slo_decls: bool = True,
) -> List[str]:
    """Errors in the declaration file: malformed values (snake_case for
    metrics, colon-case for SPAN_/INSTANT_ trace names, kebab-case for
    RULE_ doctor-verdict ids, EVENT_ ledger events, CRASH_ crash points,
    RPC_ wire op ids and SLO_ objective ids), duplicate constants,
    duplicate values. The ``include_*_decls=False`` flags leave the
    SPAN_/INSTANT_, RULE_, EVENT_, CRASH_, RPC_ and SLO_ checks to the
    span / doctor / ledger / crashpoint / rpc / slo rules (the unified
    registry runs all seven; each defect should report once — with the
    flag off, those constants are skipped here entirely)."""
    errors = []
    if not path.exists():
        return [f"{path.name}: missing (metric names must be declared here)"]
    tree = ast.parse(path.read_text())
    seen_targets = {}
    seen_values = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if not isinstance(target, ast.Name):
                continue
            if not include_rule_decls and target.id.startswith(_RULE_PREFIX):
                continue
            if not include_event_decls and target.id.startswith(
                _EVENT_PREFIX
            ):
                continue
            if not include_crash_decls and target.id.startswith(
                _CRASH_PREFIX
            ):
                continue
            if not include_rpc_decls and target.id.startswith(_RPC_PREFIX):
                continue
            if not include_slo_decls and target.id.startswith(_SLO_PREFIX):
                continue
            if not include_span_decls and target.id.startswith(
                _SPAN_PREFIXES
            ):
                continue
            if not isinstance(node.value, ast.Constant) or not isinstance(
                node.value.value, str
            ):
                errors.append(
                    f"{path.name}:{node.lineno}: {target.id} is not a "
                    f"string literal"
                )
                continue
            value = node.value.value
            if target.id.startswith(_SPAN_PREFIXES):
                if not _COLON_CASE.match(value):
                    errors.append(
                        f"{path.name}:{node.lineno}: {value!r} is not "
                        f"colon-case (span/instant names look like "
                        f"'layer:operation')"
                    )
            elif target.id.startswith(_RULE_PREFIX):
                if not _KEBAB_CASE.match(value):
                    errors.append(
                        f"{path.name}:{node.lineno}: {value!r} is not "
                        f"kebab-case (doctor verdict ids look like "
                        f"'what-is-wrong')"
                    )
            elif target.id.startswith(_EVENT_PREFIX):
                if not _KEBAB_CASE.match(value):
                    errors.append(
                        f"{path.name}:{node.lineno}: {value!r} is not "
                        f"kebab-case (ledger event ids look like "
                        f"'what-happened')"
                    )
            elif target.id.startswith(_CRASH_PREFIX):
                if not _KEBAB_CASE.match(value):
                    errors.append(
                        f"{path.name}:{node.lineno}: {value!r} is not "
                        f"kebab-case (crash-point ids look like "
                        f"'what-just-became-durable')"
                    )
            elif target.id.startswith(_RPC_PREFIX):
                if not _KEBAB_CASE.match(value):
                    errors.append(
                        f"{path.name}:{node.lineno}: {value!r} is not "
                        f"kebab-case (wire RPC op ids look like "
                        f"'layer-operation')"
                    )
            elif target.id.startswith(_SLO_PREFIX):
                if not _KEBAB_CASE.match(value):
                    errors.append(
                        f"{path.name}:{node.lineno}: {value!r} is not "
                        f"kebab-case (slo ids look like "
                        f"'what-is-promised')"
                    )
            elif not _SNAKE_CASE.match(value):
                errors.append(
                    f"{path.name}:{node.lineno}: {value!r} is not "
                    f"snake_case"
                )
            if target.id in seen_targets:
                errors.append(
                    f"{path.name}:{node.lineno}: constant {target.id} "
                    f"assigned twice (first at line "
                    f"{seen_targets[target.id]})"
                )
            seen_targets[target.id] = node.lineno
            if value in seen_values:
                errors.append(
                    f"{path.name}:{node.lineno}: metric {value!r} "
                    f"registered twice (first at line {seen_values[value]})"
                )
            seen_values[value] = node.lineno
    if not seen_values and not errors:
        errors.append(f"{path.name}: no metric names declared")
    return errors


def _scan_prefixed_decls(
    path: Path,
    prefixes: Tuple[str, ...],
    value_regex: "re.Pattern[str]",
    shape_error: str,
    dup_label: str,
    missing_what: str,
    empty_error: str,
) -> List[str]:
    """ONE declaration-file scan for a prefixed constant family
    (SPAN_/INSTANT_, RULE_): value-shape check, duplicate constants,
    duplicate values, empty registry. The span and doctor checkers are
    thin wrappers so a declaration-hygiene fix lands once, not per
    family."""
    if not path.exists():
        return [f"{path.name}: missing ({missing_what} must be declared here)"]
    errors = []
    seen_targets = {}
    seen_values = {}
    tree = ast.parse(path.read_text())
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if not isinstance(target, ast.Name) or not target.id.startswith(
                prefixes
            ):
                continue
            if not isinstance(node.value, ast.Constant) or not isinstance(
                node.value.value, str
            ):
                errors.append(
                    f"{path.name}:{node.lineno}: {target.id} is not a "
                    f"string literal"
                )
                continue
            value = node.value.value
            if not value_regex.match(value):
                errors.append(
                    f"{path.name}:{node.lineno}: {value!r} is not "
                    f"{shape_error}"
                )
            if target.id in seen_targets:
                errors.append(
                    f"{path.name}:{node.lineno}: constant {target.id} "
                    f"assigned twice (first at line "
                    f"{seen_targets[target.id]})"
                )
            seen_targets[target.id] = node.lineno
            if value in seen_values:
                errors.append(
                    f"{path.name}:{node.lineno}: {dup_label} {value!r} "
                    f"registered twice (first at line {seen_values[value]})"
                )
            seen_values[value] = node.lineno
    if not seen_values and not errors:
        errors.append(f"{path.name}: {empty_error}")
    return errors


def check_span_names_file(path: Path) -> List[str]:
    """Errors in the declaration file: no span constants at all,
    non-colon-case values, duplicate constants/values."""
    return _scan_prefixed_decls(
        path,
        _SPAN_PREFIXES,
        _COLON_CASE,
        "colon-case ('layer:operation')",
        "span",
        "span names",
        "no span/instant names declared",
    )


def check_doctor_rule_ids_file(path: Path) -> List[str]:
    """Errors in the declaration file's doctor-verdict registry: no
    RULE_ constants at all, non-kebab-case values, duplicate
    constants/values."""
    return _scan_prefixed_decls(
        path,
        (_RULE_PREFIX,),
        _KEBAB_CASE,
        "kebab-case ('what-is-wrong')",
        "rule id",
        "doctor rule ids",
        "no doctor rule ids declared",
    )


def check_ledger_event_ids_file(path: Path) -> List[str]:
    """Errors in the declaration file's run-ledger event registry: no
    EVENT_ constants at all, non-kebab-case values, duplicate
    constants/values."""
    return _scan_prefixed_decls(
        path,
        (_EVENT_PREFIX,),
        _KEBAB_CASE,
        "kebab-case ('what-happened')",
        "event id",
        "ledger event ids",
        "no ledger event ids declared",
    )


def check_crashpoint_ids_file(path: Path) -> List[str]:
    """Errors in the declaration file's crash-point registry: no CRASH_
    constants at all, non-kebab-case values, duplicate
    constants/values."""
    return _scan_prefixed_decls(
        path,
        (_CRASH_PREFIX,),
        _KEBAB_CASE,
        "kebab-case ('what-just-became-durable')",
        "crash point",
        "crash point ids",
        "no crash point ids declared",
    )


def check_slo_ids_file(path: Path) -> List[str]:
    """Errors in the declaration file's SLO objective registry: no SLO_
    constants at all, non-kebab-case values, duplicate
    constants/values."""
    return _scan_prefixed_decls(
        path,
        (_SLO_PREFIX,),
        _KEBAB_CASE,
        "kebab-case ('what-is-promised')",
        "slo id",
        "slo ids",
        "no slo ids declared",
    )


def check_rpc_op_ids_file(path: Path) -> List[str]:
    """Errors in the declaration file's wire RPC op registry: no RPC_
    constants at all, non-kebab-case values, duplicate
    constants/values."""
    return _scan_prefixed_decls(
        path,
        (_RPC_PREFIX,),
        _KEBAB_CASE,
        "kebab-case ('layer-operation')",
        "rpc op",
        "rpc op ids",
        "no rpc op ids declared",
    )


# ---------------------------------------------------------------------------
# call-site checks: ONE tree-level implementation
# ---------------------------------------------------------------------------


def _called_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _iter_metric_literal_sites(
    tree: ast.AST,
) -> Iterator[Tuple[int, str, str]]:
    """(lineno, method, literal) for string-literal metric names passed
    to registry methods."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        method = func.attr if isinstance(func, ast.Attribute) else None
        if method not in _REGISTRY_METHODS or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            yield node.lineno, method, first.value


def _iter_span_literal_sites(
    tree: ast.AST,
) -> Iterator[Tuple[int, str, str]]:
    """(lineno, callable, literal) for string-literal span names passed
    to trace_annotation/span/instant/begin."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        called = _called_name(node.func)
        if called not in _TRACE_CALLABLES:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            yield node.lineno, called, first.value


def _iter_rule_literal_sites(
    tree: ast.AST,
) -> Iterator[Tuple[int, str, str]]:
    """(lineno, callable, literal) for string-literal verdict ids at
    doctor emit sites: the first positional arg of ``doctor_rule(...)``
    / ``Verdict(...)`` or their ``rule=`` / ``rule_id=`` keyword."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        called = _called_name(node.func)
        if called not in _DOCTOR_CALLABLES:
            continue
        candidates = []
        if node.args:
            candidates.append(node.args[0])
        for kw in node.keywords:
            if kw.arg in ("rule", "rule_id"):
                candidates.append(kw.value)
        for cand in candidates:
            if isinstance(cand, ast.Constant) and isinstance(
                cand.value, str
            ):
                yield node.lineno, called, cand.value


def _iter_ledger_event_literal_sites(
    tree: ast.AST,
) -> Iterator[Tuple[int, str, str]]:
    """(lineno, callable, literal) for string-literal event ids at
    ledger post sites: the SECOND positional arg of ``post_event(root,
    event, ...)`` / ``post_event_for_snapshot(path, event, ...)`` or
    their ``event=`` keyword (the first positional is the root)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        called = _called_name(node.func)
        if called not in _LEDGER_CALLABLES:
            continue
        candidates = []
        if len(node.args) >= 2:
            candidates.append(node.args[1])
        for kw in node.keywords:
            if kw.arg == "event":
                candidates.append(kw.value)
        for cand in candidates:
            if isinstance(cand, ast.Constant) and isinstance(
                cand.value, str
            ):
                yield node.lineno, called, cand.value


def _iter_crashpoint_literal_sites(
    tree: ast.AST,
) -> Iterator[Tuple[int, str, str]]:
    """(lineno, callable, literal) for string-literal crash-point ids
    at kill-point sites: the first positional arg of ``crashpoint(...)``
    / ``arm(...)`` or their ``name=`` keyword."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        called = _called_name(node.func)
        if called not in _CRASHPOINT_CALLABLES:
            continue
        candidates = []
        if node.args:
            candidates.append(node.args[0])
        for kw in node.keywords:
            if kw.arg == "name":
                candidates.append(kw.value)
        for cand in candidates:
            if isinstance(cand, ast.Constant) and isinstance(
                cand.value, str
            ):
                yield node.lineno, called, cand.value


def _iter_slo_literal_sites(
    tree: ast.AST,
) -> Iterator[Tuple[int, str, str]]:
    """(lineno, callable, literal) for string-literal slo ids at
    objective declaration sites: the first positional arg of
    ``Objective(...)`` or its ``slo_id=`` keyword."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        called = _called_name(node.func)
        if called not in _SLO_CALLABLES:
            continue
        candidates = []
        if node.args:
            candidates.append(node.args[0])
        for kw in node.keywords:
            if kw.arg == "slo_id":
                candidates.append(kw.value)
        for cand in candidates:
            if isinstance(cand, ast.Constant) and isinstance(
                cand.value, str
            ):
                yield node.lineno, called, cand.value


def _iter_rpc_literal_sites(
    tree: ast.AST,
) -> Iterator[Tuple[int, str, str]]:
    """(lineno, callable, literal) for string-literal op ids at wire
    RPC sites: the first positional arg of ``propagate(...)`` /
    ``<client>.request(...)``, the second positional of
    ``observe_rpc(endpoint, op, ...)``, or the ``op=`` keyword of
    either."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        called = _called_name(node.func)
        candidates = []
        if called in _RPC_FIRST_ARG_CALLABLES:
            if node.args:
                candidates.append(node.args[0])
        elif called in _RPC_SECOND_ARG_CALLABLES:
            if len(node.args) >= 2:
                candidates.append(node.args[1])
        else:
            continue
        for kw in node.keywords:
            if kw.arg == "op":
                candidates.append(kw.value)
        for cand in candidates:
            if isinstance(cand, ast.Constant) and isinstance(
                cand.value, str
            ):
                yield node.lineno, called, cand.value


def check_metric_call_sites(package: Path, names_file: Path) -> List[str]:
    """Shim API: errors at registry call sites, scanned from disk."""
    errors = []
    for py in sorted(package.rglob("*.py")):
        if py == names_file:
            continue
        try:
            tree = ast.parse(py.read_text())
        except SyntaxError as e:
            errors.append(f"{py.relative_to(package.parent)}: {e}")
            continue
        for lineno, method, literal in _iter_metric_literal_sites(tree):
            errors.append(
                f"{py.relative_to(package.parent)}:{lineno}: "
                f"literal metric name {literal!r} in {method}() — "
                f"use a telemetry/names.py constant"
            )
    return errors


def check_span_call_sites(package: Path, exempt=None) -> List[str]:
    """Shim API: errors at trace call sites, scanned from disk."""
    exempt = set(exempt or ())
    errors = []
    for py in sorted(package.rglob("*.py")):
        if py in exempt:
            continue
        try:
            tree = ast.parse(py.read_text())
        except SyntaxError as e:
            errors.append(f"{py.relative_to(package.parent)}: {e}")
            continue
        for lineno, called, literal in _iter_span_literal_sites(tree):
            errors.append(
                f"{py.relative_to(package.parent)}:{lineno}: "
                f"literal span name {literal!r} in {called}() — use a "
                f"telemetry/names.py constant"
            )
    return errors


# ---------------------------------------------------------------------------
# snaplint rule adapters
# ---------------------------------------------------------------------------


def _parse_loc(error: str, default_path: str) -> Tuple[str, int, str]:
    m = _LOC_RE.match(error)
    if m:
        return m.group("path"), int(m.group("line")), error[m.end():]
    head, _, rest = error.partition(": ")
    # A path-shaped head without a line number ("pkg/broken.py: invalid
    # syntax") still names the real file; don't misattribute it.
    if head.endswith(".py") and rest:
        return head, 1, rest
    return default_path, 1, rest or error


def _package_dir(project: Project) -> Path:
    return project.root / "torchsnapshot_tpu"


def _package_trees(
    project: Project,
) -> Iterator[Tuple[str, ast.AST]]:
    """(repo-relative path, tree) for every package file — the
    project's shared parses where available, disk parses only for
    package files a partial-path run didn't load. Unparseable files are
    skipped here (the module loader reports them as parse errors when
    scanned)."""
    package = _package_dir(project).resolve()
    seen = set()
    for m in project.modules:
        resolved = m.path.resolve()
        try:
            resolved.relative_to(package)
        except ValueError:
            continue
        seen.add(resolved)
        yield m.relpath, m.tree
    for py in sorted(_package_dir(project).rglob("*.py")):
        resolved = py.resolve()
        if "__pycache__" in py.parts or resolved in seen:
            continue
        try:
            # The shared process-wide parse cache: four project-level
            # rules plus the protocol model all fall back to disk for
            # the same package files on a partial-path run.
            module = load_module_cached(py, project.root)
        except (OSError, SyntaxError):
            continue
        yield module.relpath, module.tree


def _decl_findings(
    rule: str, errors: List[str], project: Project
) -> Iterable[Finding]:
    for err in errors:
        loc_path, line, msg = _parse_loc(err, NAMES_RELPATH)
        if loc_path == Path(NAMES_RELPATH).name:
            loc_path = NAMES_RELPATH
        yield Finding(rule=rule, path=loc_path, line=line, message=msg)


@register
class MetricNameLiteral(Rule):
    name = "metric-name-literal"
    description = (
        "metric names: snake_case, declared exactly once in "
        "telemetry/names.py, no literals at registry call sites"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        names_file = project.root / NAMES_RELPATH
        if not _package_dir(project).is_dir() or not names_file.exists():
            return  # fixture runs without the real package layout
        # Span declaration hygiene is span-name-literal's, doctor-id
        # hygiene doctor-rule-ids': each defect reports once in a
        # unified run.
        yield from _decl_findings(
            self.name,
            check_metric_names_file(
                names_file,
                include_span_decls=False,
                include_rule_decls=False,
                include_event_decls=False,
                include_crash_decls=False,
                include_rpc_decls=False,
                include_slo_decls=False,
            ),
            project,
        )
        for relpath, tree in _package_trees(project):
            if relpath == NAMES_RELPATH:
                continue
            for lineno, method, literal in _iter_metric_literal_sites(tree):
                yield Finding(
                    rule=self.name,
                    path=relpath,
                    line=lineno,
                    message=(
                        f"literal metric name {literal!r} in {method}() "
                        f"— use a telemetry/names.py constant"
                    ),
                )


@register
class DoctorRuleIds(Rule):
    name = "doctor-rule-ids"
    description = (
        "doctor verdict ids: kebab-case, declared exactly once in "
        "telemetry/names.py (RULE_ constants), no literal ids at "
        "doctor_rule/Verdict emit sites"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        names_file = project.root / NAMES_RELPATH
        if not _package_dir(project).is_dir() or not names_file.exists():
            return
        yield from _decl_findings(
            self.name, check_doctor_rule_ids_file(names_file), project
        )
        for relpath, tree in _package_trees(project):
            if relpath == NAMES_RELPATH:
                continue
            for lineno, called, literal in _iter_rule_literal_sites(tree):
                yield Finding(
                    rule=self.name,
                    path=relpath,
                    line=lineno,
                    message=(
                        f"literal verdict id {literal!r} in {called}() — "
                        f"use a telemetry/names.py RULE_ constant"
                    ),
                )


@register
class LedgerEventIds(Rule):
    name = "ledger-event-ids"
    description = (
        "run-ledger event ids: kebab-case, declared exactly once in "
        "telemetry/names.py (EVENT_ constants), no literal event "
        "strings at post_event/post_event_for_snapshot call sites"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        names_file = project.root / NAMES_RELPATH
        if not _package_dir(project).is_dir() or not names_file.exists():
            return
        yield from _decl_findings(
            self.name, check_ledger_event_ids_file(names_file), project
        )
        for relpath, tree in _package_trees(project):
            if relpath == NAMES_RELPATH:
                continue
            for lineno, called, literal in _iter_ledger_event_literal_sites(
                tree
            ):
                yield Finding(
                    rule=self.name,
                    path=relpath,
                    line=lineno,
                    message=(
                        f"literal event id {literal!r} in {called}() — "
                        f"use a telemetry/names.py EVENT_ constant"
                    ),
                )


@register
class CrashpointIds(Rule):
    name = "crashpoint-ids"
    description = (
        "crash-point ids: kebab-case, declared exactly once in "
        "telemetry/names.py (CRASH_ constants), no literal ids at "
        "crashpoint()/arm() kill-point sites"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        names_file = project.root / NAMES_RELPATH
        if not _package_dir(project).is_dir() or not names_file.exists():
            return
        yield from _decl_findings(
            self.name, check_crashpoint_ids_file(names_file), project
        )
        for relpath, tree in _package_trees(project):
            if relpath == NAMES_RELPATH:
                continue
            for lineno, called, literal in _iter_crashpoint_literal_sites(
                tree
            ):
                yield Finding(
                    rule=self.name,
                    path=relpath,
                    line=lineno,
                    message=(
                        f"literal crash-point id {literal!r} in "
                        f"{called}() — use a telemetry/names.py CRASH_ "
                        f"constant"
                    ),
                )


@register
class RpcOpIds(Rule):
    name = "rpc-op-ids"
    description = (
        "wire RPC op ids: kebab-case, declared exactly once in "
        "telemetry/names.py (RPC_ constants), no literal op strings at "
        "propagate/request/observe_rpc frame-send sites"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        names_file = project.root / NAMES_RELPATH
        if not _package_dir(project).is_dir() or not names_file.exists():
            return
        yield from _decl_findings(
            self.name, check_rpc_op_ids_file(names_file), project
        )
        for relpath, tree in _package_trees(project):
            if relpath == NAMES_RELPATH:
                continue
            for lineno, called, literal in _iter_rpc_literal_sites(tree):
                yield Finding(
                    rule=self.name,
                    path=relpath,
                    line=lineno,
                    message=(
                        f"literal rpc op id {literal!r} in {called}() — "
                        f"use a telemetry/names.py RPC_ constant"
                    ),
                )


@register
class SloIds(Rule):
    name = "slo-ids"
    description = (
        "slo objective ids: kebab-case, declared exactly once in "
        "telemetry/names.py (SLO_ constants), no literal ids at "
        "Objective(...) declaration sites"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        names_file = project.root / NAMES_RELPATH
        if not _package_dir(project).is_dir() or not names_file.exists():
            return
        yield from _decl_findings(
            self.name, check_slo_ids_file(names_file), project
        )
        for relpath, tree in _package_trees(project):
            if relpath == NAMES_RELPATH:
                continue
            for lineno, called, literal in _iter_slo_literal_sites(tree):
                yield Finding(
                    rule=self.name,
                    path=relpath,
                    line=lineno,
                    message=(
                        f"literal slo id {literal!r} in {called}() — "
                        f"use a telemetry/names.py SLO_ constant"
                    ),
                )


@register
class SpanNameLiteral(Rule):
    name = "span-name-literal"
    description = (
        "span/instant names: colon-case, declared exactly once in "
        "telemetry/names.py, no literals at trace call sites"
    )

    def check_project(self, project: Project) -> Iterable[Finding]:
        names_file = project.root / NAMES_RELPATH
        if not _package_dir(project).is_dir() or not names_file.exists():
            return
        yield from _decl_findings(
            self.name, check_span_names_file(names_file), project
        )
        for relpath, tree in _package_trees(project):
            if relpath in (NAMES_RELPATH, TRACE_EXEMPT_RELPATH):
                continue
            for lineno, called, literal in _iter_span_literal_sites(tree):
                yield Finding(
                    rule=self.name,
                    path=relpath,
                    line=lineno,
                    message=(
                        f"literal span name {literal!r} in {called}() — "
                        f"use a telemetry/names.py constant"
                    ),
                )
