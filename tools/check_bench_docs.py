#!/usr/bin/env python
"""Docs-consistency check: BENCH.md must quote the signal of record.

The committed benchmark narrative drifting from the driver-captured
numbers (round 2 shipped a hand-typed 0.92 pipeline efficiency while
``BENCH_r02.json`` recorded 0.646) is exactly the class of error this
check exists to catch. BENCH.md carries a fenced JSON block between
``BENCH_SIGNAL_OF_RECORD`` markers that must equal the ``parsed`` record
of the newest ``BENCH_r*.json`` in the repo root. Stdlib-only; run from
anywhere:

    python tools/check_bench_docs.py
"""

import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
BLOCK_RE = re.compile(
    r"BENCH_SIGNAL_OF_RECORD[^\n]*-->\s*```json\s*(\{.*?\})\s*```",
    re.DOTALL,
)


def newest_record():
    rounds = []
    for path in ROOT.glob("BENCH_r*.json"):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", path.name)
        if m:
            rounds.append((int(m.group(1)), path))
    if not rounds:
        return None, None
    _, path = max(rounds)
    data = json.loads(path.read_text())
    return data.get("parsed", data), path


def main() -> int:
    record, record_path = newest_record()
    if record is None:
        print("check_bench_docs: no BENCH_r*.json found; nothing to check")
        return 0
    bench_md = ROOT / "BENCH.md"
    if not bench_md.exists():
        print("check_bench_docs: BENCH.md missing")
        return 1
    m = BLOCK_RE.search(bench_md.read_text())
    if not m:
        print(
            "check_bench_docs: BENCH.md has no BENCH_SIGNAL_OF_RECORD block "
            f"(must quote {record_path.name} verbatim)"
        )
        return 1
    try:
        quoted = json.loads(m.group(1))
    except json.JSONDecodeError as e:
        print(f"check_bench_docs: signal-of-record block is not valid JSON: {e}")
        return 1
    if quoted != record:
        print(
            f"check_bench_docs: BENCH.md signal-of-record block does not "
            f"match {record_path.name}:"
        )
        for key in sorted(set(quoted) | set(record)):
            a, b = quoted.get(key), record.get(key)
            if a != b:
                print(f"  {key}: BENCH.md has {a!r}, record has {b!r}")
        return 1
    print(
        f"check_bench_docs: BENCH.md matches the signal of record "
        f"({record_path.name})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
