#!/usr/bin/env python
"""Docs-consistency check: BENCH.md must quote the signal of record.

The committed benchmark narrative drifting from the driver-captured
numbers (round 2 shipped a hand-typed 0.92 pipeline efficiency while
``BENCH_r02.json`` recorded 0.646) is exactly the class of error this
check exists to catch. BENCH.md carries a fenced JSON block between
``BENCH_SIGNAL_OF_RECORD`` markers that must equal the ``parsed`` record
of the newest ``BENCH_r*.json`` **with a non-null parsed record** — a
timed-out driver run writes ``parsed: null`` (round 4 did), and such a
record must not vacuously green the check: it is skipped with a warning
and the check falls back to the newest round that actually parsed. If
BENCH.md carries a block but no round ever parsed, that is a hard
failure. Stdlib-only; run from anywhere:

    python tools/check_bench_docs.py
"""

import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
BLOCK_RE = re.compile(
    r"BENCH_SIGNAL_OF_RECORD[^\n]*-->\s*```json\s*(\{.*?\})\s*```",
    re.DOTALL,
)


def scan_records(root: Path = ROOT):
    """All BENCH_r*.json records, newest round first, as
    ``(round, path, parsed_or_None)`` triples. ``parsed`` is the driver's
    parse of bench.py's final JSON line; null means the run died before
    (or without) emitting one."""
    rounds = []
    for path in root.glob("BENCH_r*.json"):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", path.name)
        if m:
            try:
                data = json.loads(path.read_text())
                parsed = data.get("parsed", data)
            except (json.JSONDecodeError, OSError):
                # A corrupt/truncated record must not crash the check —
                # treat it like a run that never parsed.
                parsed = None
            rounds.append((int(m.group(1)), path, parsed))
    return sorted(rounds, reverse=True)


def newest_record(root: Path = ROOT, log=print):
    """The newest record with a non-null parse, skipping (and naming)
    broken newer rounds. Returns ``(record, path)`` — ``(None, None)``
    only when no round ever parsed."""
    skipped = []
    for _, path, parsed in scan_records(root):
        if parsed is not None:
            if skipped:
                log(
                    "check_bench_docs: WARNING: skipped "
                    + ", ".join(p.name for p in skipped)
                    + " (parsed is null or unreadable — timed-out or "
                    + f"corrupt run); using {path.name}"
                )
            return parsed, path
        skipped.append(path)
    if skipped:
        log(
            "check_bench_docs: WARNING: no record has a non-null parse: "
            + ", ".join(p.name for p in skipped)
        )
    return None, None


def main(root: Path = ROOT) -> int:
    record, record_path = newest_record(root)
    bench_md = root / "BENCH.md"
    if not bench_md.exists():
        print("check_bench_docs: BENCH.md missing")
        return 1
    m = BLOCK_RE.search(bench_md.read_text())
    if record is None:
        if m is not None:
            # The block claims to quote a signal of record that does not
            # exist — the exact situation a vacuous pass would hide.
            print(
                "check_bench_docs: BENCH.md carries a signal-of-record "
                "block but no BENCH_r*.json has a non-null parsed record"
            )
            return 1
        print("check_bench_docs: no parsed BENCH_r*.json and no block; nothing to check")
        return 0
    if not m:
        print(
            "check_bench_docs: BENCH.md has no BENCH_SIGNAL_OF_RECORD block "
            f"(must quote {record_path.name} verbatim)"
        )
        return 1
    try:
        quoted = json.loads(m.group(1))
    except json.JSONDecodeError as e:
        print(f"check_bench_docs: signal-of-record block is not valid JSON: {e}")
        return 1
    if quoted != record:
        print(
            f"check_bench_docs: BENCH.md signal-of-record block does not "
            f"match {record_path.name}:"
        )
        for key in sorted(set(quoted) | set(record)):
            a, b = quoted.get(key), record.get(key)
            if a != b:
                print(f"  {key}: BENCH.md has {a!r}, record has {b!r}")
        return 1
    print(
        f"check_bench_docs: BENCH.md matches the signal of record "
        f"({record_path.name})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
