"""Elastic fan-out restore: one storage read per unique saved shard.

Without fan-out, every restoring process pulls its bytes from durable
storage independently — a fleet of N processes pays N storage reads per
shard (and an object store bills/throttles N GETs). The fix, per
Orbax's single-reader restore and the cross-replica distribution idea
of arXiv:2004.13336: read each unique byte window exactly once and
distribute over the interconnect.

Topology: a deterministic **owner table**
(``resharding.assign_shard_owners`` over the manifest's eligible shard
blobs — a pure content hash of the committed manifest, so every rank
derives the identical table from the identical metadata file; whether
fan-out runs at all is rank 0's knob reading, broadcast-agreed at
restore start) maps each unique saved-shard blob to exactly one owner
rank. Per restore round (one per stateful key in the sync path, one
covering every plan in the async path), the ranks **exchange** their
needed byte windows, each owner issues ONE contiguous ranged read of
the union window per owned-and-needed blob, and the bytes ride
nonce-keyed coordination-store entries to the needy peers. The read
pipeline then runs unmodified against a :class:`StoragePlugin` wrapper
that serves those blobs from the exchanged cache and delegates
everything else (metadata, checksum tables, dense/object blobs) to the
real plugin.

The data plane deliberately does NOT use the shared-op-seq ``PGWrapper``
collectives: every store key is scoped to the restore round's nonce
prefix, so a rank that dies mid-restore can never leave the op-seq
counter half-advanced and poison a retry. Every wait polls the round's
**error key** — the same ``{prefix}/error`` the round's
:class:`~torchsnapshot_tpu.dist_store.LinearBarrier` poisons via
``report_error`` — so a peer that fails in planning, fetching, or setup
aborts the exchange within seconds instead of stranding it for the
store timeout (the caller's ``_reporting_to`` discipline writes that
key on any failure).

Kill switch: ``TORCHSNAPSHOT_TPU_FANOUT_RESTORE=0`` (knobs.py;
broadcast-agreed) restores every-rank-reads behavior exactly.
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

from . import knobs, telemetry
from .dist_store import Store, StoreTimeoutError, _PollPacer, scaled_poll_cap
from .io_types import ReadIO, ReadReq, StoragePlugin, WriteIO
from .telemetry import wire as _wire
from .telemetry.trace import get_recorder as _trace_recorder
from .manifest import Manifest, sharded_blob_windows
from .resharding import assign_shard_owners

logger: logging.Logger = logging.getLogger(__name__)

_DEFAULT_TIMEOUT_S = 300.0


class FanoutError(RuntimeError):
    """A fan-out round failed on some rank; every participant raises."""


class FanoutRestoreContext:
    """One restore's fan-out state: the owner table, the per-round byte
    cache, and the fetched/received byte accounting that feeds the
    restore report's ``bytes_fetched``/``bytes_received``."""

    def __init__(
        self,
        owners: Dict[str, int],
        windows: Dict[str, Tuple[int, int]],
        store: Optional[Store],
        rank: int,
        world_size: int,
    ) -> None:
        self.owners = owners
        self.windows = windows
        self.store = store
        self.rank = rank
        self.world_size = world_size
        # location -> ((lo, hi) cached window, bytes) for the current
        # round(s); served through wrap()'s plugin.
        self.cache: Dict[str, Tuple[Tuple[int, int], bytes]] = {}
        # This rank's bytes pulled from the storage plugin as an owner /
        # received from peer owners for its own needs.
        self.bytes_fetched = 0
        self.bytes_received = 0

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @classmethod
    def build(cls, manifest: Manifest, pg_wrapper: Any) -> "FanoutRestoreContext":
        """Derive the owner table from the committed global manifest.
        Pure content-deterministic computation — every rank reads the
        same metadata file, so every rank derives the same table without
        a world-sized broadcast; the *enablement* decision is what gets
        broadcast (rank 0's knob, agreed at restore start)."""
        windows = sharded_blob_windows(manifest)
        owners = assign_shard_owners(windows, pg_wrapper.get_world_size())
        return cls(
            owners,
            windows,
            pg_wrapper.store,
            pg_wrapper.get_rank(),
            pg_wrapper.get_world_size(),
        )

    # ------------------------------------------------------------------
    # the exchange round
    # ------------------------------------------------------------------

    def _needs_for(self, read_reqs: List[ReadReq]) -> Dict[str, Tuple[int, int]]:
        """Union byte window per fan-out-eligible blob this rank's reads
        touch (the preparer plans one contiguous row band per saved
        shard, so the union window is what the owner fetches)."""
        needs: Dict[str, Tuple[int, int]] = {}
        for req in read_reqs:
            full = self.windows.get(req.path)
            if full is None:
                continue
            rng = req.byte_range if req.byte_range is not None else full
            lo, hi = needs.get(req.path, rng)
            needs[req.path] = (min(lo, int(rng[0])), max(hi, int(rng[1])))
        return needs

    def _poll(self, key: str, error_key: str, timeout: float) -> bytes:
        """Wait for ``key``, aborting fast if any peer poisons the
        round's error key (the barrier ``report_error`` channel the
        enclosing ``_reporting_to`` writes on failure). Error key and
        data key ride ONE batched round trip per tick, with the shared
        exponential poll backoff."""
        out: Dict[str, bytes] = {}
        self._poll_all([key], error_key, timeout, out.__setitem__)
        return out[key]

    def _poll_all(
        self,
        keys: List[str],
        error_key: str,
        timeout: float,
        consume,
    ) -> None:
        """Batched wait for EVERY key in ``keys``: one ``multi_get``
        round trip per tick over the error key plus the still-missing
        keys — a thousand-rank needs-gather costs the leader one
        request per tick, not world sequential scans — calling
        ``consume(key, value)`` as each key lands (arrival order, so
        owner-published windows are consumed while stragglers publish).
        """
        assert self.store is not None
        pending = list(keys)
        deadline = time.monotonic() + timeout
        pacer = _PollPacer(cap=scaled_poll_cap(self.world_size))
        while pending:
            got = self.store.multi_get([error_key] + pending)
            err = got.get(error_key)
            if err is not None:
                exc = pickle.loads(err)
                raise FanoutError(
                    f"rank {self.rank}: a peer reported an error into the "
                    f"fan-out round ({error_key!r})"
                ) from exc
            still: List[str] = []
            for key in pending:
                val = got.get(key)
                if val is None:
                    still.append(key)
                else:
                    consume(key, val)
            if not still:
                return
            if len(still) < len(pending):
                pacer.reset()  # progress: keep first-poll latency low
            pending = still
            if time.monotonic() > deadline:
                raise StoreTimeoutError(
                    f"rank {self.rank} timed out in fan-out exchange "
                    f"waiting for {pending[:3]!r}"
                    + (f" (+{len(pending) - 3} more)" if len(pending) > 3 else "")
                )
            pacer.sleep(deadline)

    def exchange(
        self,
        read_reqs: List[ReadReq],
        storage: StoragePlugin,
        event_loop: asyncio.AbstractEventLoop,
        rendezvous_prefix: str,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> List[str]:
        """One fan-out round under ``rendezvous_prefix`` (the round's
        error-aware barrier prefix — data keys nest beneath it, and
        every wait polls its error key). MUST run on every rank, in the
        same round order, on the thread that owns collective ordering —
        pass an empty ``read_reqs`` when this rank loads nothing this
        round. Returns the locations cached for this rank (for
        :meth:`drop`)."""
        assert self.store is not None
        t0 = time.monotonic()
        span = _trace_recorder().begin(
            telemetry.names.SPAN_FANOUT_EXCHANGE,
            prefix=rendezvous_prefix,
            rank=self.rank,
            world=self.world_size,
            reqs=len(read_reqs),
        )
        try:
            # One wire context for the whole round: every store frame
            # of the needs gather and blob exchange carries the same
            # trace id, so the merged trace shows the round as one tree.
            with _wire.propagate(telemetry.names.RPC_FANOUT_EXCHANGE):
                return self._exchange_impl(
                    read_reqs, storage, event_loop, rendezvous_prefix, timeout
                )
        finally:
            _trace_recorder().end(span)
            try:
                telemetry.metrics().counter_inc(
                    telemetry.names.COORD_EXCHANGE_SECONDS_TOTAL,
                    time.monotonic() - t0,
                )
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass

    def _exchange_impl(
        self,
        read_reqs: List[ReadReq],
        storage: StoragePlugin,
        event_loop: asyncio.AbstractEventLoop,
        rendezvous_prefix: str,
        timeout: float = _DEFAULT_TIMEOUT_S,
    ) -> List[str]:
        p = f"{rendezvous_prefix}/fanout"
        error_key = f"{rendezvous_prefix}/error"
        needs = self._needs_for(read_reqs)

        # Needs gather, rank 0 aggregating (the Store.exchange shape,
        # re-built here so every wait is error-aware and every key is
        # round-scoped): each rank publishes its needs; rank 0 combines
        # the FULL table and republishes it as one doc; everyone reads
        # the combined doc. Batched end to end: rank 0 polls every
        # peer's needs key in one multi_get round trip per tick (not a
        # per-peer sequential scan) and tears the per-rank keys down
        # with one multi_delete — O(1) round trips per rank per round.
        if self.rank == 0:
            peer_keys = [
                f"{p}/needs/{peer}" for peer in range(1, self.world_size)
            ]
            by_key: Dict[str, Dict[str, Tuple[int, int]]] = {}
            self._poll_all(
                peer_keys,
                error_key,
                timeout,
                lambda k, v: by_key.__setitem__(k, pickle.loads(v)),
            )
            if peer_keys:
                self.store.multi_delete(peer_keys)
            gathered: List[Dict[str, Tuple[int, int]]] = [needs] + [
                by_key[k] for k in peer_keys
            ]
            self.store.set(f"{p}/needs/__all", pickle.dumps(gathered))
        else:
            self.store.set(f"{p}/needs/{self.rank}", pickle.dumps(needs))
            gathered = pickle.loads(
                self._poll(f"{p}/needs/__all", error_key, timeout)
            )
        if self.store.add(f"{p}/needs/__all_done", 1) == self.world_size:
            self.store.multi_delete(
                [f"{p}/needs/__all", f"{p}/needs/__all_done"]
            )

        union: Dict[str, Tuple[int, int]] = {}
        needy: Dict[str, List[int]] = {}
        for peer, peer_needs in enumerate(gathered):
            for loc, (lo, hi) in peer_needs.items():
                cur = union.get(loc, (lo, hi))
                union[loc] = (min(cur[0], lo), max(cur[1], hi))
                needy.setdefault(loc, []).append(peer)

        locs = sorted(union)
        cached: List[str] = []

        # Phase A — owners fetch every owned-and-needed blob
        # CONCURRENTLY (one contiguous union-window ranged read each,
        # I/O-concurrency bounded) and publish each needy peer's OWN
        # sub-window the moment its read lands. Serializing these
        # fetches would convoy the whole fleet behind one owner's
        # serial storage latency; shipping the full union to every
        # consumer would scale coordinator traffic and per-rank cache
        # with the union instead of each rank's need.
        owned = [
            (idx, loc)
            for idx, loc in enumerate(locs)
            if self.owners[loc] == self.rank
        ]
        # Every "ok" window this rank publishes for its consumers. On a
        # failing round consumers abort through the error key without
        # reading their windows, so the publisher must reap them — blob
        # payloads are the round's big bytes, and an orphaned window
        # outlives the round in the store.
        published_ok: List[str] = []
        if owned:
            io_slots = asyncio.Semaphore(
                max(1, knobs.get_per_rank_io_concurrency())
            )

            async def _fetch_one(idx: int, loc: str) -> None:
                lo, hi = union[loc]
                consumers = [r for r in needy[loc] if r != self.rank]
                try:
                    async with io_slots:
                        read_io = ReadIO(path=loc, byte_range=(lo, hi))
                        await storage.read(read_io)
                    if read_io.buf is None:
                        raise RuntimeError(
                            f"storage plugin {type(storage).__name__} "
                            f"completed read() without populating the "
                            f"buffer for {loc!r}"
                        )
                    data = bytes(read_io.buf)
                except BaseException as e:  # noqa: BLE001 - ship to peers
                    # The error rides the data channel itself (on top
                    # of the barrier error key the caller will poison),
                    # so consumers already polling this blob abort now.
                    # One batched publication for all consumers.
                    if consumers:
                        marker = pickle.dumps(("error", None, repr(e)))
                        self.store.multi_set(
                            {
                                f"{p}/blob/{idx}/{peer}": marker
                                for peer in consumers
                            }
                        )
                    raise
                self.bytes_fetched += len(data)
                # One multi_set round trip publishes every needy peer's
                # sub-window for this blob — per-key sets would cost the
                # owner O(consumers) sequential round trips per blob.
                payloads: Dict[str, bytes] = {}
                for peer in consumers:
                    plo, phi = gathered[peer][loc]
                    payloads[f"{p}/blob/{idx}/{peer}"] = pickle.dumps(
                        ("ok", (plo, phi), data[plo - lo : phi - lo])
                    )
                if payloads:
                    self.store.multi_set(payloads)
                    published_ok.extend(payloads)
                if loc in needs:
                    self.cache[loc] = ((lo, hi), data)

            async def _fetch_owned() -> None:
                results = await asyncio.gather(
                    *(_fetch_one(idx, loc) for idx, loc in owned),
                    return_exceptions=True,
                )
                errors = [r for r in results if isinstance(r, BaseException)]
                if errors:
                    # Every owned blob settled (data or error marker on
                    # the wire) before the first failure surfaces. The
                    # round is now failing: reap the windows this rank
                    # already published — its consumers abort via the
                    # markers/error key and will never read them. The
                    # markers themselves stay: they ARE the fail-fast
                    # channel, and whoever consumes one deletes it.
                    if published_ok:
                        try:
                            self.store.multi_delete(published_ok)
                        except Exception:  # noqa: BLE001 - best effort
                            pass
                    raise errors[0]

            event_loop.run_until_complete(_fetch_owned())
            cached.extend(loc for _, loc in owned if loc in needs)

        # Phase B — consume what peers own for us. Strictly this rank's
        # sub-windows: one key per (blob, consumer), polled as ONE
        # batched multi_get per tick (consumed in arrival order, so a
        # fast owner's windows land while a slow one still fetches) and
        # torn down with one multi_delete — nothing lingers in the
        # store and received bytes equal this rank's actual needs.
        awaited: Dict[str, str] = {}
        for idx, loc in enumerate(locs):
            if self.owners[loc] == self.rank or loc not in needs:
                continue
            awaited[f"{p}/blob/{idx}/{self.rank}"] = loc

        consumed: List[str] = []

        def _consume(key: str, raw: bytes) -> None:
            consumed.append(key)
            loc = awaited[key]
            status, window, data = pickle.loads(raw)
            if status == "error":
                raise FanoutError(
                    f"fan-out restore owner rank {self.owners[loc]} failed "
                    f"to fetch {loc!r}: {data}"
                )
            self.bytes_received += len(data)
            self.cache[loc] = (tuple(window), data)
            cached.append(loc)

        if awaited:
            try:
                self._poll_all(list(awaited), error_key, timeout, _consume)
            except BaseException:
                # The round is failing (a peer's error marker or the
                # poisoned error key). Tear down what we read AND what
                # we published — our consumers are aborting through the
                # same error key and will never read their windows.
                teardown = consumed + published_ok
                if teardown:
                    try:
                        self.store.multi_delete(teardown)
                    except Exception:  # noqa: BLE001 - best effort
                        pass
                raise
            # Tear down what we actually read (an owner's error marker
            # is consumed too); keys we never saw stay for their owner —
            # the round is nonce-scoped either way.
            if consumed:
                self.store.multi_delete(consumed)
        return cached

    def drop(self, locations: List[str]) -> None:
        """Release a round's cached bytes once its pipeline consumed
        them (sync restores drop per stateful key; async restores hold
        the whole plan's cache until the background reads finish)."""
        for loc in locations:
            self.cache.pop(loc, None)

    def clear(self) -> None:
        self.cache.clear()

    # ------------------------------------------------------------------
    # read-pipeline integration
    # ------------------------------------------------------------------

    def classify_read(self, req: ReadReq) -> Optional[str]:
        """Scheduler byte-accounting hook (``execute_read_reqs``): reads
        served from the exchanged cache are local copies — neither
        fetched nor received *by the pipeline* (the exchange already
        accounted them); everything else hit the real plugin."""
        return None if req.path in self.cache else "fetched"

    def wrap(self, storage: StoragePlugin) -> StoragePlugin:
        """A plugin view serving cached fan-out blobs and delegating the
        rest; hand this to the read pipeline in place of ``storage``."""
        return _FanoutStoragePlugin(storage, self)


class _FanoutStoragePlugin(StoragePlugin):
    """Serves reads of exchanged shard blobs from the fan-out cache;
    every other operation delegates to the wrapped plugin. Close is NOT
    delegated — the restore owns the real plugin's lifecycle."""

    def __init__(self, inner: StoragePlugin, ctx: FanoutRestoreContext) -> None:
        self.inner = inner
        self.ctx = ctx

    async def read(self, read_io: ReadIO) -> None:
        entry = self.ctx.cache.get(read_io.path)
        if entry is None:
            await self.inner.read(read_io)
            return
        (lo, hi), data = entry
        rng = read_io.byte_range
        if rng is None:
            rng = self.ctx.windows[read_io.path]
        a, b = int(rng[0]), int(rng[1])
        if a < lo or b > hi:
            raise FanoutError(
                f"fan-out cache for {read_io.path!r} holds [{lo}, {hi}) "
                f"but the read wants [{a}, {b}) — the exchanged union "
                f"window missed a request (planning bug)"
            )
        chunk = data[a - lo : b - lo]
        if read_io.dest is not None and len(read_io.dest) == len(chunk):
            read_io.dest[:] = chunk
            read_io.buf = read_io.dest
        else:
            read_io.buf = memoryview(chunk)
        read_io.served_by = "fanout-cache"

    async def read_degraded(self, read_io: ReadIO) -> bool:
        """Corruption fallthrough: a cache-served blob whose exchanged
        bytes fail verification re-reads from real storage directly
        (the owner's fetch — or the wire — damaged them); everything
        else walks the wrapped plugin's own ladder."""
        if read_io.served_by == "fanout-cache":
            read_io.served_by = None
            await self.inner.read(read_io)
            if read_io.served_by is None:
                read_io.served_by = "storage"
            return True
        return await self.inner.read_degraded(read_io)

    async def write(self, write_io: WriteIO) -> None:  # pragma: no cover
        await self.inner.write(write_io)

    async def delete(self, path: str) -> None:  # pragma: no cover
        await self.inner.delete(path)

    async def close(self) -> None:
        # The wrapped plugin outlives this view; nothing to release.
        return None
