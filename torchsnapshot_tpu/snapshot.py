"""The Snapshot user API: take / async_take / restore / read_object.

Reference parity: torchsnapshot/snapshot.py (991 LoC). Same protocol shape:

- ``take``: plan → partition → execute → barrier → rank-0 commits the
  ``.snapshot_metadata`` manifest (commit-after-barrier invariant,
  reference snapshot.py:230-237 — a snapshot without the metadata file never
  happened, which is what makes interrupted takes safe).
- ``async_take``: returns a :class:`PendingSnapshot` in
  checkpoint-size-independent time — the plan collectives run, a
  consistent device snapshot is pinned (on-device clones, dispatched),
  and staging (D2H + serialization), storage I/O and the commit all run
  on a background thread coordinated by a store-based
  store barrier (never collectives — reference
  snapshot.py:948). ``wait(phase=)`` exposes the staged/committed
  boundaries; docs/async.md has the full phase model.
- ``restore``: per-stateful memory-frugal load — current leaves are reused
  as restore destinations so footprint stays ~1x (reference
  snapshot.py:682-692); JAX arrays are restored host-side then
  ``device_put`` back onto their original sharding/device.
- ``read_object``: random access to one manifest path with an optional
  memory budget for chunked ranged reads.

TPU-native notes: app state is pytree-friendly (``PyTreeState``), RNG state
is explicit ``jax.random`` keys (no hidden global to guard, but the
save-first/restore-after ordering is preserved — reference
snapshot.py:340-346), and replication is declared via globs and/or detected
from fully-replicated shardings rather than inferred from DDP modules.
"""

from __future__ import annotations

import asyncio
import contextlib
import fnmatch
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from . import knobs, telemetry
from .telemetry import progress as _progress
from .telemetry.trace import (
    TraceMark,
    export_op_trace,
    get_recorder as _trace_recorder,
)
from .dist_store import StoreBarrier, make_barrier
from .flatten import flatten, inflate
from .io_preparer import (
    ArrayIOPreparer,
    capture_write_reqs,
    is_jax_array,
    prepare_read,
    prepare_write,
)
from .io_types import StoragePlugin, WriteIO, WriteReq
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Manifest,
    ObjectEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
    SnapshotMetadata,
    get_manifest_for_rank,
    is_container_entry,
)
from .pg_wrapper import PGWrapper
from .rng_state import RngState
from .scheduler import (
    DeferredIOWork,
    PendingIOWork,
    get_process_memory_budget_bytes,
    sync_execute_read_reqs,
    sync_execute_write_reqs,
)
from .stateful import AppState, Stateful
from .storage_plugin import url_to_storage_plugin
from .version import __version__

logger: logging.Logger = logging.getLogger(__name__)

SNAPSHOT_METADATA_FNAME = ".snapshot_metadata"


def _nonce_barrier(prefix: str, pg_wrapper: "PGWrapper") -> Optional[StoreBarrier]:
    """The error-propagating rendezvous used by every distributed phase
    (take commit, restore keys, async plan/apply), built one way so the
    phases can never diverge in barrier wiring. None single-process.
    ``make_barrier`` resolves the topology: the O(log world)
    :class:`~torchsnapshot_tpu.dist_store.TreeBarrier` by default,
    ``LinearBarrier`` behind the ``TORCHSNAPSHOT_TPU_TREE_BARRIER=0``
    kill switch — the contract (``report_error`` poison,
    ``BarrierError`` on every pending wait) is identical, so the phases
    swap topologies without caring."""
    if pg_wrapper.get_world_size() <= 1:
        return None
    assert pg_wrapper.store is not None
    return make_barrier(
        prefix,
        pg_wrapper.store,
        pg_wrapper.get_rank(),
        pg_wrapper.get_world_size(),
    )


@contextlib.contextmanager
def _reporting_to(barrier: Optional["StoreBarrier"], what: str):
    """Fail-fast discipline shared by every distributed phase: an error
    raised inside the block is reported into ``barrier`` (best-effort)
    before propagating, so peers waiting there abandon within seconds
    instead of blocking out the store timeout."""
    try:
        yield
    except BaseException as e:
        if barrier is not None:
            try:
                barrier.report_error(e)
            except Exception as report_exc:  # noqa: BLE001 - already failing
                logger.error(
                    "failed to report %s error to peers (%r); they will "
                    "abandon at the barrier timeout",
                    what,
                    report_exc,
                )
        raise


def _req_needed_bytes(req: Any) -> int:
    """One read request's contribution to ``bytes_needed`` — the bytes
    of destination it fills. Consumers that may read more than they
    deliver (a whole-shard read feeding a partial destination) expose
    ``destination_nbytes``; for everything else the consuming cost IS
    the destination size."""
    consumer = req.buffer_consumer
    fn = getattr(consumer, "destination_nbytes", None)
    return int(fn()) if fn is not None else int(
        consumer.get_consuming_cost_bytes()
    )


def _merge_fanout_telemetry(pipeline: Optional[dict], fanout_ctx) -> None:
    """Fold a fan-out context's byte accounting into a restore's merged
    pipeline telemetry: the owner-side union-window fetches (which ran
    in the exchange, outside any pipeline) add to ``bytes_fetched``, and
    peer-shipped bytes become ``bytes_received``."""
    if fanout_ctx is None or pipeline is None:
        return
    pipeline["bytes_fetched"] = (
        int(pipeline.get("bytes_fetched", 0)) + fanout_ctx.bytes_fetched
    )
    pipeline["bytes_received"] = (
        int(pipeline.get("bytes_received", 0)) + fanout_ctx.bytes_received
    )


def _merge_peer_telemetry(pipeline: Optional[dict], peer_ctx) -> None:
    """Fold a peer-tier restore context's per-tier byte accounting into
    the restore's merged pipeline telemetry: ``tier_split`` (bytes
    served per tier of the peer RAM -> fast -> durable ladder) and the
    ``peer`` degradation evidence the ``peer-tier-degraded`` doctor
    rule cites. The ladder's split supersedes any scheduler-recorded
    one (its ``read_degraded`` already counted corruption reroutes into
    ``tier_bytes`` — summing would double-count); the scheduler's
    ``degraded_reads`` summary rides alongside untouched."""
    if peer_ctx is None or pipeline is None:
        return
    pipeline.update(peer_ctx.pipeline_fields())


def _crashpoint(name: str) -> None:
    """Chaos kill point (chaos/crashpoints.py): production no-op."""
    from .chaos import crashpoint

    crashpoint(name)


def _maybe_push_to_peer(path: str, pending_io_work) -> None:
    """Post-commit peer-tier hook (every rank): queue this rank's
    written blobs — with the integrity entries the pipeline already
    computed — for replication into the ring neighbor's host RAM
    (tiered/peer.py). Inert unless the tier is configured; failures
    degrade (WARN + metrics), never fail the take."""
    try:
        from .tiered import peer as peer_tier

        peer_tier.maybe_enqueue_push(path, pending_io_work.checksums)
    except Exception as e:  # noqa: BLE001 - the peer tier must never fail a take
        logger.warning("peer tier: post-commit push hook failed: %r", e)
    # Kill point: the post-commit peer hook ran (enqueue, not settle).
    _crashpoint(telemetry.names.CRASH_PEER_ENQUEUED)


def _maybe_cas_storage(
    storage: StoragePlugin, path: str, cas_on: bool
) -> StoragePlugin:
    """Wrap a take's storage plugin with the content-addressed write
    interceptor (docs/cas.md) when the broadcast-agreed decision says
    so. The decision rides the existing path broadcast (rank 0 decides;
    env skew can never mix layouts *within* one blob — and even a
    per-rank mix composes, since the rank-0 rewrite is per-blob)."""
    if not cas_on:
        return storage
    from .cas import CASStoragePlugin

    return CASStoragePlugin(storage, path)


def _maybe_write_cas_map(
    storage: StoragePlugin,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    """Persist this rank's CAS ``path -> digest`` map (``cas/{rank}``)
    before the commit barrier — the input of rank 0's manifest rewrite,
    committed with the same always-before-barrier discipline as the
    checksum table. No-op for legacy takes."""
    from .cas import CASStoragePlugin

    if isinstance(storage, CASStoragePlugin):
        event_loop.run_until_complete(storage.write_chunk_map(rank))


def _mirror_state_for(path: str) -> Dict[str, Any]:
    """The process mirror's queue/lag state, for reports about tiered
    paths ({} otherwise): at take-report time the step's upload job was
    just enqueued, so this is the durability backlog the take added to."""
    from .tiered.mirror import mirror_state_for_path

    return dict(mirror_state_for_path(path) or {})


def _emit_snapshot_report(
    kind: str,
    path: str,
    pg_wrapper: "PGWrapper",
    pipeline: Optional[dict],
    counter_baseline: Dict[str, float],
    nonce: Optional[str],
    error: Optional[BaseException] = None,
    trace_mark: Optional[TraceMark] = None,
    tunables: Optional[Dict[str, Any]] = None,
) -> None:
    """Assemble this rank's SnapshotReport, aggregate across ranks, and
    hand it to the sinks. Best-effort — telemetry must never fail a
    checkpoint — EXCEPT that the cross-rank gather is unconditionally
    symmetric: every rank that reaches this function participates
    (whether or not a sink is configured locally), so a sink knob set on
    rank 0 only can never strand the gather. Store-based, not a
    collective: safe on the async-take commit thread.

    With ``trace_mark`` (the flight-recorder cursor captured at op
    start), the operation's span window is also exported as a Chrome
    trace file when the trace sink knob is on; the cross-rank gather
    doubles as the clock-offset measurement the trace merge uses to
    align per-rank timelines."""
    try:
        registry = telemetry.metrics()
        report = telemetry.build_report(
            kind=kind,
            path=path,
            rank=pg_wrapper.get_rank(),
            world_size=pg_wrapper.get_world_size(),
            pipeline=pipeline,
            counter_deltas=registry.counters_delta_since(counter_baseline),
            mirror=_mirror_state_for(path),
            error=repr(error) if error is not None else None,
            # The knob values the op actually ran under. Callers capture
            # the snapshot at op START: an async take's commit thread
            # emits after the drain, by which time the autotuner may
            # already have moved the vector for the next step.
            tunables=(
                tunables if tunables is not None else knobs.tunable_snapshot()
            ),
        )
        # Blocking-chain attribution over the op's recorder window
        # (telemetry/critpath.py). Computed BEFORE the gather so every
        # rank's dict carries its segments into the cross-rank fold.
        # The envelope span closed before this call (callers end it
        # before emitting), so the window holds the op's full extent.
        if trace_mark is not None:
            try:
                from .telemetry import critpath as _critpath

                report.critical_path = _critpath.critical_path_from_events(
                    _trace_recorder().events_since(trace_mark), kind
                )
            except Exception as e:  # noqa: BLE001 - attribution is best-effort
                logger.warning(
                    "telemetry: critical-path attribution failed: %r", e
                )
        gathered = None
        if (
            nonce
            and pg_wrapper.get_world_size() > 1
            and pg_wrapper.store is not None
        ):
            # Separately guarded with a bounded timeout: every rank that
            # commits reaches this gather, but a rank dying in the tiny
            # window after the commit barrier must cost rank 0 seconds
            # (and only the aggregation), never the 300 s store timeout
            # or the local report.
            try:
                # Every rank stamps its wall clock at gather entry —
                # moments after the same commit barrier on every rank —
                # which is what makes the per-rank deltas usable as
                # clock offsets for the trace merge.
                own = report.to_dict()
                own["gather_unix_ts"] = time.time()
                gathered = pg_wrapper.store.gather(
                    f"__telemetry/{kind}/{nonce}",
                    pg_wrapper.get_rank(),
                    pg_wrapper.get_world_size(),
                    own,
                    timeout=60.0,
                )
            except Exception as e:  # noqa: BLE001 - emit unaggregated
                logger.warning(
                    "telemetry: cross-rank gather for %s failed (%r); "
                    "emitting the unaggregated rank-local report",
                    kind,
                    e,
                )
                gathered = None
            if gathered is not None:
                report.aggregated = telemetry.aggregate_across_ranks(gathered)
                report.clock_offsets_s = telemetry.clock_offsets_from_gather(
                    gathered
                )
                for metric, spread in sorted(report.aggregated.items()):
                    logger.info(
                        "telemetry %s %s: min=%s median=%s max=%s "
                        "straggler=rank %s",
                        kind,
                        metric,
                        spread["min"],
                        spread["median"],
                        spread["max"],
                        spread["straggler"],
                    )
        telemetry.emit_report(report, registry)
        # Run-ledger events (rank 0 only; the owned-root gate inside
        # post_op_event additionally restricts posting to the process
        # whose manager opened the run — ad-hoc snapshots never post):
        # takes record their training-visible stall + overlapped drain,
        # restores the recovery time served. Failed ops post nothing —
        # their cost lands in the segment's lost-work bucket instead.
        if error is None and pg_wrapper.get_rank() == 0:
            from .telemetry import ledger as run_ledger

            # Restores carry a tier split (which tier of the peer ->
            # fast -> durable ladder served the bytes); when the gather
            # ran, sum it across ranks so the ledger records the
            # WORLD's recovery economics, not just rank 0's.
            world_tier_split = None
            if gathered:
                splits = [
                    r.get("tier_split")
                    for r in gathered
                    if isinstance(r, dict) and r.get("tier_split")
                ]
                if splits:
                    world_tier_split = {}
                    for s in splits:
                        for t, b in s.items():
                            world_tier_split[t] = (
                                world_tier_split.get(t, 0) + int(b)
                            )
            run_ledger.post_op_event(
                kind, path, report, world_tier_split=world_tier_split
            )
        if trace_mark is not None:
            export_op_trace(kind, path, pg_wrapper.get_rank(), trace_mark)
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the op
        logger.warning("telemetry: %s report emission failed: %r", kind, e)


class Snapshot:
    """A reference to an existing or to-be-created snapshot at ``path``."""

    def __init__(
        self,
        path: str,
        pg: Optional[Any] = None,
    ) -> None:
        self.path = path
        self._pg_arg = pg
        self._metadata: Optional[SnapshotMetadata] = None
        # Merged checksum tables, loaded at most once per Snapshot instance
        # (False = not loaded yet; None = no tables / verification disabled).
        self._checksum_table_cache: Any = False

    # ------------------------------------------------------------------
    # take
    # ------------------------------------------------------------------

    @classmethod
    def take(
        cls,
        path: str,
        app_state: AppState,
        pg: Optional[Any] = None,
        replicated: Optional[List[str]] = None,
        incremental_base: Optional[Any] = None,
        record_digests: bool = False,
        _custom_array_prepare_func=None,
    ) -> "Snapshot":
        """Synchronous distributed checkpoint (reference snapshot.py:175-243).

        ``incremental_base`` (a snapshot path or Snapshot, consistent
        across ranks) enables the incremental take: chunks whose on-device
        digest matches the base's recorded digest are not staged or
        written — the manifest references the base's blob instead
        (incremental.py). ``record_digests`` records digests without a
        base, making this snapshot usable as a future base."""
        import uuid

        from .cas import cas_eligible

        pg_wrapper = PGWrapper(pg)
        # Rank-0 path wins; the CAS layout decision rides the same
        # broadcast (one agreement, no extra collective) so ranks can
        # never diverge on where data bytes land.
        path, cas_on = pg_wrapper.broadcast_object(
            (path, cas_eligible(path))
        )
        # Error-propagating commit barrier, same design as async_take's:
        # a rank whose writes fail must not strand its peers for the full
        # store timeout — they observe the reported error at arrive() and
        # abandon (no commit marker anywhere). The nonce keeps barrier
        # keys from aliasing any earlier take to the same path.
        barrier = None
        commit_nonce = ""
        if pg_wrapper.get_world_size() > 1:
            commit_nonce = pg_wrapper.broadcast_object(uuid.uuid4().hex)
            barrier = _nonce_barrier(
                f"__snapshot_commit/{commit_nonce}", pg_wrapper
            )
        event_loop = asyncio.new_event_loop()
        counter_baseline = telemetry.metrics().counters_snapshot()
        tunables_at_start = knobs.tunable_snapshot()
        recorder = _trace_recorder()
        trace_mark = recorder.mark()
        take_span = recorder.begin(
            telemetry.names.SPAN_TAKE, path=path, rank=pg_wrapper.get_rank()
        )
        # Live-progress heartbeat for the whole op: external pollers see
        # a stuck rank from outside the process (telemetry/progress.py).
        tracker = _progress.track("take", path, pg_wrapper.get_rank())
        op_error: Optional[BaseException] = None
        try:
            storage = _maybe_cas_storage(
                url_to_storage_plugin(path), path, cas_on
            )
            with _reporting_to(barrier, "take"):
                pending_io_work, metadata = cls._take_impl(
                    path=path,
                    app_state=app_state,
                    pg_wrapper=pg_wrapper,
                    replicated=replicated or [],
                    storage=storage,
                    event_loop=event_loop,
                    is_async_snapshot=False,
                    incremental_base=incremental_base,
                    record_digests=record_digests,
                    _custom_array_prepare_func=_custom_array_prepare_func,
                    progress_tracker=tracker,
                )
                pending_io_work.sync_complete(event_loop)
                _crashpoint(telemetry.names.CRASH_TAKE_WRITES_DONE)
                pending_io_work.finalize_checksums()
                _maybe_write_checksum_table(
                    pending_io_work, pg_wrapper.get_rank(), storage, event_loop
                )
                _crashpoint(telemetry.names.CRASH_CHECKSUM_TABLE_WRITTEN)
                _maybe_write_cas_map(
                    storage, pg_wrapper.get_rank(), event_loop
                )
                _crashpoint(telemetry.names.CRASH_CAS_MAP_WRITTEN)

            # All writes are durable on every rank before the commit marker
            # exists anywhere (commit-after-barrier invariant). The commit
            # window itself stays under _reporting_to: if rank 0's metadata
            # write fails between arrive() and depart(), peers polling at
            # depart() observe the reported error and abandon in seconds
            # instead of blocking out the store timeout (the async path's
            # catch-all in PendingSnapshot._complete_snapshot already
            # covers its equivalent window).
            with _reporting_to(barrier, "commit"):
                if barrier is not None:
                    barrier.arrive()
                if pg_wrapper.get_rank() == 0:
                    cls._write_snapshot_metadata(metadata, storage, event_loop)
                if barrier is not None:
                    barrier.depart()
            # Post-commit: hand this rank's blobs to the peer tier (the
            # committed step is what a replacement rank would restore).
            _maybe_push_to_peer(path, pending_io_work)
            event_loop.run_until_complete(storage.close())
            # The envelope span closes before the report/trace emission
            # so the exported timeline carries the take's full extent.
            recorder.end(take_span)
            # Post-close on purpose: a tiered plugin enqueues its mirror
            # job at close, so the report's mirror state reflects the
            # durability backlog this take just created.
            _emit_snapshot_report(
                kind="take",
                path=path,
                pg_wrapper=pg_wrapper,
                pipeline=pending_io_work.pipeline_telemetry(),
                counter_baseline=counter_baseline,
                nonce=commit_nonce,
                trace_mark=trace_mark,
                tunables=tunables_at_start,
            )
        except BaseException as e:
            op_error = e
            raise
        finally:
            # Success removes the heartbeat file; failure leaves a
            # terminal document (doctor evidence the op *ended*).
            tracker.finish(op_error)
            recorder.end(take_span)  # no-op if already closed
            event_loop.close()
        snapshot = cls(path=path, pg=pg)
        snapshot._metadata = metadata
        return snapshot

    @classmethod
    def async_take(
        cls,
        path: str,
        app_state: AppState,
        pg: Optional[Any] = None,
        replicated: Optional[List[str]] = None,
        incremental_base: Optional[Any] = None,
        record_digests: bool = False,
        _custom_array_prepare_func=None,
    ) -> "PendingSnapshot":
        """Pipelined checkpoint whose training-visible span is independent
        of checkpoint size (docs/async.md): by default the call returns as
        soon as the manifest/plan collectives finish and a consistent
        device snapshot is pinned — on-device clones of the leaves the
        write plan needs (dispatched, not awaited), host copies of mutable
        numpy leaves — and the ENTIRE staging (D2H + serialize) plus
        storage drain and commit run on a background thread through a
        slab-bounded host staging pool (``scheduler.StagingPool``). The
        application may mutate, donate, or delete the live arrays freely
        once this returns. ``PendingSnapshot.wait(phase=)`` distinguishes
        the ``"staged"`` point (D2H done; host buffers hold the bytes)
        from the default ``"committed"`` barrier.

        ``TORCHSNAPSHOT_TPU_ASYNC_DEVICE_SNAPSHOT=0`` restores the
        pre-deferral behavior (staging completes before this returns —
        reference snapshot.py:245-314 — costing no transient HBM copy).
        ``incremental_base``/``record_digests`` as in :meth:`take`."""
        import uuid

        from .cas import cas_eligible

        op_begin = time.monotonic()
        pg_wrapper = PGWrapper(pg)
        # Same combined broadcast as the sync take: rank-0 path wins and
        # the CAS layout decision is agreed before any write exists.
        path, cas_on = pg_wrapper.broadcast_object(
            (path, cas_eligible(path))
        )
        # Unique per-take commit nonce: barrier keys from any earlier take
        # to the same path (including failed ones) must never alias this
        # take's barrier.
        commit_nonce = pg_wrapper.broadcast_object(uuid.uuid4().hex)
        # Error-reporting handle on the SAME commit barrier the background
        # commit threads key off this nonce: staging (_take_impl) includes
        # rank-0-only work such as replication verification, and a rank
        # that fails there must poison the barrier before raising — peers
        # whose staging succeeded already have commit threads waiting at
        # arrive(), and without the report they block out the full store
        # timeout.
        barrier = _nonce_barrier(
            f"__snapshot_commit/{commit_nonce}", pg_wrapper
        )
        event_loop = asyncio.new_event_loop()
        counter_baseline = telemetry.metrics().counters_snapshot()
        tunables_at_start = knobs.tunable_snapshot()
        recorder = _trace_recorder()
        trace_mark = recorder.mark()
        storage = _maybe_cas_storage(
            url_to_storage_plugin(path), path, cas_on
        )
        tracker = _progress.track("async_take", path, pg_wrapper.get_rank())
        defer_staging = knobs.is_async_device_snapshot_enabled()
        try:
            with recorder.span(
                telemetry.names.SPAN_ASYNC_TAKE_STAGE,
                path=path,
                rank=pg_wrapper.get_rank(),
            ), _reporting_to(barrier, "async take staging"):
                pending_io_work, metadata = cls._take_impl(
                    path=path,
                    app_state=app_state,
                    pg_wrapper=pg_wrapper,
                    replicated=replicated or [],
                    storage=storage,
                    event_loop=event_loop,
                    is_async_snapshot=True,
                    incremental_base=incremental_base,
                    record_digests=record_digests,
                    _custom_array_prepare_func=_custom_array_prepare_func,
                    progress_tracker=tracker,
                    defer_staging=defer_staging,
                )
        except BaseException as e:
            # The failure path owns the loop/storage (no PendingSnapshot
            # thread will ever run to close them).
            tracker.finish(e)
            try:
                event_loop.run_until_complete(storage.close())
            except Exception:  # noqa: BLE001 - already failing
                pass
            event_loop.close()
            raise
        return PendingSnapshot(
            path=path,
            pending_io_work=pending_io_work,
            pg_wrapper=pg_wrapper,
            metadata=metadata,
            storage=storage,
            event_loop=event_loop,
            commit_nonce=commit_nonce,
            counter_baseline=counter_baseline,
            trace_mark=trace_mark,
            progress_tracker=tracker,
            op_begin=op_begin,
            tunables=tunables_at_start,
        )

    @classmethod
    def _take_impl(
        cls,
        path: str,
        app_state: AppState,
        pg_wrapper: PGWrapper,
        replicated: List[str],
        storage: StoragePlugin,
        event_loop: asyncio.AbstractEventLoop,
        is_async_snapshot: bool,
        incremental_base: Optional[Any] = None,
        record_digests: bool = False,
        _custom_array_prepare_func=None,
        progress_tracker: Optional[_progress.ProgressTracker] = None,
        defer_staging: bool = False,
    ) -> Tuple["PendingIOWork | DeferredIOWork", Optional[SnapshotMetadata]]:
        """Shared take core (reference snapshot.py:316-440). The returned
        metadata is None on non-leader ranks (manifests gather to rank 0
        only; see :func:`_gather_manifest`).

        With ``defer_staging`` (device-snapshot async takes), no staging
        runs here: the write plan's sources are captured (on-device
        clones / host copies) and the returned :class:`DeferredIOWork`
        runs the whole pool-bounded pipeline on the background commit
        thread. Collectives still all happen on this (the calling)
        thread either way."""
        _validate_app_state(app_state)
        rank = pg_wrapper.get_rank()
        world_size = pg_wrapper.get_world_size()
        replicated_patterns = _coalesce_replicated(replicated, pg_wrapper)

        # RNG first: capturing other statefuls must not perturb what gets
        # saved as the RNG state (reference invariant snapshot.py:340-346).
        # With explicit jax keys nothing mutates behind our back, but
        # .state_dict() of arbitrary statefuls may consume entropy. The
        # capture is collective-free and happens HERE, out of band; the
        # RNG key keeps its *sorted* slot in the barriered loop below —
        # which key is the RNG one is rank-local knowledge, so reordering
        # the loop by it would diverge the barrier/collective schedule on
        # ranks that lack (or name differently) the RngState.
        rng_key_and_state = _pop_rng_state(app_state)
        rng_capture = None
        if rng_key_and_state is not None:
            rng_key, rng_stateful = rng_key_and_state
            rng_capture = flatten(rng_stateful.state_dict(), prefix=rng_key)
        flattened_global: Dict[str, Any] = {}
        rank_manifest: Manifest = {}

        keys = _gather_keys(app_state, pg_wrapper)
        for key in keys:
            if rng_key_and_state is not None and key == rng_key_and_state[0]:
                container_entries, flattened = rng_capture
                pg_wrapper.barrier()
                rank_manifest.update(container_entries)
                flattened_global.update(flattened)
                continue
            stateful = app_state.get(key)
            if stateful is None:
                pg_wrapper.barrier()
                continue
            state_dict = stateful.state_dict()
            # Statefuls are captured in globally-sorted key order with a
            # barrier in between: .state_dict() may itself run collectives
            # (reference snapshot.py:353-370).
            pg_wrapper.barrier()
            container_entries, flattened = flatten(state_dict, prefix=key)
            rank_manifest.update(container_entries)
            flattened_global.update(flattened)

        replicated_paths = _calculate_replicated_entries(
            flattened_global,
            replicated_patterns,
            pg_wrapper,
            inferred=_infer_replicated_paths(flattened_global, world_size),
        )

        incr_ctx = None
        if incremental_base is not None or record_digests:
            from .incremental import IncrementalTakeContext

            incr_ctx = IncrementalTakeContext.build(
                path, incremental_base, rank
            )
            # One launch pass before any stager exists: device digests
            # dispatch asynchronously and overlap each other; skip
            # decisions must precede D2H prefetches.
            incr_ctx.launch(flattened_global, _custom_array_prepare_func)
            # Replicated entries are asserted equal at consolidation, so
            # per-rank degradation (unreadable base, failed digest launch)
            # must degrade every rank identically.
            incr_ctx.synchronize(pg_wrapper, replicated_paths)

        write_reqs: List[WriteReq] = []
        for logical_path, leaf in flattened_global.items():
            entry, reqs = prepare_write(
                obj=leaf,
                logical_path=logical_path,
                rank=rank,
                replicated=logical_path in replicated_paths,
                is_async_snapshot=is_async_snapshot,
                array_prepare_func=_custom_array_prepare_func,
                incremental=(
                    incr_ctx.plan_for(logical_path) if incr_ctx else None
                ),
            )
            rank_manifest[logical_path] = entry
            write_reqs.extend(reqs)

        if world_size > 1:
            from .partitioner import partition_write_reqs

            rank_manifest, write_reqs = partition_write_reqs(
                entries=rank_manifest, write_reqs=write_reqs, pg_wrapper=pg_wrapper
            )

        if knobs.is_batching_enabled():
            from .batcher import batch_write_requests

            entry_list = list(rank_manifest.values())
            entry_list, write_reqs = batch_write_requests(entry_list, write_reqs)
            rank_manifest = dict(zip(rank_manifest.keys(), entry_list))

        # Budget agreement runs BEFORE the manifest gather on purpose: the
        # gather's consolidation/validation is the last rank-0-only
        # failure point of staging, and it must also be the last wrapped
        # collective — a peer must have nothing left between its
        # (non-blocking) gather send and the error-propagating commit
        # barrier, or a rank-0 failure strands it inside an op-seq
        # collective poll (a 300 s store timeout) where the reported
        # error is invisible.
        memory_budget_bytes = get_process_memory_budget_bytes(pg_wrapper)

        global_manifest = _gather_manifest(rank_manifest, pg_wrapper)
        # Non-leader ranks carry no metadata object: the snapshot they
        # return lazy-loads the committed global manifest from storage
        # (Snapshot.metadata), which is both cheaper than shipping it
        # through the coordinator and guaranteed consistent with what
        # rank 0 committed.
        metadata = (
            SnapshotMetadata(
                version=__version__,
                world_size=world_size,
                manifest=global_manifest,
            )
            if global_manifest is not None
            else None
        )

        if defer_staging:
            # Device-snapshot point: pin every write source (on-device
            # clone dispatch for jax leaves — cheap; host copies for
            # mutable numpy leaves; eager pickles for objects), then
            # hand the un-staged plan to the background drain. From the
            # caller's return onward the live arrays are free to be
            # mutated, donated, or deleted.
            recorder = _trace_recorder()
            with recorder.span(
                telemetry.names.SPAN_DEVICE_CAPTURE,
                rank=rank,
                reqs=len(write_reqs),
            ):
                captured = capture_write_reqs(write_reqs)
            logger.debug(
                "async take captured %d device/host sources for %d "
                "deferred write requests",
                captured,
                len(write_reqs),
            )
            if progress_tracker is not None:
                progress_tracker.set_phase("captured")
            pending_io_work: "PendingIOWork | DeferredIOWork" = (
                DeferredIOWork(
                    write_reqs=write_reqs,
                    storage=storage,
                    memory_budget_bytes=memory_budget_bytes,
                    rank=rank,
                    progress=progress_tracker,
                )
            )
        else:
            pending_io_work = sync_execute_write_reqs(
                write_reqs=write_reqs,
                storage=storage,
                memory_budget_bytes=memory_budget_bytes,
                rank=rank,
                event_loop=event_loop,
                progress=progress_tracker,
            )
        if incr_ctx is not None:
            # Referenced blobs were not rewritten, so their checksums come
            # from the base snapshot's tables (keyed by the ref location):
            # restore-time verification must cover unwritten bytes too.
            # Deferred to finalize_checksums (the background commit thread
            # for async takes) — it reads base tables from storage, which
            # must not delay the staging-done return.
            pending_io_work.checksum_finalizer = (
                lambda: incr_ctx.inherit_checksums(pending_io_work.checksums)
            )
        from .cas import CASStoragePlugin

        if isinstance(storage, CASStoragePlugin):
            # CAS takes additionally re-home the table entries from the
            # original write paths to the chunk locations the rewritten
            # manifest will name — composed AFTER the incremental
            # inherit (whose entries already carry chunk-ref keys).
            prev_finalizer = pending_io_work.checksum_finalizer

            def _cas_finalize(prev=prev_finalizer) -> None:
                if prev is not None:
                    prev()
                storage.rekey_checksums(pending_io_work.checksums)

            pending_io_work.checksum_finalizer = _cas_finalize
        return pending_io_work, metadata

    @staticmethod
    def _write_snapshot_metadata(
        metadata: SnapshotMetadata,
        storage: StoragePlugin,
        event_loop: asyncio.AbstractEventLoop,
    ) -> None:
        # CAS takes: fold every rank's committed ``cas/{rank}`` chunk
        # map into the manifest first — entry locations become
        # ``../chunks/<key>`` parent refs, after which the snapshot
        # reads like any other to every consumer. No-op for legacy
        # takes (the wrapper's absence is the signal).
        from .cas import maybe_rewrite_manifest

        event_loop.run_until_complete(
            maybe_rewrite_manifest(metadata, storage)
        )
        # Kill points bracketing the commit write: before, the step
        # must read as never-happened; after, as committed (whether or
        # not anything downstream — index, mirror, peer — ever ran).
        _crashpoint(telemetry.names.CRASH_PRE_COMMIT_MARKER)
        # Committed as JSON — a YAML subset (reference manifest.py:19-22
        # invariant), so any YAML tooling still reads it, and loading takes
        # the fast json.loads path instead of a YAML parse.
        metadata_bytes = metadata.to_json().encode("utf-8")
        event_loop.run_until_complete(
            storage.write(WriteIO(path=SNAPSHOT_METADATA_FNAME, buf=metadata_bytes))
        )
        _crashpoint(telemetry.names.CRASH_COMMIT_MARKER)

    # ------------------------------------------------------------------
    # metadata / manifest
    # ------------------------------------------------------------------

    @property
    def metadata(self) -> SnapshotMetadata:
        if self._metadata is None:
            event_loop = asyncio.new_event_loop()
            try:
                storage = url_to_storage_plugin(self.path)
                from .io_types import ReadIO

                read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
                event_loop.run_until_complete(storage.read(read_io))
                assert read_io.buf is not None
                self._metadata = SnapshotMetadata.from_yaml(
                    bytes(read_io.buf).decode("utf-8")
                )
                event_loop.run_until_complete(storage.close())
            finally:
                event_loop.close()
        return self._metadata

    def get_manifest(self) -> Manifest:
        import copy

        return copy.deepcopy(self.metadata.manifest)

    def _get_checksum_table(
        self, storage: StoragePlugin, event_loop: asyncio.AbstractEventLoop
    ):
        """Merged blob digests, fetched at most once per Snapshot instance
        (repeated read_object calls must not re-read every rank's table)."""
        if self._checksum_table_cache is False:
            self._checksum_table_cache = _get_checksum_table_impl(
                self.metadata.world_size, storage, event_loop
            )
        return self._checksum_table_cache

    # ------------------------------------------------------------------
    # restore
    # ------------------------------------------------------------------

    def restore(self, app_state: AppState) -> None:
        """In-place restore (reference snapshot.py:442-491)."""
        import uuid

        _validate_app_state(app_state)
        pg_wrapper = PGWrapper(self._pg_arg)
        rank = pg_wrapper.get_rank()
        # Error-propagating inter-stateful barriers (same design as the
        # take commit barrier): a rank whose reads fail — bit rot, a
        # CRC mismatch — reports before raising, so peers waiting at the
        # current key's barrier abandon instead of blocking out the full
        # store timeout.
        restore_nonce = None
        fanout_agreed = False
        if pg_wrapper.get_world_size() > 1:
            # The fan-out enablement rides the nonce broadcast: ONE
            # agreement collective, before any failure point, so rank
            # 0's knob reading decides for the whole job (env skew can
            # never diverge the schedule) and a later setup failure can
            # never leave the shared op-seq counter half-advanced.
            restore_nonce, fanout_agreed = pg_wrapper.broadcast_object(
                (uuid.uuid4().hex, knobs.is_fanout_restore_enabled())
            )
        counter_baseline = telemetry.metrics().counters_snapshot()
        tunables_at_start = knobs.tunable_snapshot()
        recorder = _trace_recorder()
        trace_mark = recorder.mark()
        restore_span = recorder.begin(
            telemetry.names.SPAN_RESTORE, path=self.path, rank=rank
        )
        tracker = _progress.track("restore", self.path, rank)
        op_error: Optional[BaseException] = None
        pipeline_sink: List[dict] = []

        def key_barrier(i: int) -> Optional[StoreBarrier]:
            if restore_nonce is None:
                return None
            return _nonce_barrier(
                f"__restore/{restore_nonce}/{i}", pg_wrapper
            )

        # Cold-start attribution: the envelope work before the first
        # storage byte can move — event-loop spin-up, plugin open, and
        # the native digest library's first load — timed separately so
        # a first-trial restore that dwarfs warm trials convicts its
        # cause in the report (``cold_start``/``cold_start_s``) instead
        # of leaving the gap a guess.
        cold_start: Dict[str, float] = {}
        _cold_t = time.monotonic()
        event_loop = asyncio.new_event_loop()
        cold_start["event_loop_s"] = time.monotonic() - _cold_t
        try:
            _cold_t = time.monotonic()
            storage = url_to_storage_plugin(self.path)
            cold_start["plugin_open_s"] = time.monotonic() - _cold_t
            _cold_t = time.monotonic()
            from .integrity import _alg_available

            _alg_available("crc32c")  # first call loads the native lib
            cold_start["native_load_s"] = time.monotonic() - _cold_t
            # Peer-tier ladder (docs/peer.md): when surviving peers hold
            # this step's shards in RAM, reads resolve peer -> fast ->
            # durable per blob, digest-verified. Build is rank-local
            # (inventory RPCs, no collectives), so peers building or
            # not building the ladder independently can never diverge
            # the restore schedule; every failure degrades to None.
            from .tiered import peer as _peer_tier

            peer_ctx = _peer_tier.build_restore_context(self.path)
            if peer_ctx is not None:
                storage = peer_ctx.wrap(storage)
            # Collectives FIRST, storage reads second (round 5; same
            # principle as _take_impl's budget-before-gather order): the
            # metadata and checksum-table reads are the restore's
            # pre-coordination failure points, and a rank failing there
            # must not leave peers inside an op-seq collective poll —
            # where a reported error is invisible. After the reorder,
            # only local work sits between a rank's setup reads and the
            # first error-aware key barrier, so setup failures reported
            # into key barrier 0 abandon peers in seconds.
            rng_key_and_state = _pop_rng_state(app_state)
            rng_key = rng_key_and_state[0] if rng_key_and_state else None
            keys = _gather_keys(app_state, pg_wrapper)
            memory_budget_bytes = get_process_memory_budget_bytes(pg_wrapper)
            setup_barrier = key_barrier(0) if keys else None
            fanout_ctx = None
            with _reporting_to(setup_barrier, "restore setup"):
                available = get_manifest_for_rank(self.metadata, rank)
                checksum_table = self._get_checksum_table(storage, event_loop)
                # Single-reader fan-out (docs/restore.md): enablement was
                # broadcast-agreed above; the owner table is derived
                # deterministically from the committed manifest (same
                # bytes on every rank), inside the error-aware setup
                # window like every other failure-prone setup read.
                if fanout_agreed:
                    from .fanout import FanoutRestoreContext

                    fanout_ctx = FanoutRestoreContext.build(
                        self.metadata.manifest, pg_wrapper
                    )
                    if not fanout_ctx.owners:
                        fanout_ctx = None  # nothing shard-shaped to fan out
            for i, key in enumerate(keys):
                stateful = app_state.get(key)
                if key == rng_key:
                    stateful = None  # restored last, below
                barrier = key_barrier(i)
                with _reporting_to(barrier, "restore"):
                    # Plan first so the fan-out exchange (a round every
                    # rank runs in the same order, plan or no plan)
                    # knows this rank's needed byte windows. The
                    # exchange's waits poll THIS round's barrier error
                    # key, so a peer failing anywhere in this block
                    # aborts the round in seconds (_reporting_to writes
                    # that key on the way out).
                    plan = None
                    if stateful is not None:
                        plan = self._plan_stateful_load(
                            key, stateful, available, memory_budget_bytes
                        )
                    round_locs: List[str] = []
                    if fanout_ctx is not None:
                        round_locs = fanout_ctx.exchange(
                            plan.read_reqs if plan is not None else [],
                            storage,
                            event_loop,
                            rendezvous_prefix=(
                                f"__restore/{restore_nonce}/{i}"
                            ),
                        )
                    try:
                        if plan is not None:
                            self._execute_load_plan(
                                plan,
                                storage=storage,
                                memory_budget_bytes=memory_budget_bytes,
                                event_loop=event_loop,
                                rank=rank,
                                checksum_table=checksum_table,
                                pipeline_sink=pipeline_sink,
                                progress_tracker=tracker,
                                fanout_ctx=fanout_ctx,
                            )
                    finally:
                        if fanout_ctx is not None:
                            fanout_ctx.drop(round_locs)
                if barrier is not None:
                    barrier.arrive()
                    barrier.depart()
            # RNG state is restored last so that load_state_dict side
            # effects of other statefuls cannot disturb it (reference
            # snapshot.py:478-489).
            if rng_key_and_state is not None:
                key, stateful = rng_key_and_state
                self._load_stateful(
                    key=key,
                    stateful=stateful,
                    available=available,
                    storage=storage,
                    memory_budget_bytes=memory_budget_bytes,
                    event_loop=event_loop,
                    rank=rank,
                    checksum_table=checksum_table,
                    pipeline_sink=pipeline_sink,
                    progress_tracker=tracker,
                )
            event_loop.run_until_complete(storage.close())
            recorder.end(restore_span)
            pipeline = telemetry.merge_pipeline_telemetry(pipeline_sink)
            _merge_fanout_telemetry(pipeline, fanout_ctx)
            _merge_peer_telemetry(pipeline, peer_ctx)
            # Round the parts BEFORE summing: the report layer rounds
            # each part to 6dp on serialization, so deriving the total
            # from the raw values can disagree with the serialized
            # parts by 1e-06 for unlucky timings.
            cold_start = {k: round(v, 6) for k, v in cold_start.items()}
            pipeline["cold_start"] = cold_start
            pipeline["cold_start_s"] = round(sum(cold_start.values()), 6)
            _emit_snapshot_report(
                kind="restore",
                path=self.path,
                pg_wrapper=pg_wrapper,
                pipeline=pipeline,
                counter_baseline=counter_baseline,
                nonce=restore_nonce,
                trace_mark=trace_mark,
                tunables=tunables_at_start,
            )
        except BaseException as e:
            op_error = e
            raise
        finally:
            tracker.finish(op_error)
            recorder.end(restore_span)  # no-op if already closed
            event_loop.close()

    def async_restore(self, app_state: AppState) -> "PendingRestore":
        """Pipelined restore: storage reads (and H2D placement) run on a
        background thread; ``wait()`` applies the restored state dicts.

        No reference counterpart (its restore is synchronous only). The
        use case is TPU cold-start: restore I/O overlaps the train-step
        compilation that dominates restore-to-step0, e.g.::

            pending = snapshot.async_restore(app_state)
            compiled = train_step.lower(state, batch).compile()  # overlaps
            pending.wait()                                        # applies

        State capture (``state_dict()``) and the read *planning* happen on
        the calling thread before this returns — collectives stay on the
        main thread, mirroring async_take's discipline (reference
        snapshot.py:948) — so until ``wait()`` returns, the application's
        jax leaves are untouched (fresh host buffers absorb the reads;
        ``wait()`` re-raises background failures before applying anything,
        leaving app state unmodified on error). In-place numpy leaves are
        the exception: they are read into directly and must not be used
        until ``wait()`` returns."""
        _validate_app_state(app_state)
        pg_wrapper = PGWrapper(self._pg_arg)
        rank = pg_wrapper.get_rank()
        trace_mark = _trace_recorder().mark()
        memory_budget_bytes = get_process_memory_budget_bytes(pg_wrapper)

        rng_key_and_state = _pop_rng_state(app_state)
        rng_key = rng_key_and_state[0] if rng_key_and_state else None
        # The key list (and hence the barrier schedule) must be identical
        # on every rank; the RNG key is rank-local knowledge, so it keeps
        # its sorted slot here and only its *apply* is deferred (to last,
        # after all barriers — RngState application is collective-free),
        # exactly like the sync path.
        keys = _gather_keys(app_state, pg_wrapper)

        # Nonce for the plan AND apply phases' error-propagating barriers
        # — agreed BEFORE any storage read or planning (round 5), so the
        # whole setup runs with an error-aware rendezvous in place: the
        # metadata read and per-key planning report failures into the
        # plan barriers below, and peers abandon there in seconds instead
        # of stranding inside a plain op-seq barrier (where a reported
        # error is invisible) for the full store timeout.
        restore_nonce = None
        fanout_agreed = False
        if pg_wrapper.get_world_size() > 1:
            import uuid

            # Fan-out enablement rides the nonce broadcast (one
            # agreement collective before any failure point; rank 0's
            # knob decides for the job) — same shape as the sync path.
            restore_nonce, fanout_agreed = pg_wrapper.broadcast_object(
                (uuid.uuid4().hex, knobs.is_fanout_restore_enabled())
            )

        def plan_barrier(i: int) -> Optional[StoreBarrier]:
            if restore_nonce is None:
                return None
            return _nonce_barrier(
                f"__restore/{restore_nonce}/plan{i}", pg_wrapper
            )

        setup_barrier = plan_barrier(0) if keys else None
        with _reporting_to(setup_barrier, "async restore setup"):
            available = get_manifest_for_rank(self.metadata, rank)
            world_size = self.metadata.world_size

        plans: Dict[str, _StatefulLoadPlan] = {}
        for i, key in enumerate(keys):
            barrier = plan_barrier(i)
            with _reporting_to(barrier, "async restore planning"):
                stateful = app_state.get(key)
                if stateful is not None:
                    plan = self._plan_stateful_load(
                        key, stateful, available, memory_budget_bytes
                    )
                    if plan is not None:
                        plans[key] = plan
            # state_dict() may itself run collectives: keep the capture
            # globally ordered (reference snapshot.py:353-370). The
            # barrier is error-aware: a peer's planning failure abandons
            # this rank here instead of at a store timeout.
            if barrier is not None:
                barrier.arrive()
                barrier.depart()

        # Single-reader fan-out, async flavor: the exchange is a
        # cross-rank rendezvous, so it runs HERE — on the calling
        # thread, after every plan exists — covering all plans in one
        # round; the owner-side unique-shard fetches land in this
        # (visible) span and the background pipeline then reads them
        # from the cache (no rendezvous off the main thread). The
        # round's error-aware barrier keeps a failing rank from
        # stranding its peers in the exchange.
        fanout_ctx = None
        if fanout_agreed:
            exchange_prefix = f"__restore/{restore_nonce}/fanout"
            exchange_barrier = _nonce_barrier(exchange_prefix, pg_wrapper)
            with _reporting_to(exchange_barrier, "fan-out exchange"):
                from .fanout import FanoutRestoreContext

                fanout_ctx = FanoutRestoreContext.build(
                    self.metadata.manifest, pg_wrapper
                )
                if fanout_ctx.owners:
                    reqs = [
                        r for plan in plans.values() for r in plan.read_reqs
                    ]
                    exchange_loop = asyncio.new_event_loop()
                    try:
                        exchange_storage = url_to_storage_plugin(self.path)
                        try:
                            fanout_ctx.exchange(
                                reqs,
                                exchange_storage,
                                exchange_loop,
                                rendezvous_prefix=exchange_prefix,
                            )
                        finally:
                            exchange_loop.run_until_complete(
                                exchange_storage.close()
                            )
                    finally:
                        exchange_loop.close()
                else:
                    fanout_ctx = None  # nothing shard-shaped to fan out

        # Peer-tier ladder, async flavor: the owner table is assembled
        # on the calling thread (inventory RPCs only — cheap, and no
        # rendezvous belongs on the read thread); the background
        # pipeline then pulls table-resident blobs from peer RAM.
        from .tiered import peer as _peer_tier

        peer_ctx = _peer_tier.build_restore_context(self.path)

        return PendingRestore(
            path=self.path,
            keys=keys,
            plans=plans,
            pg_wrapper=pg_wrapper,
            memory_budget_bytes=memory_budget_bytes,
            rank=rank,
            world_size=world_size,
            rng_key=rng_key,
            restore_nonce=restore_nonce,
            counter_baseline=telemetry.metrics().counters_snapshot(),
            trace_mark=trace_mark,
            tunables=knobs.tunable_snapshot(),
            fanout_ctx=fanout_ctx,
            peer_ctx=peer_ctx,
        )

    def _load_stateful(
        self,
        key: str,
        stateful: Stateful,
        available: Manifest,
        storage: StoragePlugin,
        memory_budget_bytes: int,
        event_loop: asyncio.AbstractEventLoop,
        rank: int,
        checksum_table=None,
        pipeline_sink: Optional[List[dict]] = None,
        progress_tracker: Optional[_progress.ProgressTracker] = None,
    ) -> None:
        """Memory-frugal restore of one stateful: reuse the leaves already
        allocated in its current state dict as read destinations so peak
        footprint stays ~1x (reference snapshot.py:668-766).
        ``pipeline_sink`` collects the read pipeline's telemetry for the
        caller's SnapshotReport. Plan + execute in one call, with no
        fan-out — the entry point for loads outside the shared barrier
        schedule (the RNG stateful, restored rank-locally last)."""
        plan = self._plan_stateful_load(
            key, stateful, available, memory_budget_bytes
        )
        if plan is None:
            return
        self._execute_load_plan(
            plan,
            storage=storage,
            memory_budget_bytes=memory_budget_bytes,
            event_loop=event_loop,
            rank=rank,
            checksum_table=checksum_table,
            pipeline_sink=pipeline_sink,
            progress_tracker=progress_tracker,
        )

    def _execute_load_plan(
        self,
        plan: "_StatefulLoadPlan",
        storage: StoragePlugin,
        memory_budget_bytes: int,
        event_loop: asyncio.AbstractEventLoop,
        rank: int,
        checksum_table=None,
        pipeline_sink: Optional[List[dict]] = None,
        progress_tracker: Optional[_progress.ProgressTracker] = None,
        fanout_ctx=None,
    ) -> None:
        """Run one planned stateful load's read pipeline and apply it.
        With ``fanout_ctx`` (an exchange for this plan already ran), the
        pipeline reads exchanged shard blobs from the fan-out cache and
        only the rest from the real plugin."""
        read_reqs = plan.read_reqs
        # The rank's pre-batching destination bytes — the denominator of
        # the read-amplification metric restore reports carry.
        bytes_needed = sum(_req_needed_bytes(r) for r in read_reqs)
        if knobs.is_batching_enabled():
            from .batcher import batch_read_requests

            read_reqs = batch_read_requests(read_reqs)
        # Streaming placement: completed leaves device_put while the
        # remaining reads are still in flight.
        placer = _StreamingPlacer()
        placer.register_plan(plan)
        pipeline_telemetry = sync_execute_read_reqs(
            read_reqs=read_reqs,
            storage=(
                fanout_ctx.wrap(storage) if fanout_ctx is not None else storage
            ),
            memory_budget_bytes=memory_budget_bytes,
            rank=rank,
            event_loop=event_loop,
            checksum_table=checksum_table,
            on_req_complete=placer.on_req_complete,
            progress=progress_tracker,
            classify_read=(
                fanout_ctx.classify_read if fanout_ctx is not None else None
            ),
        )
        pipeline_telemetry["bytes_needed"] = bytes_needed
        if pipeline_sink is not None:
            pipeline_sink.append(pipeline_telemetry)
        placer.flush()
        plan.finish_reads()
        plan.apply()

    def _plan_stateful_load(
        self,
        key: str,
        stateful: Stateful,
        available: Manifest,
        memory_budget_bytes: int,
    ) -> Optional["_StatefulLoadPlan"]:
        """Pure planning for one stateful's restore: captures its current
        state dict, picks/allocates read destinations, builds read
        requests + deferred conversions. No storage I/O happens here."""
        from .flatten import _encode

        encoded_key = _encode(key)
        entries = {
            path: entry
            for path, entry in available.items()
            if path == encoded_key or path.startswith(encoded_key + "/")
        }
        if not entries:
            logger.warning("No entries found for stateful %r; skipping", key)
            return None

        current_container_entries, current_flattened = flatten(
            stateful.state_dict(), prefix=key
        )
        del current_container_entries

        read_reqs = []
        restored: Dict[str, Any] = {}
        container_entries: Manifest = {}
        # Per-leaf groups of (reads, deferred conversion): the conversion
        # (np buffer -> the leaf flavor the application currently holds,
        # e.g. a jax device array) may run as soon as the group's reads
        # complete — streaming placement — or all together after.
        groups: List[_LeafGroup] = []

        for path, entry in entries.items():
            if is_container_entry(entry):
                container_entries[path] = entry
                continue
            if isinstance(entry, PrimitiveEntry):
                restored[path] = entry.get_value()
                continue
            current_leaf = current_flattened.get(path)
            if isinstance(entry, ObjectEntry):

                def _cb(obj: Any, path: str = path) -> None:
                    restored[path] = obj

                read_reqs.extend(prepare_read(entry, callback=_cb))
                continue
            if isinstance(entry, ShardedArrayEntry):
                from .sharded_io_preparer import ShardedArrayIOPreparer

                reqs, finalize = ShardedArrayIOPreparer.prepare_read_into(
                    entry,
                    current_leaf,
                    restored,
                    path,
                    buffer_size_limit_bytes=memory_budget_bytes,
                )
                read_reqs.extend(reqs)
                if finalize is not None:
                    groups.append(_LeafGroup(reqs, finalize))
                continue
            assert isinstance(entry, (ArrayEntry, ChunkedArrayEntry))
            dst, convert, owned = _restore_destination(entry, current_leaf)
            reqs = prepare_read(entry, obj_out=dst, dest_owned=owned)
            read_reqs.extend(reqs)
            if convert is None:
                restored[path] = dst
            else:

                def _pp(
                    batch: Optional["_PlacementBatch"],
                    path: str = path,
                    dst: np.ndarray = dst,
                    convert: Callable[..., Any] = convert,
                ) -> None:
                    out = convert(dst, batch)
                    if isinstance(out, _PlacementSlot):
                        assert batch is not None
                        batch.defer(
                            lambda: restored.__setitem__(path, out.value)
                        )
                    else:
                        restored[path] = out

                groups.append(_LeafGroup(reqs, _pp))

        return _StatefulLoadPlan(
            key=key,
            stateful=stateful,
            container_entries=container_entries,
            restored=restored,
            groups=groups,
            read_reqs=read_reqs,
        )

    # ------------------------------------------------------------------
    # read_object
    # ------------------------------------------------------------------

    def read_object(
        self,
        path: str,
        obj_out: Optional[Any] = None,
        memory_budget_bytes: Optional[int] = None,
        sharding: Optional[Any] = None,
    ) -> Any:
        """Random access to a single object by manifest path
        ``"RANK/STATEFUL/KEY..."`` (reference snapshot.py:507-612).

        ``sharding`` places a ShardedArray entry directly under an
        arbitrary jax ``Sharding`` — any layout, any world size,
        no template leaf needed (reshard-on-read, docs/restore.md);
        only the byte windows overlapping this process's addressable
        devices are read. Mutually exclusive with ``obj_out`` — an
        in-place destination defines its own layout, and silently
        preferring one would leave the other untouched."""
        if sharding is not None and obj_out is not None:
            raise ValueError(
                "read_object: pass either obj_out (in-place restore into "
                "your array) or sharding (fresh placement under a target "
                "Sharding), not both"
            )
        rank_str, _, logical_path = path.partition("/")
        try:
            rank = int(rank_str)
        except ValueError:
            raise ValueError(
                f"read_object path must start with a rank (got {path!r})"
            ) from None
        available = get_manifest_for_rank(self.metadata, rank)
        if logical_path not in available:
            raise ValueError(
                f"{logical_path!r} is not a valid entry for rank {rank} "
                f"(candidates: {sorted(available)[:20]}...)"
            )
        entry = available[logical_path]
        if isinstance(entry, PrimitiveEntry):
            return entry.get_value()
        if is_container_entry(entry):
            raise ValueError(
                f"{logical_path!r} is a container; read leaf paths instead"
            )

        event_loop = asyncio.new_event_loop()
        try:
            storage = url_to_storage_plugin(self.path)
            restored: Dict[str, Any] = {}
            result_path = "__read_object__"
            finalize: Optional[Callable[[], None]] = None

            if isinstance(entry, ObjectEntry):
                read_reqs = prepare_read(
                    entry, callback=lambda o: restored.__setitem__(result_path, o)
                )
            elif isinstance(entry, ShardedArrayEntry):
                from .sharded_io_preparer import ShardedArrayIOPreparer

                read_reqs, finalize = ShardedArrayIOPreparer.prepare_read_into(
                    entry,
                    obj_out,
                    restored,
                    result_path,
                    buffer_size_limit_bytes=memory_budget_bytes,
                    target_sharding=sharding,
                )
            else:
                assert isinstance(entry, (ArrayEntry, ChunkedArrayEntry))
                dst, convert, owned = _restore_destination(entry, obj_out)
                if sharding is not None and obj_out is None:
                    import jax

                    target = sharding

                    def convert(
                        host: np.ndarray, batch=None, _t=target
                    ) -> Any:
                        return jax.device_put(host, _t)

                read_reqs = prepare_read(
                    entry,
                    obj_out=dst,
                    buffer_size_limit_bytes=memory_budget_bytes,
                    dest_owned=owned,
                )
                if convert is None:
                    restored[result_path] = dst
                else:
                    finalize = lambda: restored.__setitem__(  # noqa: E731
                        result_path, convert(dst)
                    )

            if knobs.is_batching_enabled():
                from .batcher import batch_read_requests

                read_reqs = batch_read_requests(read_reqs)

            sync_execute_read_reqs(
                read_reqs=read_reqs,
                storage=storage,
                memory_budget_bytes=memory_budget_bytes
                or get_process_memory_budget_bytes(None),
                rank=rank,
                event_loop=event_loop,
                checksum_table=self._get_checksum_table(storage, event_loop),
            )
            if finalize is not None:
                finalize()
            event_loop.run_until_complete(storage.close())
            return restored[result_path]
        finally:
            event_loop.close()


class _PlacementSlot:
    """Future for one array's device placement inside a _PlacementBatch."""

    __slots__ = ("_batch", "_idx")

    def __init__(self, batch: "_PlacementBatch", idx: int) -> None:
        self._batch = batch
        self._idx = idx

    @property
    def value(self) -> Any:
        return self._batch._results[self._idx]


class _PlacementBatch:
    """Batches every restore-time H2D placement into ONE ``jax.device_put``
    dispatch. Per-leaf device_put calls pay per-dispatch latency once per
    leaf (hundreds of calls for a real model's cold restore); jax's
    batched device_put moves the same bytes in a single dispatch.
    ``put`` registers (host array, target sharding/device) and returns a
    slot; ``defer`` registers work that reads slots; ``run`` executes the
    batched transfer then the deferred work."""

    def __init__(self) -> None:
        self._values: List[Any] = []
        self._targets: List[Any] = []
        self._deferred: List[Callable[[], None]] = []
        self._results: List[Any] = []

    def put(self, value: Any, target: Any) -> _PlacementSlot:
        self._values.append(value)
        self._targets.append(target)
        return _PlacementSlot(self, len(self._values) - 1)

    def defer(self, fn: Callable[[], None]) -> None:
        self._deferred.append(fn)

    def run(self) -> None:
        if self._values:
            import jax

            self._results = jax.device_put(self._values, self._targets)
        for fn in self._deferred:
            fn()
        self._values, self._targets, self._deferred = [], [], []


class _LeafGroup:
    """One leaf's read requests plus the deferred conversion that turns
    their completed buffers into the application's leaf flavor. ``done``
    flips once the conversion ran (streamed or final batch) so it can
    never run twice."""

    __slots__ = ("reqs", "fn", "nbytes", "remaining", "done")

    def __init__(
        self,
        reqs: List[Any],
        fn: Callable[[Optional["_PlacementBatch"]], None],
    ) -> None:
        self.reqs = reqs
        self.fn = fn
        self.nbytes = sum(
            r.buffer_consumer.get_consuming_cost_bytes() for r in reqs
        )
        self.remaining = len(reqs)
        self.done = False


class _StreamingPlacer:
    """Rolling restore-time H2D placement: a leaf's conversion runs as
    soon as ALL of its reads complete, batched into one ``jax.device_put``
    dispatch per ~``flush_bytes`` of restored data. Storage reads and
    device transfers then overlap instead of serializing (all reads
    first, one placement after) — the transfer of early leaves hides
    behind the remaining reads. ``flush_bytes <= 0`` disables streaming
    (everything places in the caller's final batch).

    Single-threaded by construction: completion callbacks, flushes, and
    ``finalize`` all run on the scheduler's event-loop thread.
    """

    def __init__(self, flush_bytes: Optional[int] = None) -> None:
        self.flush_bytes = (
            knobs.get_restore_placement_flush_bytes()
            if flush_bytes is None
            else flush_bytes
        )
        self._by_req: Dict[int, _LeafGroup] = {}
        self._pending: List[_LeafGroup] = []
        self._pending_bytes = 0

    def register_plan(self, plan: "_StatefulLoadPlan") -> None:
        if self.flush_bytes <= 0:
            return
        for group in plan.groups:
            if group.remaining == 0:
                self._ready(group)
            else:
                for req in group.reqs:
                    self._by_req[id(req)] = group

    def on_req_complete(self, req: Any) -> None:
        """Scheduler hook. Batched spanning reads complete their member
        requests (the planned objects live inside the merged consumer)."""
        from .batcher import BatchedBufferConsumer

        consumer = req.buffer_consumer
        if isinstance(consumer, BatchedBufferConsumer):
            for member in consumer.members:
                self.on_req_complete(member)
            return
        group = self._by_req.pop(id(req), None)
        if group is None:
            return
        group.remaining -= 1
        if group.remaining == 0:
            self._ready(group)

    def _ready(self, group: _LeafGroup) -> None:
        self._pending.append(group)
        self._pending_bytes += group.nbytes
        if self._pending_bytes >= self.flush_bytes:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        batch = _PlacementBatch()
        for group in self._pending:
            group.fn(batch)
            group.done = True
        self._pending = []
        self._pending_bytes = 0
        batch.run()


class _StatefulLoadPlan:
    """Planned restore of one stateful: read requests plus the deferred
    work that turns completed reads into application state."""

    def __init__(
        self,
        key: str,
        stateful: Stateful,
        container_entries: Manifest,
        restored: Dict[str, Any],
        groups: List[_LeafGroup],
        read_reqs: List[Any],
    ) -> None:
        self.key = key
        self.stateful = stateful
        self.container_entries = container_entries
        self.restored = restored
        self.groups = groups
        self.read_reqs = read_reqs

    def finish_reads(self, batch: Optional[_PlacementBatch] = None) -> None:
        """Run deferred conversions (np buffers -> device arrays on their
        original shardings) not already streamed. Safe off the main
        thread: conversions only ``device_put`` addressable data — no
        collectives. With a shared ``batch`` the placements only register
        here; the caller runs the batch (one dispatch spanning many
        plans). Without one, a local batch runs immediately."""
        own = batch is None
        if batch is None:
            batch = _PlacementBatch()
        for group in self.groups:
            if not group.done:
                group.fn(batch)
                group.done = True
        if own:
            batch.run()

    def apply(self) -> None:
        """Hand the restored state dict to the application. May run
        arbitrary user code (collectives included) — main thread only."""
        state_dict = inflate(
            {**self.container_entries}, self.restored, prefix=self.key
        )
        self.stateful.load_state_dict(state_dict)


# ---------------------------------------------------------------------------
# PendingSnapshot
# ---------------------------------------------------------------------------


class PendingSnapshot:
    """Handle on an in-flight async snapshot (reference snapshot.py:904-991).

    A background thread drains staging (for device-snapshot takes) and
    storage I/O, synchronizes through a store-based
    :class:`StoreBarrier` (collectives are not thread-safe to issue off
    the main thread — reference comment snapshot.py:948), and rank 0
    writes the commit marker only if every rank succeeded. Errors
    propagate to every rank through the barrier and re-raise in
    ``wait()``.

    The snapshot moves through three phases (docs/async.md):

    - **visible** — over by the time the caller holds this handle: the
      plan collectives ran and a consistent snapshot is pinned (device
      clones / host copies); the live state is free.
    - **staged** — background D2H + serialization finished; the bytes
      sit in host buffers (and, for tiered paths, partly in the fast
      tier). ``wait(phase="staged")``.
    - **committed** — every rank's writes are durable and the commit
      marker exists. ``wait()`` / ``wait(phase="committed")``.
    """

    def __init__(
        self,
        path: str,
        pending_io_work: "PendingIOWork | DeferredIOWork",
        pg_wrapper: PGWrapper,
        metadata: Optional[SnapshotMetadata],
        storage: StoragePlugin,
        event_loop: asyncio.AbstractEventLoop,
        commit_nonce: str = "",
        counter_baseline: Optional[Dict[str, float]] = None,
        trace_mark: Optional[TraceMark] = None,
        progress_tracker: Optional[_progress.ProgressTracker] = None,
        op_begin: Optional[float] = None,
        tunables: Optional[Dict[str, Any]] = None,
    ) -> None:
        import threading

        self.path = path
        self.commit_nonce = commit_nonce
        self.pg = pg_wrapper
        self._metadata = metadata
        self._storage = storage
        self._event_loop = event_loop
        self._pending_io_work = pending_io_work
        self._counter_baseline = counter_baseline or {}
        self._trace_mark = trace_mark
        # Effective tunable values captured at async_take entry — the
        # ones the take ran under, regardless of what the autotuner
        # applies between now and the commit thread's report emission.
        self._tunables = tunables
        self._progress_tracker = progress_tracker
        self._exc_info: Optional[BaseException] = None
        self._done = threading.Event()
        self._staged = threading.Event()
        # Phase-split telemetry, relative to async_take's entry: the
        # visible span is over by construction time (this handle IS the
        # return value); staged_s is stamped by the drain callback.
        self._op_begin = op_begin if op_begin is not None else time.monotonic()
        self._visible_s = time.monotonic() - self._op_begin
        self._staged_s: Optional[float] = None
        if isinstance(pending_io_work, DeferredIOWork):
            # Wired BEFORE the thread starts: the drain may reach the
            # staged boundary arbitrarily fast.
            def _mark_staged() -> None:
                self._staged_s = time.monotonic() - self._op_begin
                self._staged.set()

            pending_io_work.on_staged = _mark_staged
        else:
            # Non-deferred takes staged before this handle existed.
            self._staged_s = self._visible_s
            self._staged.set()
        self._thread = threading.Thread(
            target=self._complete_snapshot, name="snapshot-commit", daemon=True
        )
        self._thread.start()

    def _complete_snapshot(self) -> None:
        barrier = None
        recorder = _trace_recorder()
        commit_span = recorder.begin(
            telemetry.names.SPAN_ASYNC_TAKE_COMMIT,
            path=self.path,
            rank=self.pg.get_rank(),
        )
        try:
            barrier = _nonce_barrier(
                f"__snapshot_commit/{self.commit_nonce}", self.pg
            )
            self._pending_io_work.sync_complete(self._event_loop)
            _crashpoint(telemetry.names.CRASH_TAKE_WRITES_DONE)
            self._pending_io_work.finalize_checksums()
            _maybe_write_checksum_table(
                self._pending_io_work,
                self.pg.get_rank(),
                self._storage,
                self._event_loop,
            )
            _crashpoint(telemetry.names.CRASH_CHECKSUM_TABLE_WRITTEN)
            _maybe_write_cas_map(
                self._storage, self.pg.get_rank(), self._event_loop
            )
            _crashpoint(telemetry.names.CRASH_CAS_MAP_WRITTEN)
            if barrier is not None:
                barrier.arrive()
            if self.pg.get_rank() == 0:
                Snapshot._write_snapshot_metadata(
                    self._metadata, self._storage, self._event_loop
                )
            if barrier is not None:
                barrier.depart()
            # Post-commit peer push, same hook as the sync take's: the
            # enqueue is queue-put cheap and the job runs on the peer
            # replicator's own worker, not this commit thread.
            _maybe_push_to_peer(self.path, self._pending_io_work)
            self._event_loop.run_until_complete(self._storage.close())
            recorder.end(commit_span)
            # Store-based gather + local file append only — safe on this
            # background thread (no collectives), same rule the commit
            # barrier follows. Post-close so a tiered take's report sees
            # its just-enqueued mirror job. The pipeline dict carries the
            # visible/staged phase split for the doctor's
            # async-visible-stall rule.
            pipeline = dict(self._pending_io_work.pipeline_telemetry())
            pipeline["visible_s"] = round(self._visible_s, 6)
            if self._staged_s is not None:
                pipeline["staged_s"] = round(self._staged_s, 6)
            _emit_snapshot_report(
                kind="async_take",
                path=self.path,
                pg_wrapper=self.pg,
                pipeline=pipeline,
                counter_baseline=self._counter_baseline,
                nonce=self.commit_nonce,
                trace_mark=self._trace_mark,
                tunables=self._tunables,
            )
        except BaseException as e:  # noqa: BLE001 - must propagate via wait()
            # Record the failure before telling peers: report_error talks to
            # the store and may itself fail, but wait() must still raise.
            self._exc_info = e
            logger.error("Async snapshot failed: %r", e)
            if barrier is not None:
                try:
                    barrier.report_error(e)
                except Exception as report_exc:
                    logger.error(
                        "Failed to report snapshot error to peers: %r", report_exc
                    )
        finally:
            # Ordering matters on the failure path: the error is recorded
            # and the heartbeat settled TERMINAL ("failed", never a
            # crash-shaped non-terminal leftover) before the staged/done
            # events release any waiter — a woken wait() must observe the
            # final state, exactly once, not a half-settled one.
            if self._progress_tracker is not None:
                self._progress_tracker.finish(self._exc_info)
            recorder.end(commit_span)  # no-op if already closed
            self._event_loop.close()
            self._staged.set()  # no-op if staging completed normally
            self._done.set()

    def wait(self, phase: str = "committed") -> Optional[Snapshot]:
        """Block until the snapshot reaches ``phase``:

        - ``"staged"`` — background staging (D2H + serialize) finished;
          returns None (there is no committed snapshot yet). The legacy
          unblock point: everything the pre-deferral ``async_take``
          guaranteed at return time holds here.
        - ``"committed"`` (default) — storage drain + commit barrier
          done on every rank; returns the committed :class:`Snapshot`.

        A background failure re-raises here — on the first ``wait()``
        that observes it and on every later one (callers polling
        ``wait(phase="staged")`` then ``wait()`` see it at both, rather
        than a success after an error). The progress heartbeat is
        settled terminal by the drain thread before any waiter wakes."""
        if phase not in ("staged", "committed"):
            raise ValueError(
                f'phase must be "staged" or "committed", got {phase!r}'
            )
        if phase == "staged":
            self._staged.wait()
            if self._exc_info is not None:
                raise self._exc_info
            return None
        self._thread.join()
        if self._exc_info is not None:
            raise self._exc_info
        # Preserve the process group: restore() on the returned snapshot
        # must keep per-rank availability and coordination semantics.
        snapshot = Snapshot(path=self.path, pg=self.pg)
        snapshot._metadata = self._metadata
        return snapshot

    def done(self) -> bool:
        return self._done.is_set()

    def staged(self) -> bool:
        """True once background staging finished (``wait(phase="staged")``
        will not block). Also true after a failed drain — ``wait`` then
        raises instead of blocking."""
        return self._staged.is_set()


class PendingRestore:
    """Handle on an in-flight async restore (see Snapshot.async_restore).

    The background thread runs only storage reads, deserialization, and
    device placement of addressable data — never collectives (the same
    rule the async-take commit thread follows, reference snapshot.py:948).
    ``wait()`` joins it, re-raises any failure *before* touching app
    state, then applies the restored state dicts on the calling thread in
    globally-sorted key order with barriers in between (load_state_dict
    may run collectives)."""

    def __init__(
        self,
        path: str,
        keys: List[str],
        plans: Dict[str, _StatefulLoadPlan],
        pg_wrapper: PGWrapper,
        memory_budget_bytes: int,
        rank: int,
        world_size: int,
        rng_key: Optional[str] = None,
        restore_nonce: Optional[str] = None,
        counter_baseline: Optional[Dict[str, float]] = None,
        trace_mark: Optional[TraceMark] = None,
        tunables: Optional[Dict[str, Any]] = None,
        fanout_ctx=None,
        peer_ctx=None,
    ) -> None:
        import threading

        self.path = path
        self._keys = keys
        self._plans = plans
        self._rng_key = rng_key
        self._restore_nonce = restore_nonce
        self._pg = pg_wrapper
        self._memory_budget_bytes = memory_budget_bytes
        self._rank = rank
        self._world_size = world_size
        self._counter_baseline = counter_baseline or {}
        self._trace_mark = trace_mark
        self._tunables = tunables
        # Fan-out cache populated by the calling-thread exchange; the
        # background pipeline serves exchanged shard blobs from it (no
        # collectives off the main thread — the bytes already moved).
        self._fanout_ctx = fanout_ctx
        # Peer-tier owner table built on the calling thread; pulls are
        # point-to-point socket reads, safe on the read thread.
        self._peer_ctx = peer_ctx
        # Created on the initiating thread; fed and settled by the
        # background read thread.
        self._progress_tracker = _progress.track(
            "async_restore", path, rank
        )
        self._pipeline_telemetry: Optional[dict] = None
        self._exc_info: Optional[BaseException] = None
        self._applied = False
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run_reads, name="restore-reads", daemon=True
        )
        self._thread.start()

    def _run_reads(self) -> None:
        event_loop = asyncio.new_event_loop()
        reads_span = _trace_recorder().begin(
            telemetry.names.SPAN_ASYNC_RESTORE_READS,
            path=self.path,
            rank=self._rank,
        )
        try:
            storage = url_to_storage_plugin(self.path)
            if self._peer_ctx is not None:
                storage = self._peer_ctx.wrap(storage)
            read_reqs = [
                r for plan in self._plans.values() for r in plan.read_reqs
            ]
            bytes_needed = sum(_req_needed_bytes(r) for r in read_reqs)
            if knobs.is_batching_enabled():
                from .batcher import batch_read_requests

                read_reqs = batch_read_requests(read_reqs)
            checksum_table = _get_checksum_table_impl(
                self._world_size, storage, event_loop
            )
            # Streaming placement across every plan: leaves whose reads
            # completed device_put in rolling batches while later reads
            # are still draining.
            placer = _StreamingPlacer()
            for plan in self._plans.values():
                placer.register_plan(plan)
            fanout_ctx = self._fanout_ctx
            self._pipeline_telemetry = sync_execute_read_reqs(
                read_reqs=read_reqs,
                storage=(
                    fanout_ctx.wrap(storage)
                    if fanout_ctx is not None
                    else storage
                ),
                memory_budget_bytes=self._memory_budget_bytes,
                rank=self._rank,
                event_loop=event_loop,
                checksum_table=checksum_table,
                on_req_complete=placer.on_req_complete,
                progress=self._progress_tracker,
                classify_read=(
                    fanout_ctx.classify_read
                    if fanout_ctx is not None
                    else None
                ),
            )
            self._pipeline_telemetry["bytes_needed"] = bytes_needed
            _merge_fanout_telemetry(self._pipeline_telemetry, fanout_ctx)
            _merge_peer_telemetry(self._pipeline_telemetry, self._peer_ctx)
            placer.flush()
            # Whatever didn't stream (flush disabled, zero-read leaves)
            # places in one final batched device_put spanning all plans
            # (per-leaf dispatch latency × hundreds of leaves is real
            # cold-start time).
            placement = _PlacementBatch()
            for plan in self._plans.values():
                plan.finish_reads(placement)
            placement.run()
            event_loop.run_until_complete(storage.close())
        except BaseException as e:  # noqa: BLE001 - must propagate via wait()
            self._exc_info = e
            logger.error("Async restore failed: %r", e)
        finally:
            # Release the exchanged shard bytes whether or not the reads
            # succeeded; the handle may outlive the restore.
            if self._fanout_ctx is not None:
                self._fanout_ctx.clear()
            self._progress_tracker.finish(self._exc_info)
            _trace_recorder().end(reads_span)
            event_loop.close()
            self._done.set()

    def _key_barrier(self, i: int) -> Optional[StoreBarrier]:
        if self._restore_nonce is None:
            return None
        return _nonce_barrier(
            f"__restore/{self._restore_nonce}/{i}", self._pg
        )

    def wait(self) -> None:
        """Block until reads finish, then apply the state dicts. Must be
        called from the thread that owns collective ordering (the one
        that called async_restore).

        Failure semantics match the sync restore: a rank whose reads (or
        applies) failed reports the error into the barrier its peers are
        waiting at and raises; the peers observe it and abandon within
        seconds (no commit-style retry — a failed distributed restore is
        fatal to the job, not recoverable per-rank)."""
        self._thread.join()
        if self._exc_info is not None:
            # State was never applied; the read buffers are useless.
            # Release them before raising (the handle may be kept for
            # diagnostics, and a retry will allocate its own). Peers whose
            # reads succeeded are waiting at the FIRST apply barrier —
            # tell them before raising.
            self._plans = {}
            first = self._key_barrier(0) if self._keys else None
            with _reporting_to(first, "restore-read"):
                raise self._exc_info
        if self._applied:
            return
        # One barrier per gathered KEY, plan or no plan: different ranks
        # may hold plans for different keys (per-rank statefuls, elastic
        # world-size changes), and a per-plan barrier count would diverge
        # and deadlock. Mirrors the sync path (restore(): barrier after
        # every key, whether or not this rank loaded it). The RNG plan is
        # skipped here — its key is rank-local knowledge, so it must not
        # perturb the shared schedule — and applied after all barriers
        # (RngState application is collective-free), the sync path's
        # restore-RNG-last invariant.
        for i, key in enumerate(self._keys):
            barrier = self._key_barrier(i)
            with _reporting_to(barrier, "restore-apply"):
                plan = self._plans.get(key)
                if plan is not None and key != self._rng_key:
                    plan.apply()
            # load_state_dict may run collectives; keep global order
            # (reference snapshot.py:466-476 barrier discipline).
            if barrier is not None:
                barrier.arrive()
                barrier.depart()
            else:
                self._pg.barrier()
        rng_plan = self._plans.get(self._rng_key) if self._rng_key else None
        if rng_plan is not None:
            rng_plan.apply()
        # Applied only if every plan succeeded: a raised apply leaves the
        # handle un-applied, so a retried wait() re-applies from the start
        # (deterministic) instead of silently succeeding half-restored.
        self._applied = True
        # Local report only (nonce=None -> no cross-rank gather): wait()
        # call times are application-controlled, and the emission must
        # not add a rendezvous of its own to the apply schedule.
        _emit_snapshot_report(
            kind="async_restore",
            path=self.path,
            pg_wrapper=self._pg,
            pipeline=self._pipeline_telemetry,
            counter_baseline=self._counter_baseline,
            nonce=None,
            trace_mark=self._trace_mark,
            tunables=self._tunables,
        )
        # Release the checkpoint-sized host buffers the plans hold; the
        # handle itself may outlive the restore (done()-polling callers).
        self._plans = {}

    def done(self) -> bool:
        """True once background reads finished (wait() will not block)."""
        return self._done.is_set()


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def _validate_app_state(app_state: AppState) -> None:
    """Reference parity: snapshot.py:658-666."""
    if not isinstance(app_state, dict):
        raise TypeError(
            f"app_state must be a Dict[str, Stateful], got {type(app_state)}"
        )
    for key, value in app_state.items():
        if not isinstance(key, str):
            raise TypeError(f"app_state keys must be str, got {type(key)}")
        if not (hasattr(value, "state_dict") and hasattr(value, "load_state_dict")):
            raise TypeError(
                f"app_state[{key!r}] ({type(value)}) does not implement the "
                f"Stateful protocol (state_dict/load_state_dict). Wrap pure "
                f"pytrees in PyTreeState."
            )


def _pop_rng_state(app_state: AppState) -> Optional[Tuple[str, RngState]]:
    """At most one RngState is allowed (reference snapshot.py:858-877)."""
    rng_items = [(k, v) for k, v in app_state.items() if isinstance(v, RngState)]
    if len(rng_items) > 1:
        raise RuntimeError(
            f"At most one RngState is allowed in app_state "
            f"(found {[k for k, _ in rng_items]})"
        )
    if not rng_items:
        return None
    key, stateful = rng_items[0]
    del app_state[key]
    # Caller re-inserts after processing so the dict is left intact.
    app_state[key] = stateful
    return key, stateful


def _gather_keys(
    app_state: AppState,
    pg_wrapper: PGWrapper,
) -> List[str]:
    """Sorted union of app-state keys across ranks (reference
    snapshot.py:851-856). Deliberately *never* reordered by rank-local
    facts (e.g. which key holds the RngState): the list defines the
    barrier/collective schedule and must be identical on every rank."""
    local_keys = list(app_state.keys())
    gathered = pg_wrapper.all_gather_object(local_keys)
    return sorted({k for ks in gathered for k in ks})


def _coalesce_replicated(
    replicated: List[str], pg_wrapper: PGWrapper
) -> List[str]:
    """Intersection of replication globs across ranks (reference
    snapshot.py:789-849): a path is treated as replicated only if every rank
    declared it."""
    if pg_wrapper.get_world_size() == 1:
        return list(replicated)
    gathered = pg_wrapper.all_gather_object(sorted(replicated))
    common = set(gathered[0])
    for patterns in gathered[1:]:
        common &= set(patterns)
    return sorted(common)


def _infer_replicated_paths(
    flattened: Dict[str, Any], world_size: int
) -> Set[str]:
    """Auto-detect replicated leaves from their GSPMD sharding — the
    TPU-native analog of the reference's DDP-module introspection
    (reference snapshot.py:828-844).

    A ``jax.Array`` fully replicated over more than one device is inferred
    replicated only when that is a *global* declaration:

    - world size 1: trivially global — the snapshot holds exactly one
      value, so marking it replicated only widens restore-time
      availability (any future world size reads it).
    - world size > 1: only when the sharding's devices span more than one
      process — under SPMD a multi-process ``jax.Array`` holds one
      consistent global value, so every participating process has the
      same bytes. An array replicated over a rank's *local* devices only
      (e.g. per-host statistics) carries no cross-rank guarantee and is
      never inferred; per-rank state must stay per-rank.

    Single-device arrays carry no declaration at all and are never
    inferred (the reference likewise only infers from the explicit DDP
    wrapper, not from plain tensors).
    """
    inferred: Set[str] = set()
    for path, leaf in flattened.items():
        if not is_jax_array(leaf):
            continue
        sharding = getattr(leaf, "sharding", None)
        if (
            sharding is None
            or not sharding.is_fully_replicated
            or len(sharding.device_set) <= 1
        ):
            continue
        if world_size > 1:
            processes = {d.process_index for d in sharding.device_set}
            if len(processes) <= 1:
                continue
        inferred.add(path)
    return inferred


def _calculate_replicated_entries(
    flattened: Dict[str, Any],
    patterns: List[str],
    pg_wrapper: PGWrapper,
    inferred: Optional[Set[str]] = None,
) -> Set[str]:
    """Glob-match replication patterns and verify matched paths exist on
    every rank; rank 0 decides, everyone follows (reference
    snapshot.py:623-656)."""
    matched = {
        path
        for path in flattened
        if any(fnmatch.fnmatch(path, p) for p in patterns)
    }
    if inferred:
        matched |= inferred & set(flattened)
    if pg_wrapper.get_world_size() == 1:
        return matched
    # Gather-to-leader + broadcast of the decision: "rank 0 decides,
    # everyone follows" never needed every rank to hold every rank's
    # matched list — non-leaders send O(own list) and receive O(common).
    all_matched = pg_wrapper.gather_object(sorted(matched))
    common: List[str] = []
    if all_matched is not None:
        common_set: Set[str] = set(all_matched[0])
        for paths in all_matched[1:]:
            common_set &= set(paths)
        common = sorted(common_set)
    verified = pg_wrapper.broadcast_object(common)
    return set(verified)


def _gather_manifest(
    rank_manifest: Manifest, pg_wrapper: PGWrapper
) -> Optional[Manifest]:
    """Gather per-rank manifests TO RANK 0 and merge into the global
    ``{rank}/{path}``-keyed manifest there; returns None on every other
    rank (reference snapshot.py:879-901 all_gathers over c10d, which
    spreads the world² bytes peer-to-peer; over a KV store the leader is
    the only socket, so the non-leaders — which don't need the global
    manifest: rank 0 alone writes metadata, and restore lazy-loads it
    from storage post-commit — must not each pull O(world x manifest)
    bytes through it)."""
    from .manifest import is_replicated

    gathered = pg_wrapper.gather_object(rank_manifest)
    if gathered is None:
        return None
    merged_replicated: Manifest = {}
    if pg_wrapper.get_world_size() > 1:
        from .partitioner import consolidate_replicated_entries

        merged_replicated = consolidate_replicated_entries(gathered)

    global_manifest: Manifest = {}
    for rnk, manifest in enumerate(gathered):
        for logical_path, entry in manifest.items():
            if is_replicated(entry) and not is_container_entry(entry):
                if rnk > 0:
                    continue  # replicated entries live under rank 0 only
                entry = merged_replicated.get(logical_path, entry)
            global_manifest[f"{rnk}/{logical_path}"] = entry
    return global_manifest


def _get_checksum_table_impl(
    world_size: int,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
):
    """Merged digests of every writer rank, or None (no tables written,
    or verification disabled)."""
    if knobs.is_checksums_disabled():
        return None
    from .integrity import load_checksum_tables

    return load_checksum_tables(world_size, storage, event_loop)


def _maybe_write_checksum_table(
    pending_io_work: PendingIOWork,
    rank: int,
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    """Persist this rank's blob digests (recorded during the write
    pipeline) before the commit barrier: a committed snapshot always has
    complete tables. No-ops when checksums are disabled (the pipeline
    recorded nothing)."""
    if not pending_io_work.checksums:
        return
    from .integrity import sync_write_checksum_table

    sync_write_checksum_table(
        pending_io_work.checksums, rank, storage, event_loop
    )


def _restore_destination(
    entry: "ArrayEntry | ChunkedArrayEntry", current_leaf: Any
) -> Tuple[np.ndarray, Optional[Callable[[np.ndarray], Any]], bool]:
    """Pick/allocate the host read destination for a dense entry and, when
    the application's current leaf is a device array, a converter that puts
    the restored bytes back on its device/sharding. The third element says
    whether the destination is framework-allocated (owned): only owned
    buffers may be direct-read targets — the application's own in-place
    array keeps copy-on-success semantics so a failed restore can't tear
    it."""
    if isinstance(current_leaf, np.ndarray) and ArrayIOPreparer.can_load_inplace(
        _as_array_entry(entry), current_leaf
    ):
        return current_leaf, None, False
    if (
        hasattr(current_leaf, "shape")
        and list(getattr(current_leaf, "shape")) != list(entry.shape)
    ):
        # JAX state is replaced, not mutated, so the checkpointed shape wins;
        # but a silent shape change usually means the wrong checkpoint.
        logger.warning(
            "Restoring shape %s over a current leaf of shape %s; the "
            "checkpointed value replaces the leaf",
            list(entry.shape),
            list(current_leaf.shape),
        )
    dst = ArrayIOPreparer.empty_array_from_entry(entry)
    if is_jax_array(current_leaf):
        import jax

        sharding = current_leaf.sharding
        # Uncommitted leaves (e.g. optax step counters created by plain
        # jnp ops) must stay uncommitted: committing them to a concrete
        # device makes the restored state unusable in a jit alongside
        # differently-placed arrays.
        committed = getattr(current_leaf, "_committed", True)

        def convert(
            host: np.ndarray, batch: Optional["_PlacementBatch"] = None
        ) -> Any:
            if not committed:
                import jax.numpy as jnp

                return jnp.asarray(host)
            if batch is None:
                return jax.device_put(host, sharding)
            # Registered into the restore-wide batched device_put; the
            # caller resolves the slot after batch.run().
            return batch.put(host, sharding)

        return dst, convert, True
    return dst, None, True


def _as_array_entry(entry: "ArrayEntry | ChunkedArrayEntry") -> ArrayEntry:
    if isinstance(entry, ArrayEntry):
        return entry
    from .serialization import Serializer

    return ArrayEntry(
        location="",
        serializer=Serializer.BUFFER_PROTOCOL.value,
        dtype=entry.dtype,
        shape=entry.shape,
        replicated=entry.replicated,
    )
