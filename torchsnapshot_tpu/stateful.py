"""The ``Stateful`` protocol: anything that can produce and absorb a state dict.

Reference parity: torchsnapshot/stateful.py:13-23. In the JAX world most
checkpointable things are pure pytrees (params, optax states) rather than
mutable modules, so the protocol is complemented by :class:`PyTreeState`
(state_dict.py) which adapts an immutable pytree into a ``Stateful``.
"""

from __future__ import annotations

from typing import Any, Dict, Protocol, runtime_checkable


@runtime_checkable
class Stateful(Protocol):
    def state_dict(self) -> Dict[str, Any]: ...

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None: ...


AppState = Dict[str, Stateful]
