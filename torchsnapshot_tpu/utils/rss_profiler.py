"""RSS sampling for proving bounded-memory checkpointing.

Reference parity: torchsnapshot/rss_profiler.py:20-56 — a context manager
that samples the process RSS on a background thread (100 ms period) and
records deltas against the RSS at entry. Benchmarks use it to demonstrate
that the scheduler's memory budget actually bounds host memory
(reference benchmarks/torchrec/main.py:211-231).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Generator, List

import psutil

from .. import telemetry
from ..telemetry import names as metric_names
from ..telemetry.trace import get_recorder as _trace_recorder

_SAMPLE_PERIOD_SECONDS = 0.1


@dataclass
class RSSDeltas:
    """Sampled ``rss - rss_at_entry`` values, in bytes."""

    deltas: List[int] = field(default_factory=list)

    @property
    def peak_bytes(self) -> int:
        return max(self.deltas, default=0)


@contextmanager
def measure_rss_deltas(
    rss_deltas: RSSDeltas,
    sample_period_seconds: float = _SAMPLE_PERIOD_SECONDS,
) -> Generator[None, None, None]:
    """Sample RSS deltas into ``rss_deltas`` until the block exits.

    The sampler thread is joined on EVERY exit path (the block raising
    included), and its peak delta feeds the telemetry registry's
    ``rss_peak_delta_bytes`` gauge — bench runs and snapshot reports
    read memory pressure from the same place. Each NEW peak also lands
    as an ``rss:peak`` instant event in the flight recorder, so the
    moment host memory crested is placeable on the span timeline
    (which write/stage was in flight when RSS peaked)."""
    process = psutil.Process()
    baseline = process.memory_info().rss
    stop = threading.Event()
    peak_seen = [0]

    def note(delta: int) -> None:
        rss_deltas.deltas.append(delta)
        if delta > peak_seen[0]:
            peak_seen[0] = delta
            _trace_recorder().instant(
                metric_names.INSTANT_RSS_PEAK, delta_bytes=delta
            )

    def sampler() -> None:
        while not stop.is_set():
            try:
                note(process.memory_info().rss - baseline)
            except Exception:  # noqa: BLE001 - a failed sample must not
                # wedge the thread (join below would then hang forever)
                break
            stop.wait(sample_period_seconds)

    thread = threading.Thread(
        target=sampler, name="rss-profiler", daemon=True
    )
    thread.start()
    try:
        yield
    finally:
        # Unconditional stop+join FIRST: nothing before the join may
        # raise, or an exception in the block would leak the sampler.
        stop.set()
        thread.join()
        try:
            note(process.memory_info().rss - baseline)
        finally:
            telemetry.metrics().gauge_set(
                metric_names.RSS_PEAK_DELTA_BYTES, rss_deltas.peak_bytes
            )
