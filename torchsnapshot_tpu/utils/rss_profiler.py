"""RSS sampling for proving bounded-memory checkpointing.

Reference parity: torchsnapshot/rss_profiler.py:20-56 — a context manager
that samples the process RSS on a background thread (100 ms period) and
records deltas against the RSS at entry. Benchmarks use it to demonstrate
that the scheduler's memory budget actually bounds host memory
(reference benchmarks/torchrec/main.py:211-231).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Generator, List

import psutil

_SAMPLE_PERIOD_SECONDS = 0.1


@dataclass
class RSSDeltas:
    """Sampled ``rss - rss_at_entry`` values, in bytes."""

    deltas: List[int] = field(default_factory=list)

    @property
    def peak_bytes(self) -> int:
        return max(self.deltas, default=0)


@contextmanager
def measure_rss_deltas(
    rss_deltas: RSSDeltas,
    sample_period_seconds: float = _SAMPLE_PERIOD_SECONDS,
) -> Generator[None, None, None]:
    """Sample RSS deltas into ``rss_deltas`` until the block exits."""
    process = psutil.Process()
    baseline = process.memory_info().rss
    stop = threading.Event()

    def sampler() -> None:
        while not stop.is_set():
            rss_deltas.deltas.append(process.memory_info().rss - baseline)
            stop.wait(sample_period_seconds)

    thread = threading.Thread(
        target=sampler, name="rss-profiler", daemon=True
    )
    thread.start()
    try:
        yield
    finally:
        stop.set()
        thread.join()
        rss_deltas.deltas.append(process.memory_info().rss - baseline)
