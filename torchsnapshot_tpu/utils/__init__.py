from .rss_profiler import measure_rss_deltas, RSSDeltas

__all__ = ["measure_rss_deltas", "RSSDeltas", "shard_map_compat"]


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the promotion boundary: newer jax ships
    it top-level (with ``check_vma``); older 0.4.x releases only ship
    ``jax.experimental.shard_map`` (where the same knob is spelled
    ``check_rep``). The three shard_map consumers (ring attention, the
    flash-attention mesh wrapper, the GPipe schedule) route through
    here so either jax runs them instead of failing on the missing
    attribute."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
    )
