from .rss_profiler import measure_rss_deltas, RSSDeltas

__all__ = ["measure_rss_deltas", "RSSDeltas"]
