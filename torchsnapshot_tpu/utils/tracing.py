"""Dual-sink trace annotations for the checkpoint pipeline.

Reference parity: the reference emits progress/throughput lines
(scheduler.py:96-175) but no timeline tracing. Here every annotation
lands in TWO places at once:

- the **flight recorder** (telemetry/trace.py) — always on, bounded
  ring, exported per-operation as Chrome trace JSON; this is what the
  stall watchdog and ``python -m torchsnapshot_tpu.telemetry trace``
  consume, profiler session or not;
- the **jax profiler timeline** — when a session is active
  (``jax.profiler.start_trace`` or the TensorBoard plugin), the same
  span appears on the XPlane timeline next to device compute, making
  D2H/compute/I-O overlap directly visible. With no session active the
  TraceAnnotation is a couple of cheap TraceMe calls; without jax
  importable it degrades away entirely.

jax availability is resolved once at import time — these annotations
sit on the per-buffer hot path. Span names are declared once in
``telemetry/names.py`` (``tools/check_span_names.py`` lints call
sites); keyword args become the recorder span's args (the jax side
carries the name only).

NOTE: the jax annotation is thread-local begin/end, so call sites that
hold a span across an ``await`` should use the recorder directly
(``telemetry.trace.get_recorder().span(...)``, which tracks per
asyncio task) rather than this helper — an interleaved task on the
same thread would otherwise mis-nest the XPlane timeline.
"""

from __future__ import annotations

from typing import Any, ContextManager

from ..telemetry.trace import get_recorder

try:
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover - jax always present in this repo
    _TraceAnnotation = None


class _DualAnnotation:
    """Flight-recorder span + jax TraceAnnotation, one context manager
    (hand-rolled: this wraps every buffer's staging/write/read, and a
    generator-based contextmanager costs ~3x per entry)."""

    __slots__ = ("_name", "_args", "_token", "_jax")

    def __init__(self, name: str, args: dict) -> None:
        self._name = name
        self._args = args
        self._token = 0
        self._jax = None

    def __enter__(self) -> None:
        self._token = get_recorder().begin(self._name, **self._args)
        if _TraceAnnotation is not None:
            self._jax = _TraceAnnotation(self._name)
            self._jax.__enter__()

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if self._jax is not None:
                self._jax.__exit__(exc_type, exc, tb)
        finally:
            get_recorder().end(self._token)


def trace_annotation(name: str, **args: Any) -> ContextManager[None]:
    """A context manager placing ``name`` on the flight recorder AND
    the active jax profiler timeline (thread-local on the jax side —
    safe on executor threads; see module note for coroutines)."""
    return _DualAnnotation(name, args)
