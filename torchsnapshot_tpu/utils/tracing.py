"""Profiler trace annotations for the checkpoint pipeline.

Reference parity: the reference emits progress/throughput lines
(scheduler.py:96-175) but no timeline tracing; the TPU-native equivalent
of choice is ``jax.profiler`` — when a profiler session is active
(``jax.profiler.start_trace`` or the TensorBoard plugin), these
annotations place the checkpointer's stage/write/read/consume spans on
the same XPlane timeline as device compute, making D2H/compute/I-O
overlap directly visible. With no session active, TraceAnnotation is a
couple of cheap TraceMe calls; without jax importable at all it degrades
to a nullcontext. jax availability is resolved once at import time —
these annotations sit on the per-buffer hot path.
"""

from __future__ import annotations

import contextlib
from typing import ContextManager

try:
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover - jax always present in this repo
    _TraceAnnotation = None


def trace_annotation(name: str) -> ContextManager[None]:
    """A context manager placing ``name`` on the active jax profiler
    timeline (thread-local, safe on executor threads)."""
    if _TraceAnnotation is None:
        return contextlib.nullcontext()
    return _TraceAnnotation(name)
