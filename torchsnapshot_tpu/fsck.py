"""Snapshot fsck: verify a committed snapshot's blobs without restoring.

No reference counterpart (its integrity story ends at the commit
marker); this exists because fleets want to audit checkpoints *before*
pointing an expensive pod at them. Two levels:

- **shallow** (default): manifest parses; every entry's blob exists and
  holds at least the bytes the entry claims (one ranged read of the
  final byte per blob — object-store HEAD-equivalent, no data
  transfer).
- **deep** (``--deep``): additionally reads every blob fully and
  verifies its recorded CRC (integrity.py tables, including entries
  inherited from incremental bases).

Incremental snapshots are first-class: parent-relative (``../step_X``)
locations resolve through the storage plugin like any restore would, so
a broken chain (GC'd base, missing origin blob) is caught here instead
of at restore time on the pod.

CLI::

    python -m torchsnapshot_tpu.fsck /path/to/snapshot [--deep]

exits 0 when the snapshot is sound, 1 otherwise, printing one line per
problem.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from typing import Dict, List, Optional, Set, Tuple

from . import knobs
from .io_types import ReadIO, StoragePlugin, WriteIO
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    ObjectEntry,
    ShardedArrayEntry,
    SnapshotMetadata,
    is_container_entry,
)
from .serialization import array_size_bytes
from .snapshot import SNAPSHOT_METADATA_FNAME
from .storage_plugin import split_tiered_url, url_to_storage_plugin

logger: logging.Logger = logging.getLogger(__name__)


@dataclasses.dataclass
class FsckProblem:
    location: str
    kind: str  # missing | truncated | checksum | unreadable | unmirrored
    detail: str


@dataclasses.dataclass
class FsckReport:
    path: str
    blobs_checked: int
    bytes_verified: int
    problems: List[FsckProblem]
    deep: bool
    # Number of blobs whose content was actually CRC-verified in a deep
    # audit. 0 with deep=True means the audit was length-only (snapshot
    # written with checksums off, or verification disabled locally) —
    # surfaced so "deep OK" can never silently be hollow.
    crcs_verified: int = 0

    @property
    def ok(self) -> bool:
        return not self.problems


def blob_requirements(manifest: Dict[str, Entry]) -> Dict[str, int]:
    """location -> minimum byte length the manifest implies. Batched slab
    members share a location; the requirement is the max end offset any
    member claims. Shared by the audit below and the manager's ledger
    accounting (per-step new vs. base-referenced bytes)."""
    need: Dict[str, int] = {}

    def add_array(ae: ArrayEntry) -> None:
        nbytes = array_size_bytes(ae.shape, ae.dtype)
        end = ae.byte_range_tuple[1] if ae.byte_range_tuple else nbytes
        need[ae.location] = max(need.get(ae.location, 0), end)

    for entry in manifest.values():
        if is_container_entry(entry):
            continue
        if isinstance(entry, ArrayEntry):
            add_array(entry)
        elif isinstance(entry, (ChunkedArrayEntry, ShardedArrayEntry)):
            shards = (
                entry.chunks
                if isinstance(entry, ChunkedArrayEntry)
                else entry.shards
            )
            for shard in shards:
                add_array(shard.array)
        elif isinstance(entry, ObjectEntry):
            # Pickled blobs carry no size in the manifest; existence (>= 1
            # byte) is the shallow requirement.
            need.setdefault(entry.location, 1)
    return need


# Streaming chunk for deep audits: bounds the audit host's memory at
# ~(io concurrency × 16 MiB) regardless of blob size (batched slabs can
# be GBs; the tool must never OOM the host it exists to protect).
_DEEP_CHUNK_BYTES = 16 * 1024 * 1024


async def _shallow_check(
    storage: StoragePlugin,
    location: str,
    min_bytes: int,
    problems: List[FsckProblem],
) -> int:
    """Existence + length via one ranged read of the final required byte
    (object-store HEAD-equivalent; no data transfer)."""
    start = max(0, min_bytes - 1)
    want = min_bytes - start
    read_io = ReadIO(path=location, byte_range=(start, min_bytes))
    try:
        await storage.read(read_io)
    except FileNotFoundError:
        problems.append(FsckProblem(location, "missing", "blob not found"))
        return 0
    except OSError as e:
        # Plugins fail short ranged reads with plain OSError (the native
        # path uses EIO): the blob exists but lacks the byte.
        problems.append(
            FsckProblem(
                location,
                "truncated",
                f"cannot read byte {min_bytes - 1} ({e!r})",
            )
        )
        return 0
    except Exception as e:  # noqa: BLE001 - transient/storage errors are
        # NOT corruption; misreporting them as such would make fleets
        # discard sound checkpoints on a retryable 503.
        problems.append(FsckProblem(location, "unreadable", repr(e)))
        return 0
    got = memoryview(read_io.buf).nbytes
    if got < want:
        # Plugins without short-read errors (e.g. the in-memory store
        # slices past EOF silently) surface truncation here instead.
        problems.append(
            FsckProblem(
                location,
                "truncated",
                f"byte {min_bytes - 1} absent ({got} of {want} bytes read)",
            )
        )
        return 0
    return got


async def _deep_check(
    storage: StoragePlugin,
    location: str,
    min_bytes: int,
    expected: Optional[Tuple],
    problems: List[FsckProblem],
) -> Tuple[int, bool]:
    """Stream the blob in bounded chunks, chaining the CRC across them
    (both crc32c and crc32 support continuation). Returns (bytes read,
    crc verified?)."""
    from .integrity import _alg_available, _as_bytes_view, _crc_of

    if expected is None or not _alg_available(expected[0]):
        return await _shallow_check(storage, location, min_bytes, problems), False

    alg, want_crc, nbytes = expected[0], expected[1], expected[2]
    crc = 0
    pos = 0
    while pos < nbytes:
        end = min(pos + _DEEP_CHUNK_BYTES, nbytes)
        read_io = ReadIO(path=location, byte_range=(pos, end))
        try:
            await storage.read(read_io)
        except FileNotFoundError:
            problems.append(
                FsckProblem(location, "missing", "blob not found")
            )
            return pos, False
        except OSError as e:
            problems.append(
                FsckProblem(
                    location,
                    "truncated",
                    f"{nbytes} bytes recorded, read fails at {pos} ({e!r})",
                )
            )
            return pos, False
        except Exception as e:  # noqa: BLE001
            problems.append(FsckProblem(location, "unreadable", repr(e)))
            return pos, False
        mv = _as_bytes_view(read_io.buf)
        if mv.nbytes != end - pos:
            problems.append(
                FsckProblem(
                    location,
                    "truncated",
                    f"ranged read [{pos}, {end}) returned {mv.nbytes} bytes",
                )
            )
            return pos, False
        crc = _crc_of(mv, alg, seed=crc)
        pos = end
    if want_crc is not None and crc != want_crc:
        problems.append(
            FsckProblem(
                location,
                "checksum",
                f"{alg} mismatch (expected {want_crc:#010x}, "
                f"got {crc:#010x})",
            )
        )
        return nbytes, False
    if nbytes < min_bytes:
        problems.append(
            FsckProblem(
                location,
                "truncated",
                f"{nbytes} bytes recorded, manifest needs >= {min_bytes}",
            )
        )
    return nbytes, True


async def _check_blob(
    storage: StoragePlugin,
    location: str,
    min_bytes: int,
    deep: bool,
    checksum_table,
    problems: List[FsckProblem],
    slots: asyncio.Semaphore,
) -> Tuple[int, bool]:
    async with slots:
        if deep:
            expected = (
                checksum_table.get(location) if checksum_table else None
            )
            return await _deep_check(
                storage, location, min_bytes, expected, problems
            )
        return (
            await _shallow_check(storage, location, min_bytes, problems),
            False,
        )


def _describe_partial_mirror(
    tiered_path: str, event_loop: asyncio.AbstractEventLoop
) -> Optional[str]:
    """For a tiered snapshot whose DURABLE tier lacks the commit marker:
    a mirror-in-progress description from the fast tier's journal, or
    None when no journal exists (nothing was ever committed, or the
    mirror never started)."""
    tiers = split_tiered_url(tiered_path)
    if tiers is None:
        return None
    from .tiered.journal import MirrorJournal

    fast_url, _ = tiers
    fast = url_to_storage_plugin(fast_url)
    try:
        journal = event_loop.run_until_complete(MirrorJournal.load(fast))
    finally:
        event_loop.run_until_complete(fast.close())
    if journal is None:
        return None
    total = len(journal.blobs)
    return (
        f"mirror in progress: {len(journal.done)} of {total} blobs "
        f"durable (journal in the fast tier resumes the upload)"
    )


def _verify_peer_placement(path: str) -> FsckReport:
    """``fsck --tier peer``: audit the peer-RAM placement journal.

    For each rank named by the snapshot's metadata, load its
    ``.peer_placement-rank<r>.json`` (written by that rank's push job to
    the local/fast tier) and union the claimed blob placements; every
    required data blob (base-referenced locations excluded — they
    belong to another step's placement) with no claim, and every
    placement doc recording a degraded push, lands in the report."""
    from .storage_plugin import split_tiered_url as _split
    from .tiered.peer import placement_doc_path

    problems: List[FsckProblem] = []
    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin(path)
        try:
            read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
            try:
                event_loop.run_until_complete(storage.read(read_io))
                metadata = SnapshotMetadata.from_yaml(
                    bytes(read_io.buf).decode("utf-8")
                )
            except FileNotFoundError:
                problems.append(
                    FsckProblem(
                        SNAPSHOT_METADATA_FNAME,
                        "missing",
                        "no commit marker: not a committed snapshot",
                    )
                )
                return FsckReport(path, 0, 0, problems, False)
            except Exception as e:  # noqa: BLE001
                problems.append(
                    FsckProblem(SNAPSHOT_METADATA_FNAME, "unreadable", repr(e))
                )
                return FsckReport(path, 0, 0, problems, False)

            tiers = _split(path)
            placement_storage = storage
            placement_owned = False
            if tiers is not None:
                # Placement docs live on the FAST tier only (they are a
                # local operator artifact, like the mirror journal).
                placement_storage = url_to_storage_plugin(tiers[0])
                placement_owned = True
            try:
                placed: Set[str] = set()
                docs = 0
                for rank in range(metadata.world_size):
                    doc_io = ReadIO(path=placement_doc_path(rank))
                    try:
                        event_loop.run_until_complete(
                            placement_storage.read(doc_io)
                        )
                        import json as _json

                        doc = _json.loads(bytes(doc_io.buf))
                    except FileNotFoundError:
                        continue
                    except Exception as e:  # noqa: BLE001
                        problems.append(
                            FsckProblem(
                                placement_doc_path(rank),
                                "unreadable",
                                repr(e),
                            )
                        )
                        continue
                    docs += 1
                    placed.update(
                        str(blob) for blob in doc.get("blobs", [])
                    )
                    degraded = (
                        doc.get("error")
                        or doc.get("blobs_failed")
                        or doc.get("blobs_refused")
                    )
                    if degraded:
                        problems.append(
                            FsckProblem(
                                placement_doc_path(rank),
                                "unmirrored",
                                f"degraded push: "
                                f"{doc.get('blobs_failed', 0)} failed, "
                                f"{doc.get('blobs_refused', 0)} refused "
                                f"({doc.get('error')})",
                            )
                        )
                from .cas import is_chunk_location

                need = blob_requirements(metadata.manifest)
                # Base-referenced locations belong to another step's
                # placement — EXCEPT content-addressed chunk refs, which
                # are this step's payload (pushed or dedup-referenced
                # into the peer pool) and must have a recorded copy for
                # a preemption to recover at RAM speed.
                required = {
                    loc
                    for loc in need
                    if not loc.startswith("../") or is_chunk_location(loc)
                }
                if docs == 0:
                    problems.append(
                        FsckProblem(
                            placement_doc_path(0),
                            "missing",
                            "no peer placement recorded: the peer tier "
                            "never pushed this step (tier off, "
                            "single-process world, or every push failed)",
                        )
                    )
                else:
                    for loc in sorted(required - placed):
                        problems.append(
                            FsckProblem(
                                loc,
                                "missing",
                                "no peer copy recorded: a preemption now "
                                "restores this blob from storage",
                            )
                        )
                return FsckReport(
                    path=path,
                    blobs_checked=len(required),
                    bytes_verified=0,
                    problems=problems,
                    deep=False,
                    crcs_verified=0,
                )
            finally:
                if placement_owned:
                    event_loop.run_until_complete(placement_storage.close())
        finally:
            event_loop.run_until_complete(storage.close())
    finally:
        event_loop.close()


@dataclasses.dataclass
class CasStoreReport:
    """Whole-store audit of a manager root's content-addressed chunk
    store (docs/cas.md): every referenced chunk exists with the byte
    length its digest key claims (and, with ``deep``, bytes matching
    the digest itself — the key is self-verifying), no committed
    manifest reference dangles, and leftover unreferenced chunks are
    listed (informational: pre-GC orphans of crashed takes, or dead
    chunks inside the GC grace window — they never fail the audit)."""

    root: str
    steps: List[int]
    chunks_present: int
    stored_bytes: int
    chunks_referenced: int
    logical_bytes: int  # retention-visible bytes across all steps
    problems: List[FsckProblem]
    unreferenced: Dict[str, int]
    deep: bool
    crcs_verified: int = 0

    @property
    def ok(self) -> bool:
        return not self.problems

    @property
    def dedup_ratio(self) -> float:
        """Logical (retention-visible) bytes per stored byte — 1.0 means
        no sharing; N retained steps of an unchanged state approach N."""
        return self.logical_bytes / self.stored_bytes if self.stored_bytes else 0.0

    @property
    def bytes_per_retained_step(self) -> float:
        return self.stored_bytes / len(self.steps) if self.steps else 0.0


def _present_chunks(root: str) -> Dict[str, Dict[str, int]]:
    """Chunk files across every locally-listable tier of the root (fast
    AND durable for all-fs tiered roots): ``key -> {tier dir: size}``.
    Per-copy sizes are kept so the audit can flag a torn copy in ONE
    tier even when another tier holds the full bytes (collapsing with
    ``max`` would pass a root whose durable tier is unrestorable)."""
    import os as _os

    from .cas import CHUNKS_DIRNAME, is_chunk_key

    urls = [root]
    tiers = split_tiered_url(root)
    if tiers is not None:
        urls = list(tiers)
    present: Dict[str, Dict[str, int]] = {}
    from .telemetry.sink import local_fs_root

    for url in urls:
        local = local_fs_root(url)
        if local is None:
            continue
        chunk_dir = _os.path.join(local, CHUNKS_DIRNAME)
        try:
            names = _os.listdir(chunk_dir)
        except OSError:
            continue
        for name in names:
            if not is_chunk_key(name):
                continue
            try:
                size = _os.path.getsize(_os.path.join(chunk_dir, name))
            except OSError:
                continue
            present.setdefault(name, {})[chunk_dir] = size
    return present


def verify_cas_store(root: str, deep: bool = False) -> CasStoreReport:
    """Audit one manager root's chunk store against its committed
    steps' manifests. Never raises for store damage — every problem
    lands in the report."""
    from . import manager as manager_mod
    from .cas import CHUNKS_DIRNAME, chunk_refs, nbytes_of_key, parse_key

    problems: List[FsckProblem] = []
    steps: List[int] = []
    referenced: Dict[str, int] = {}
    logical_bytes = 0
    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin(root)
        try:
            # Committed + pinned steps from the manager index (the
            # source of truth for what must be restorable).
            try:
                index = event_loop.run_until_complete(
                    manager_mod.read_index_full_async(storage)
                )
                steps = sorted(set(index["steps"]) | set(index["pinned"]))
            except Exception as e:  # noqa: BLE001 - index damage is a finding
                problems.append(
                    FsckProblem(manager_mod.INDEX_BLOB, "unreadable", repr(e))
                )

            for step in steps:
                meta_path = (
                    f"{manager_mod._step_dirname(step)}/"
                    f"{SNAPSHOT_METADATA_FNAME}"
                )
                read_io = ReadIO(path=meta_path)
                try:
                    event_loop.run_until_complete(storage.read(read_io))
                    metadata = SnapshotMetadata.from_yaml(
                        bytes(read_io.buf).decode("utf-8")
                    )
                except FileNotFoundError:
                    problems.append(
                        FsckProblem(
                            meta_path,
                            "missing",
                            "indexed step has no commit marker",
                        )
                    )
                    continue
                except Exception as e:  # noqa: BLE001
                    problems.append(
                        FsckProblem(meta_path, "unreadable", repr(e))
                    )
                    continue
                refs = chunk_refs(metadata.manifest)
                logical_bytes += sum(refs.values())
                for key, nbytes in refs.items():
                    referenced[key] = max(referenced.get(key, 0), nbytes)

            present = _present_chunks(root)
            for key in sorted(set(referenced) - set(present)):
                problems.append(
                    FsckProblem(
                        f"{CHUNKS_DIRNAME}/{key}",
                        "missing",
                        "chunk referenced by a committed manifest is "
                        "absent from the store (dangling ref)",
                    )
                )
            crcs_verified = 0
            checks: List[Tuple[str, int]] = []
            for key in sorted(set(referenced) & set(present)):
                want = nbytes_of_key(key)
                torn = False
                if want is not None:
                    # Every tier's copy must match the key's embedded
                    # length — a torn copy on one tier is a finding even
                    # when another tier holds the full bytes (restore
                    # from the damaged tier alone would fail).
                    for tier_dir, size in sorted(present[key].items()):
                        if size != want:
                            torn = True
                            problems.append(
                                FsckProblem(
                                    f"{CHUNKS_DIRNAME}/{key}",
                                    "truncated",
                                    f"digest key claims {want} bytes, "
                                    f"{size} stored in {tier_dir} "
                                    f"(torn chunk write)",
                                )
                            )
                if torn:
                    continue
                checks.append((key, want if want is not None else 0))

            if deep and checks:
                # Per-TIER verification against the self-describing key
                # (not a read through the composed fast-first view,
                # which would let a good fast copy mask size-preserving
                # corruption in the durable copy — exactly the damage
                # ``--repair`` exists to fix).
                import os as _os

                for key, _nbytes in checks:
                    if parse_key(key) is None:
                        continue
                    all_ok = True
                    for tier_dir in sorted(present[key]):
                        if not _chunk_copy_ok(
                            _os.path.join(tier_dir, key), key
                        ):
                            all_ok = False
                            problems.append(
                                FsckProblem(
                                    f"{CHUNKS_DIRNAME}/{key}",
                                    "checksum",
                                    f"bytes do not match the digest "
                                    f"key in {tier_dir} (fsck "
                                    f"--repair rebuilds from a "
                                    f"verifying tier)",
                                )
                            )
                    if all_ok:
                        crcs_verified += 1

            unreferenced = {
                k: max(copies.values())
                for k, copies in sorted(present.items())
                if k not in referenced
            }
            return CasStoreReport(
                root=root,
                steps=steps,
                chunks_present=len(present),
                stored_bytes=sum(
                    max(copies.values()) for copies in present.values()
                ),
                chunks_referenced=len(referenced),
                logical_bytes=logical_bytes,
                problems=problems,
                unreferenced=unreferenced,
                deep=deep,
                crcs_verified=crcs_verified,
            )
        finally:
            event_loop.run_until_complete(storage.close())
    finally:
        event_loop.close()


def verify_snapshot(
    path: str, deep: bool = False, tier: Optional[str] = None
) -> FsckReport:
    """Audit one committed snapshot. Never raises for snapshot damage —
    every problem lands in the report; raises only for programmer error
    (e.g. a path that is not a snapshot *directory* at all still yields
    a report with the metadata problem recorded).

    ``tier`` (tiered:// paths only) restricts the audit to one tier:
    ``"fast"`` or ``"durable"``; ``"peer"`` (any path) audits the
    peer-RAM placement journal instead of storage bytes (docs/peer.md).
    The default audits the composed view
    (reads fall back per blob, exactly as restore would resolve them).
    Auditing the durable tier of a partially-mirrored step reports an
    ``unmirrored`` problem with the journal's progress instead of a bare
    missing-commit-marker."""
    audit_path = path
    if tier == "peer":
        # The peer tier is host RAM, not storage: the audit reads the
        # placement journal each pushing rank recorded next to the
        # snapshot (fast tier for tiered paths) and reports which
        # required blobs have NO recorded peer copy — the offline view
        # of what a preemption right now could and could not recover at
        # RAM speed.
        return _verify_peer_placement(path)
    if tier is not None:
        tiers = split_tiered_url(path)
        if tiers is None:
            raise ValueError(
                f"tier={tier!r} requires a tiered:// path, got {path!r}"
            )
        if tier not in ("fast", "durable"):
            raise ValueError(
                f"tier must be 'fast', 'durable' or 'peer', got {tier!r}"
            )
        audit_path = tiers[0] if tier == "fast" else tiers[1]
    problems: List[FsckProblem] = []
    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin(audit_path)
        try:
            read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
            try:
                event_loop.run_until_complete(storage.read(read_io))
                metadata = SnapshotMetadata.from_yaml(
                    bytes(read_io.buf).decode("utf-8")
                )
            except FileNotFoundError:
                partial = None
                if tier == "durable":
                    partial = _describe_partial_mirror(path, event_loop)
                if partial is not None:
                    problems.append(
                        FsckProblem(
                            SNAPSHOT_METADATA_FNAME, "unmirrored", partial
                        )
                    )
                else:
                    problems.append(
                        FsckProblem(
                            SNAPSHOT_METADATA_FNAME,
                            "missing",
                            "no commit marker: not a committed snapshot",
                        )
                    )
                return FsckReport(path, 0, 0, problems, deep)
            except Exception as e:  # noqa: BLE001
                problems.append(
                    FsckProblem(SNAPSHOT_METADATA_FNAME, "unreadable", repr(e))
                )
                return FsckReport(path, 0, 0, problems, deep)

            checksum_table = None
            if deep and not knobs.is_checksums_disabled():
                from .integrity import load_checksum_tables

                checksum_table = load_checksum_tables(
                    metadata.world_size, storage, event_loop
                )

            need = blob_requirements(metadata.manifest)
            slots = asyncio.Semaphore(knobs.get_per_rank_io_concurrency())

            async def _run() -> List[Tuple[int, bool]]:
                return await asyncio.gather(
                    *(
                        _check_blob(
                            storage,
                            loc,
                            n,
                            deep,
                            checksum_table,
                            problems,
                            slots,
                        )
                        for loc, n in sorted(need.items())
                    )
                )

            results = event_loop.run_until_complete(_run())
            return FsckReport(
                path=path,
                blobs_checked=len(need),
                bytes_verified=(
                    sum(nb for nb, crc_ok in results if crc_ok)
                    if deep
                    else 0
                ),
                problems=problems,
                deep=deep,
                crcs_verified=sum(1 for _, crc_ok in results if crc_ok),
            )
        finally:
            event_loop.run_until_complete(storage.close())
    finally:
        event_loop.close()


QUARANTINE_DIRNAME = ".quarantine"


@dataclasses.dataclass
class RepairReport:
    """What ``fsck --repair`` did (docs/chaos.md): ``rewritten`` maps a
    damaged location to the tier directory (or tier name) whose copy
    verified and re-sourced it; ``quarantined`` lists locations no tier
    could vouch for — their copies moved to ``chunks/.quarantine/``
    (chunks) or were left in place but reported (legacy blobs), so a
    later restore fails loudly instead of serving rot; ``unrepairable``
    lists damage with no alternate source at all (non-tiered roots,
    dangling refs)."""

    target: str
    rewritten: Dict[str, str] = dataclasses.field(default_factory=dict)
    quarantined: List[str] = dataclasses.field(default_factory=list)
    unrepairable: List[FsckProblem] = dataclasses.field(
        default_factory=list
    )
    checked: int = 0

    @property
    def acted(self) -> bool:
        return bool(self.rewritten or self.quarantined)


def _post_repair_event(root: str, report: RepairReport) -> None:
    """Record the repair in the root's run ledger (only roots a manager
    opened a run for carry one — ``create=False``); the
    ``storage-corruption`` doctor rule cites these records."""
    try:
        from .telemetry import ledger as run_ledger
        from .telemetry import names as event_names

        run_ledger.post_event(
            root,
            event_names.EVENT_REPAIR_PERFORMED,
            target=report.target,
            rewritten=len(report.rewritten),
            quarantined=len(report.quarantined),
            unrepairable=len(report.unrepairable),
            locations=sorted(
                list(report.rewritten) + report.quarantined
            )[:20],
        )
    except Exception as e:  # noqa: BLE001 - repair must not fail on telemetry
        logger.warning("could not post repair-performed event: %r", e)


def _chunk_copy_ok(path: str, key: str) -> bool:
    """Verify one on-disk chunk copy against its self-describing key
    (size + whole-blob CRC, streamed in bounded chunks)."""
    import os as _os

    from .cas import parse_key
    from .integrity import _alg_available, _crc_of

    parsed = parse_key(key)
    if parsed is None:
        return False
    alg, want_n, want_crc = parsed
    try:
        if _os.path.getsize(path) != want_n:
            return False
    except OSError:
        return False
    if not _alg_available(alg):
        return True  # cannot judge the bytes; size is all we have
    crc = 0
    try:
        with open(path, "rb") as f:
            while True:
                block = f.read(_DEEP_CHUNK_BYTES)
                if not block:
                    break
                crc = _crc_of(memoryview(block), alg, seed=crc)
    except OSError:
        return False
    return crc == want_crc


def repair_cas_store(root: str) -> RepairReport:
    """Cross-tier chunk repair: every chunk a committed manifest
    references is verified per tier copy against its digest key; a
    damaged copy is rewritten from whichever tier's copy verifies, and
    a chunk with NO verifying copy has every copy moved to
    ``chunks/.quarantine/<key>`` — a dangling ref a later restore fails
    on loudly, never bytes served silently corrupt. Posts one
    ``repair-performed`` ledger event when anything was done."""
    import os as _os

    from .cas import chunk_refs

    report = RepairReport(target=root)
    referenced: Set[str] = set()
    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin(root)
        try:
            from . import manager as manager_mod

            try:
                index = event_loop.run_until_complete(
                    manager_mod.read_index_full_async(storage)
                )
                steps = sorted(set(index["steps"]) | set(index["pinned"]))
            except Exception as e:  # noqa: BLE001
                report.unrepairable.append(
                    FsckProblem(manager_mod.INDEX_BLOB, "unreadable", repr(e))
                )
                steps = []
            for step in steps:
                meta_path = (
                    f"{manager_mod._step_dirname(step)}/"
                    f"{SNAPSHOT_METADATA_FNAME}"
                )
                read_io = ReadIO(path=meta_path)
                try:
                    event_loop.run_until_complete(storage.read(read_io))
                    metadata = SnapshotMetadata.from_yaml(
                        bytes(read_io.buf).decode("utf-8")
                    )
                except Exception:  # noqa: BLE001 - verify reports these
                    continue
                referenced.update(chunk_refs(metadata.manifest))
        finally:
            event_loop.run_until_complete(storage.close())
    finally:
        event_loop.close()

    present = _present_chunks(root)
    for key in sorted(referenced & set(present)):
        report.checked += 1
        copies = present[key]
        status = {
            tier_dir: _chunk_copy_ok(_os.path.join(tier_dir, key), key)
            for tier_dir in sorted(copies)
        }
        good = [t for t, ok in status.items() if ok]
        bad = [t for t, ok in status.items() if not ok]
        if not bad:
            continue
        if good:
            src = _os.path.join(good[0], key)
            with open(src, "rb") as f:
                data = f.read()
            for tier_dir in bad:
                dst = _os.path.join(tier_dir, key)
                tmp = dst + ".repair-tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                _os.replace(tmp, dst)
                report.rewritten[f"{tier_dir}/{key}"] = good[0]
        else:
            for tier_dir in bad:
                qdir = _os.path.join(tier_dir, QUARANTINE_DIRNAME)
                _os.makedirs(qdir, exist_ok=True)
                _os.replace(
                    _os.path.join(tier_dir, key),
                    _os.path.join(qdir, key),
                )
            report.quarantined.append(key)
            report.unrepairable.append(
                FsckProblem(
                    f"chunks/{key}",
                    "checksum",
                    "no tier holds a verifying copy; all copies "
                    "quarantined (chunks/.quarantine/)",
                )
            )
    for key in sorted(referenced - set(present)):
        report.unrepairable.append(
            FsckProblem(
                f"chunks/{key}",
                "missing",
                "referenced chunk absent from every tier (dangling "
                "ref); nothing to rebuild from",
            )
        )
    if report.acted:
        _post_repair_event(root, report)
    return report


def repair_snapshot(path: str) -> RepairReport:
    """Cross-tier repair of one committed snapshot's step-local blobs
    (tiered:// paths): every blob with a recorded digest is verified
    per tier, and a damaged copy is rewritten from the tier whose copy
    verifies. Parent-relative locations are skipped — incremental refs
    belong to their origin step, chunk refs to ``--cas --repair``.
    Non-tiered paths have no alternate source: damage is reported
    unrepairable (restores already fail loudly on it)."""
    from .cas import root_url_of_snapshot
    from .integrity import load_checksum_tables, verify_checksum

    report = RepairReport(target=path)
    tiers = split_tiered_url(path)
    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin(path)
        try:
            read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
            try:
                event_loop.run_until_complete(storage.read(read_io))
                metadata = SnapshotMetadata.from_yaml(
                    bytes(read_io.buf).decode("utf-8")
                )
            except Exception as e:  # noqa: BLE001
                report.unrepairable.append(
                    FsckProblem(SNAPSHOT_METADATA_FNAME, "unreadable", repr(e))
                )
                return report
            table = load_checksum_tables(
                metadata.world_size, storage, event_loop
            )
        finally:
            event_loop.run_until_complete(storage.close())
        if not table:
            report.unrepairable.append(
                FsckProblem(
                    SNAPSHOT_METADATA_FNAME,
                    "unreadable",
                    "no checksum tables: repair cannot judge which "
                    "copy is sound",
                )
            )
            return report
        need = blob_requirements(metadata.manifest)
        locations = sorted(
            loc
            for loc in need
            if not loc.startswith("../") and loc in table
        )
        if tiers is None:
            return report  # single tier: nothing to rebuild from
        tier_plugins = []
        for tier_name, tier_url in zip(("fast", "durable"), tiers):
            tier_plugins.append(
                (tier_name, url_to_storage_plugin(tier_url))
            )
        try:
            for loc in locations:
                report.checked += 1
                entry = table[loc]
                copies: Dict[str, Optional[bytes]] = {}
                for tier_name, plugin in tier_plugins:
                    tier_io = ReadIO(path=loc)
                    try:
                        event_loop.run_until_complete(plugin.read(tier_io))
                        copies[tier_name] = bytes(tier_io.buf)
                    except FileNotFoundError:
                        continue  # absent here (evicted/unmirrored): fine
                    except Exception:  # noqa: BLE001
                        copies[tier_name] = None
                good: Optional[bytes] = None
                bad: List[str] = []
                for tier_name, data in copies.items():
                    ok = False
                    if data is not None:
                        try:
                            verify_checksum(data, entry, loc)
                            ok = True
                        except Exception:  # noqa: BLE001 - damage
                            ok = False
                    if ok and good is None:
                        good = data
                    elif not ok:
                        bad.append(tier_name)
                if not bad:
                    continue
                if good is None:
                    report.unrepairable.append(
                        FsckProblem(
                            loc,
                            "checksum",
                            f"no tier holds a verifying copy "
                            f"(damaged: {sorted(bad)})",
                        )
                    )
                    continue
                for tier_name in bad:
                    plugin = dict(tier_plugins)[tier_name]
                    event_loop.run_until_complete(
                        plugin.write(WriteIO(path=loc, buf=good))
                    )
                    report.rewritten[f"{tier_name}:{loc}"] = "cross-tier"
        finally:
            for _, plugin in tier_plugins:
                event_loop.run_until_complete(plugin.close())
    finally:
        event_loop.close()
    if report.acted:
        try:
            _post_repair_event(root_url_of_snapshot(path), report)
        except ValueError:
            pass  # rootless path shapes carry no ledger
    return report


def _print_repair(report: RepairReport) -> None:
    for loc, src in sorted(report.rewritten.items()):
        print(f"FSCK repaired: {loc}: rewritten from {src}")
    for key in report.quarantined:
        print(
            f"FSCK quarantined: chunks/{key}: no tier verified; moved "
            f"to chunks/{QUARANTINE_DIRNAME}/"
        )
    for prob in report.unrepairable:
        print(f"FSCK unrepairable: {prob.location}: {prob.detail}")
    if not report.acted and not report.unrepairable:
        print(f"repair: nothing to do ({report.checked} location(s) sound)")


def _cas_main(root: str, deep: bool, repair: bool = False) -> int:
    if repair:
        _print_repair(repair_cas_store(root))
    report = verify_cas_store(root, deep=deep)
    for prob in report.problems:
        print(f"FSCK {prob.kind}: {prob.location}: {prob.detail}")
    mode = "deep" if report.deep else "shallow"
    print(
        f"chunk store: {report.chunks_present} chunk(s), "
        f"{report.stored_bytes / 1e6:.1f} MB stored across "
        f"{len(report.steps)} retained step(s)"
    )
    print(
        f"  dedup ratio: {report.dedup_ratio:.2f}x "
        f"({report.logical_bytes / 1e6:.1f} MB retention-visible per "
        f"{report.stored_bytes / 1e6:.1f} MB stored); "
        f"{report.bytes_per_retained_step / 1e6:.2f} MB per retained step"
    )
    if report.unreferenced:
        waste = sum(report.unreferenced.values())
        print(
            f"  {len(report.unreferenced)} unreferenced chunk(s) "
            f"({waste / 1e6:.1f} MB): pre-GC orphans of crashed takes or "
            f"dead chunks inside the GC grace window — reclaimed by the "
            f"manager's next retention pass"
        )
    if report.deep:
        print(f"  {report.crcs_verified} chunk(s) CRC-verified")
    if report.ok:
        print(
            f"OK ({mode}): {report.chunks_referenced} referenced "
            f"chunk(s) checked"
        )
        return 0
    print(
        f"FAILED ({mode}): {len(report.problems)} problem(s) across "
        f"{report.chunks_referenced} referenced chunk(s)"
    )
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="torchsnapshot_tpu.fsck",
        description="Verify a committed snapshot's blobs without restoring.",
    )
    p.add_argument("path", help="snapshot location (path or storage URL)")
    p.add_argument(
        "--deep",
        action="store_true",
        help="read every blob fully and verify recorded CRCs",
    )
    p.add_argument(
        "--tier",
        choices=("fast", "durable", "peer"),
        default=None,
        help="for tiered:// paths: audit only this tier (default: the "
        "composed view with per-blob durable fallback). 'peer' audits "
        "the peer-RAM placement journal instead of storage bytes: "
        "which required blobs have a recorded peer copy (docs/peer.md)",
    )
    p.add_argument(
        "--stats",
        action="store_true",
        help="after the audit, summarize the snapshot's telemetry "
        "events (.telemetry.jsonl written by the JSONL sink; see "
        "docs/observability.md)",
    )
    p.add_argument(
        "--cas",
        action="store_true",
        help="treat PATH as a manager ROOT and audit its content-"
        "addressed chunk store (docs/cas.md): every committed "
        "manifest's chunk refs resolve, every referenced chunk has "
        "the byte length its digest key claims (--deep additionally "
        "verifies the bytes against the digest), unreferenced "
        "leftovers are listed, and the dedup ratio / bytes per "
        "retained step are reported",
    )
    p.add_argument(
        "--repair",
        action="store_true",
        help="before the audit, rebuild damaged copies from whichever "
        "tier verifies: with --cas, per-tier chunk repair against the "
        "self-describing digest keys (unrepairable chunks move to "
        "chunks/.quarantine/ and their refs dangle loudly); without, "
        "cross-tier rewrite of a tiered snapshot's step-local blobs "
        "against the checksum tables. Posts repair-performed ledger "
        "events the storage-corruption doctor rule cites "
        "(docs/chaos.md)",
    )
    args = p.parse_args(argv)
    if args.cas:
        return _cas_main(args.path, deep=args.deep, repair=args.repair)
    if args.repair:
        _print_repair(repair_snapshot(args.path))
    report = verify_snapshot(args.path, deep=args.deep, tier=args.tier)
    if args.stats:
        # One artifact sweep: the same Evidence bundle drives the
        # listings below AND the doctor's diagnosis (events, traces and
        # heartbeats are read from disk exactly once).
        from .telemetry.doctor import diagnose_evidence, gather_evidence
        from .telemetry.stats import render_summary

        evidence = gather_evidence(args.path)
        print()
        if evidence.reports:
            print(f"telemetry ({len(evidence.reports)} event(s)):")
            print(render_summary(evidence.reports))
        else:
            print(
                "telemetry: no events recorded for this snapshot (take "
                "it with TORCHSNAPSHOT_TPU_TELEMETRY=1 for the "
                "snapshot-adjacent sink, or run this command with the "
                "same TORCHSNAPSHOT_TPU_TELEMETRY_DIR the take used)"
            )
        n_traces = len(evidence.trace_spans) + len(evidence.trace_unreadable)
        if n_traces:
            print()
            print(f"flight-recorder traces ({n_traces} file(s)):")
            for tf, tops in sorted(evidence.trace_spans.items()):
                top_str = ", ".join(
                    f"{t['name']}={t['dur_ms']}ms" for t in tops[:3]
                )
                print(f"  {tf}: {top_str}")
            for tf, err in sorted(evidence.trace_unreadable.items()):
                print(f"  {tf}: unreadable ({err})")
            print(
                "  merge + straggler summary: "
                "python -m torchsnapshot_tpu.telemetry trace <snapshot>"
            )
        # Progress-heartbeat leftovers: a completed op removes its
        # heartbeat, so anything still here is a live op, a failed one
        # (terminal document), or a crashed one (non-terminal) — the
        # doctor's interrupted-take evidence, listed rather than
        # silently ignored as unknown dotfiles.
        if evidence.progress_files:
            print()
            print(
                f"progress heartbeats ({len(evidence.progress_files)} "
                f"leftover file(s); completed ops remove theirs):"
            )
            docs_by_file = {d.get("file"): d for d in evidence.progress}
            for pf in evidence.progress_files:
                doc = docs_by_file.get(pf)
                if doc is None:
                    print(f"  {pf}: unreadable")
                    continue
                status = doc.get("terminal") or "NOT TERMINAL (live or crashed)"
                print(
                    f"  {pf}: {doc.get('kind', '?')} rank "
                    f"{doc.get('rank', '?')} {doc.get('phase', '?')} — "
                    f"{doc.get('written_bytes', 0)}/"
                    f"{doc.get('planned_bytes', 0)} bytes, "
                    f"{doc.get('items_done', 0)}/"
                    f"{doc.get('planned_items', 0)} items [{status}]"
                )
        # Run-ledger summary: the goodput substrate is a first-class
        # artifact, not an unknown dotfile — event counts, run/segment
        # spans, and interrupted (unclosed) segments, with a pointer at
        # the full attribution CLI.
        if evidence.ledger_records:
            from .telemetry.ledger import describe as describe_ledger

            print()
            print(f"run ledger ({evidence.ledger_file}):")
            for line in describe_ledger(evidence.ledger_records):
                print(f"  {line}")
            print(
                "  full attribution: "
                "python -m torchsnapshot_tpu.telemetry goodput <root>"
            )
        # Captured incident bundles (telemetry/bundle.py): the black
        # boxes an SLO breach / watchdog stall / failed op froze —
        # listed so an audit surfaces them before a cleanup pass does.
        try:
            from .telemetry.bundle import list_bundles

            bundles = list_bundles(args.path)
        except Exception:  # noqa: BLE001 - listing is best-effort
            bundles = []
        if bundles:
            print()
            print(f"incident bundles ({len(bundles)}):")
            for b in bundles:
                print(
                    f"  {b['path']}: trigger {b.get('trigger')!r}, "
                    f"{b.get('files', 0)} files, {b.get('bytes', 0)} "
                    f"bytes"
                )
            print(
                "  analyze: python -m torchsnapshot_tpu.telemetry "
                "doctor --bundle <path>"
            )
        verdicts = diagnose_evidence(evidence)
        if verdicts:
            print()
            print(f"doctor verdicts ({len(verdicts)}):")
            for v in verdicts:
                print(f"  {v.format()}")
        print()
    for prob in report.problems:
        print(f"FSCK {prob.kind}: {prob.location}: {prob.detail}")
    mode = "deep" if report.deep else "shallow"
    if report.deep and report.crcs_verified == 0 and report.blobs_checked:
        print(
            "WARNING: 0 blobs CRC-verified (snapshot has no checksum "
            "tables, or checksums are disabled locally) — this deep "
            "audit checked existence and length only"
        )
    if report.ok:
        extra = (
            f", {report.crcs_verified} CRC-verified "
            f"({report.bytes_verified / 1e6:.1f} MB)"
            if report.deep
            else ""
        )
        print(
            f"OK ({mode}): {report.blobs_checked} blobs checked{extra}"
        )
        return 0
    print(
        f"FAILED ({mode}): {len(report.problems)} problem(s) across "
        f"{report.blobs_checked} blobs"
    )
    return 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
