"""IO preparers: turn pytree leaves into write/read requests + manifest entries.

Reference parity: torchsnapshot/io_preparer.py (the type-dispatch core).
``prepare_write`` dispatch order (reference :872-927): primitive-inline →
sharded array → dense array (chunked when larger than the chunk knob) →
opaque object pickle. ``prepare_read`` mirrors it.

TPU-native design points (vs the reference's CUDA/torch machinery):

- **Immutability replaces defensive copies.** ``jax.Array`` values never
  mutate, so async snapshots need no consistency copy of device state — the
  reference must copy CPU tensors for async takes (io_preparer.py:555-579);
  here only mutable ``np.ndarray`` leaves get that treatment.
- **Async D2H DMA replaces the thread-pool ``.to("cpu")``.** Staging calls
  ``copy_to_host_async()`` at prepare time so the TPU→host transfer overlaps
  other requests' serialization and storage I/O (the overlap the reference
  forgoes, io_preparer.py:522-526).
- **One dtype path.** Every JAX dtype (incl. bf16/fp8) is buffer-protocol
  serializable (serialization.py), so there is no ``TORCH_SAVE`` fallback for
  arrays and no quantized-tensor special case — fp8 is a first-class dtype,
  not a (scale, zero_point) codec.

The sharded-array preparer (``NamedSharding`` shards, elastic resharding)
lives in ``sharded_io_preparer.py``; it subsumes the reference's
ShardedTensorIOPreparer.
"""

from __future__ import annotations

import asyncio
import sys
from concurrent.futures import Executor
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from . import knobs
from .io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    ObjectEntry,
    PrimitiveEntry,
    Shard,
)
from .serialization import (
    SUPPORTED_DTYPES,
    Serializer,
    array_as_memoryview,
    array_from_memoryview,
    array_size_bytes,
    dtype_to_string,
    obj_type_name,
    pickle_load_from_bytes,
    pickle_save_as_bytes,
)

from .telemetry import names as metric_names
from .utils.tracing import trace_annotation

ArrayPrepareFunc = Callable[[Any, bool], Any]


def _jax():
    import jax

    return jax


def is_jax_array(obj: Any) -> bool:
    if "jax" not in sys.modules:
        return False
    import jax

    return isinstance(obj, jax.Array)


def is_sharded_array(obj: Any) -> bool:
    """True when ``obj`` is a jax.Array actually partitioned over devices
    (not merely replicated). Replicated multi-device arrays are dense:
    every process holds the full value."""
    if not is_jax_array(obj):
        return False
    sharding = obj.sharding
    if sharding.is_fully_replicated:
        return False
    return len(sharding.device_set) > 1 or not obj.is_fully_addressable


def get_storage_path(logical_path: str, rank: int, replicated: bool) -> str:
    """Reference parity: io_preparer.py:849-855 (sharded paths are chosen by
    the sharded preparer)."""
    if replicated:
        return f"replicated/{logical_path}"
    return f"{rank}/{logical_path}"


# ---------------------------------------------------------------------------
# Dense arrays
# ---------------------------------------------------------------------------


# Below this size a host-resident buffer is staged inline on the event
# loop instead of a ThreadPoolExecutor round-trip (GIL release buys
# nothing for a sub-millisecond memcpy; the future machinery costs more).
_INLINE_STAGE_MAX_BYTES = 1 << 20


class ArrayBufferStager(BufferStager):
    """Stages a dense array (np.ndarray or unsharded jax.Array) to a host
    byte buffer.

    For jax arrays the D2H DMA is kicked off asynchronously at construction
    (prepare time); ``stage_buffer`` then materializes the (already
    in-flight) host copy on the executor. ``slc`` selects a row range for
    chunked writes — sliced on-device so only the chunk's bytes transfer.
    """

    def __init__(
        self,
        arr: Any,
        is_async_snapshot: bool,
        slc: Optional[slice] = None,
        array_prepare_func: Optional[ArrayPrepareFunc] = None,
    ) -> None:
        self.arr = arr
        self.is_async_snapshot = is_async_snapshot
        self.slc = slc
        self.array_prepare_func = array_prepare_func
        # Whether capture() already pinned a consistent copy of a
        # mutable (numpy) source — staging must not copy it again.
        self._captured = False
        # Device-snapshot async takes skip the D2H prefetch on purpose:
        # capture() pins an ON-DEVICE clone instead, and the background
        # drain's staging pool is what bounds host memory — an eager
        # whole-state prefetch here would fill jax's host-copy cache
        # with the entire checkpoint outside the pool's accounting.
        if (
            is_jax_array(arr)
            and slc is None
            and not (is_async_snapshot and knobs.is_async_device_snapshot_enabled())
            and not self._may_device_pack()
        ):
            try:
                arr.copy_to_host_async()
            except Exception:
                pass  # prefetch is best-effort; np.asarray below still works

    def _may_device_pack(self) -> bool:
        """True when this array will likely land in a device-packed slab
        (batching + device-pack on, pack-capable dtype, below the slab
        threshold): its bytes then leave the device inside the slab's
        single packed transfer, and a per-member prefetch here would pay
        that D2H twice. (Residual: an array that ends up *alone* in its
        device group still stages individually without the prefetch —
        unknowable at prepare time, and bounded at one cold transfer per
        device.)"""
        if (
            self.array_prepare_func is not None
            or not knobs.is_batching_enabled()
            or not knobs.is_device_pack_enabled()
        ):
            return False
        from .ops.device_pack import pack_supported

        if not pack_supported(self.arr.dtype):
            return False
        return (
            self.get_staging_cost_bytes() < knobs.get_slab_size_threshold_bytes()
        )

    def capture(self, cache: dict) -> None:
        """Device-snapshot capture (the deferred-staging async take's
        pre-return consistency point):

        - jax leaves get an on-device clone — dispatched asynchronously,
          so the visible cost is the dispatch, not the copy — making the
          snapshot immune to the application donating (or deleting) the
          live buffers after ``async_take`` returns;
        - mutable numpy leaves get the defensive host copy that staging
          would otherwise have made (staging now runs after control
          returned to training, too late to be a consistency point);
        - either way the copy is made once per underlying array
          (``cache``), however many chunk/shard stagers slice it.

        A jax clone that fails (e.g. a multi-process array this process
        cannot re-materialize on device) falls back to an eager HOST
        snapshot of the bytes — slower (it pays the D2H in the visible
        span, for that leaf only) but never inconsistent."""
        arr = self.arr
        if arr is None:
            return
        key = id(arr)
        if key in cache:
            self.arr = cache[key]
            self._captured = True
            return
        if is_jax_array(arr):
            try:
                import jax.numpy as jnp

                snap = jnp.copy(arr)
            except Exception:  # noqa: BLE001 - host fallback, never torn
                snap = np.ascontiguousarray(np.asarray(arr))
        elif isinstance(arr, np.ndarray):
            snap = np.array(arr, order="C", copy=True)
        else:
            # Exotic array-like: materialize through numpy now — the
            # generic consistency fallback.
            snap = np.array(np.asarray(arr), order="C", copy=True)
        cache[key] = snap
        self.arr = snap
        self._captured = True

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        # Tiny host-resident leaves (torchrec-style 1e5-leaf manifests are
        # mostly these) aren't worth an executor hop: the future/queue
        # machinery costs ~100x the memcpy. Device arrays always go to the
        # executor — np.asarray would block the event loop on D2H — and so
        # do prepare-func stagers: the hook is arbitrary user code and may
        # return a device array or something larger than the pre-prepare
        # size gate saw (same exclusion batcher._is_batchable applies).
        if (
            self.array_prepare_func is None
            and not is_jax_array(self.arr)
            and self.get_staging_cost_bytes() <= _INLINE_STAGE_MAX_BYTES
        ):
            return self._stage_sync()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(executor, self._stage_sync)

    def _stage_sync(self) -> BufferType:
        with trace_annotation(metric_names.SPAN_LEAF_STAGE):
            return self._stage_sync_impl()

    def _stage_sync_impl(self) -> BufferType:
        arr = self.arr
        if self.array_prepare_func is not None:
            arr = self.array_prepare_func(arr, self.is_async_snapshot)
        if self.slc is not None:
            arr = arr[self.slc]
        if is_jax_array(arr):
            # jax.Array is immutable: the host copy is consistent even for
            # async snapshots, with no defensive copy.
            host = np.asarray(arr)
            host = np.ascontiguousarray(host)
        else:
            host = np.asarray(arr)
            if self.is_async_snapshot and not self._captured:
                # Mutable leaf: snapshot a consistent copy before returning
                # control to training (reference io_preparer.py:555-565).
                # A captured source was already copied at async_take time
                # (device-snapshot mode) and nothing mutates it now.
                host = np.array(host, order="C", copy=True)
            else:
                host = np.ascontiguousarray(host)
        # Drop the device reference promptly so HBM isn't pinned by the
        # pending storage write.
        self.arr = None
        return array_as_memoryview(host)

    def get_staging_cost_bytes(self) -> int:
        # Pure arithmetic — slicing a jax array here would run a device op
        # (and allocate HBM) just to read a shape.
        shape = tuple(self.arr.shape)
        if self.slc is not None and shape:
            shape = (len(range(*self.slc.indices(shape[0]))),) + shape[1:]
        return int(np.dtype(self.arr.dtype).itemsize * np.prod(shape, dtype=np.int64))


class ArrayBufferConsumer(BufferConsumer):
    """Deserializes bytes and copies them into a destination view.

    The destination is an ``np.ndarray`` view (possibly a narrowed slice of
    a larger restore target); the copy runs on the executor since it is
    pure-numpy and GIL-releasing for large blocks.
    """

    def __init__(
        self,
        dst: np.ndarray,
        dtype: str,
        shape: Tuple[int, ...],
        dest_owned: bool = False,
    ) -> None:
        self.dst = dst
        self.dtype = dtype
        self.shape = tuple(shape)
        # Only framework-allocated destinations may be read into directly:
        # a failed direct read leaves partial bytes, which is harmless in a
        # fresh buffer but would tear a user-owned in-place array that the
        # caller might keep using after catching the restore error.
        self.dest_owned = dest_owned

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        # Mirror of ArrayBufferStager.stage_buffer: a tiny copy is cheaper
        # than the future/queue round-trip it would ride.
        if self.get_consuming_cost_bytes() <= _INLINE_STAGE_MAX_BYTES:
            self._consume_sync(buf)
            return
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(executor, self._consume_sync, buf)

    def _consume_sync(self, buf: BufferType) -> None:
        with trace_annotation(metric_names.SPAN_LEAF_CONSUME):
            src = array_from_memoryview(buf, self.dtype, self.shape)
            np.copyto(self.dst, src, casting="no")

    def get_consuming_cost_bytes(self) -> int:
        return array_size_bytes(self.shape, self.dtype)

    def direct_destination(self) -> Optional[memoryview]:
        from .serialization import try_writable_byte_view

        if not self.dest_owned:
            return None
        if dtype_to_string(self.dst.dtype) != self.dtype or tuple(
            self.dst.shape
        ) != self.shape:
            return None
        return try_writable_byte_view(self.dst)


class ArrayIOPreparer:
    """Dense-array preparer (reference TensorIOPreparer, io_preparer.py:631-782)."""

    @staticmethod
    def prepare_write(
        obj: Any,
        logical_path: str,
        rank: int,
        replicated: bool,
        is_async_snapshot: bool,
        array_prepare_func: Optional[ArrayPrepareFunc] = None,
        incremental: Optional[Any] = None,
    ) -> Tuple[Entry, List[WriteReq]]:
        location = get_storage_path(logical_path, rank, replicated)
        dtype_str = dtype_to_string(obj.dtype)
        shape = [int(d) for d in obj.shape]
        if incremental is not None:
            # Unchanged since the incremental base: reference its blob and
            # construct no stager (so no D2H prefetch fires).
            ref = incremental.ref_entry(
                tuple(0 for _ in shape), tuple(shape), replicated
            )
            if ref is not None:
                return ref, []
        entry = ArrayEntry(
            location=location,
            serializer=Serializer.BUFFER_PROTOCOL.value,
            dtype=dtype_str,
            shape=shape,
            replicated=replicated,
            digest=(
                incremental.digest_for(tuple(0 for _ in shape), tuple(shape))
                if incremental is not None
                else None
            ),
        )
        req = WriteReq(
            path=location,
            buffer_stager=ArrayBufferStager(
                obj, is_async_snapshot, array_prepare_func=array_prepare_func
            ),
        )
        return entry, [req]

    @staticmethod
    def can_load_inplace(entry: ArrayEntry, obj: Any) -> bool:
        if not isinstance(obj, np.ndarray):
            return False
        return (
            list(obj.shape) == list(entry.shape)
            and dtype_to_string(obj.dtype) == entry.dtype
            and obj.flags.writeable
        )

    @staticmethod
    def empty_array_from_entry(entry: "ArrayEntry | ChunkedArrayEntry") -> np.ndarray:
        from .serialization import string_to_dtype

        return np.empty(tuple(entry.shape), dtype=string_to_dtype(entry.dtype))

    @staticmethod
    def prepare_read(
        entry: ArrayEntry,
        arr_out: np.ndarray,
        buffer_size_limit_bytes: Optional[int] = None,
        dest_owned: bool = False,
    ) -> List[ReadReq]:
        """Build read request(s) for a dense entry into ``arr_out``.

        With a buffer size limit, large entries become multiple *ranged*
        reads, each consuming directly into a flat slice of the destination
        so peak memory stays bounded (reference io_preparer.py:706-752).
        Falls back to one whole read when the destination can't be viewed
        flat (non-contiguous narrow).
        """
        if list(arr_out.shape) != list(entry.shape):
            raise ValueError(
                f"Destination shape {list(arr_out.shape)} != entry shape "
                f"{entry.shape} for {entry.location}"
            )
        total_bytes = array_size_bytes(entry.shape, entry.dtype)
        base = entry.byte_range_tuple[0] if entry.byte_range_tuple else 0

        flat: Optional[np.ndarray] = None
        if (
            buffer_size_limit_bytes is not None
            and total_bytes > buffer_size_limit_bytes
            and arr_out.flags.c_contiguous
        ):
            flat = arr_out.reshape(-1)

        if flat is None:
            byte_range = (
                (base, base + total_bytes) if entry.byte_range_tuple else None
            )
            return [
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ArrayBufferConsumer(
                        dst=arr_out,
                        dtype=entry.dtype,
                        shape=tuple(entry.shape),
                        dest_owned=dest_owned,
                    ),
                    byte_range=byte_range,
                )
            ]

        itemsize = total_bytes // max(1, flat.size)
        elems_per_read = max(1, buffer_size_limit_bytes // itemsize)
        reqs = []
        for begin in range(0, flat.size, elems_per_read):
            end = min(begin + elems_per_read, flat.size)
            reqs.append(
                ReadReq(
                    path=entry.location,
                    buffer_consumer=ArrayBufferConsumer(
                        dst=flat[begin:end],
                        dtype=entry.dtype,
                        shape=(end - begin,),
                        dest_owned=dest_owned,
                    ),
                    byte_range=(base + begin * itemsize, base + end * itemsize),
                )
            )
        return reqs


# ---------------------------------------------------------------------------
# Chunked arrays (large dense arrays written as multiple blobs)
# ---------------------------------------------------------------------------


def chunk_shapes(
    shape: List[int], dtype: str, max_chunk_size_bytes: int
) -> List[Tuple[int, int]]:
    """Split dim 0 into ``[start, stop)`` row ranges of at most the chunk
    budget (rows larger than the budget stay whole — reference
    chunk_tensor, io_preparer.py:72-100). Delegates to the shared
    dim-0 box-splitting in parallel/overlap.py so dense chunking and
    sharded-shard subdivision cannot drift apart."""
    from .parallel.overlap import Box, subdivide_box
    from .serialization import string_to_dtype

    if not shape or shape[0] <= 1:
        return [(0, shape[0] if shape else 0)]
    pieces = subdivide_box(
        Box(tuple(0 for _ in shape), tuple(shape)),
        max_chunk_size_bytes,
        string_to_dtype(dtype).itemsize,
    )
    return [(p.offsets[0], p.offsets[0] + p.sizes[0]) for p in pieces]


def effective_max_chunk_size_bytes(incremental: Optional[Any]) -> int:
    """Digest-enabled takes chunk tighter (the incremental-chunk knob) so
    the skip unit is fine enough for sparse updates; plain takes use the
    chunk knob alone. Applied identically on every step of a base chain,
    keeping chunk boundaries (the digest keys) stable."""
    size = knobs.get_max_chunk_size_bytes()
    if incremental is not None:
        size = min(size, knobs.get_incremental_chunk_size_bytes())
    return size


def effective_max_shard_size_bytes(incremental: Optional[Any]) -> int:
    """Shard-piece analog of :func:`effective_max_chunk_size_bytes`."""
    size = knobs.get_max_shard_size_bytes()
    if incremental is not None:
        size = min(size, knobs.get_incremental_chunk_size_bytes())
    return size


class ChunkedArrayIOPreparer:
    """Reference parity: ChunkedTensorIOPreparer (io_preparer.py:71-164)."""

    @staticmethod
    def should_chunk(obj: Any, incremental: Optional[Any] = None) -> bool:
        nbytes = int(
            np.dtype(obj.dtype).itemsize * np.prod(obj.shape, dtype=np.int64)
        )
        return (
            nbytes > effective_max_chunk_size_bytes(incremental)
            and len(obj.shape) >= 1
            and int(obj.shape[0]) > 1
        )

    @staticmethod
    def prepare_write(
        obj: Any,
        logical_path: str,
        rank: int,
        replicated: bool,
        is_async_snapshot: bool,
        array_prepare_func: Optional[ArrayPrepareFunc] = None,
        incremental: Optional[Any] = None,
    ) -> Tuple[ChunkedArrayEntry, List[WriteReq]]:
        location = get_storage_path(logical_path, rank, replicated)
        dtype_str = dtype_to_string(obj.dtype)
        shape = [int(d) for d in obj.shape]
        chunks: List[Shard] = []
        write_reqs: List[WriteReq] = []
        for start, stop in chunk_shapes(
            shape, dtype_str, effective_max_chunk_size_bytes(incremental)
        ):
            chunk_location = f"{location}_{start}"
            chunk_shape = [stop - start] + shape[1:]
            offsets = [start] + [0] * (len(shape) - 1)
            if incremental is not None:
                ref = incremental.ref_entry(offsets, chunk_shape, replicated)
                if ref is not None:
                    chunks.append(
                        Shard(offsets=offsets, sizes=chunk_shape, array=ref)
                    )
                    continue
            chunks.append(
                Shard(
                    offsets=offsets,
                    sizes=chunk_shape,
                    array=ArrayEntry(
                        location=chunk_location,
                        serializer=Serializer.BUFFER_PROTOCOL.value,
                        dtype=dtype_str,
                        shape=chunk_shape,
                        replicated=replicated,
                        digest=(
                            incremental.digest_for(offsets, chunk_shape)
                            if incremental is not None
                            else None
                        ),
                    ),
                )
            )
            write_reqs.append(
                WriteReq(
                    path=chunk_location,
                    buffer_stager=ArrayBufferStager(
                        obj,
                        is_async_snapshot,
                        slc=slice(start, stop),
                        array_prepare_func=array_prepare_func,
                    ),
                )
            )
        entry = ChunkedArrayEntry(
            dtype=dtype_str, shape=shape, chunks=chunks, replicated=replicated
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ChunkedArrayEntry,
        arr_out: np.ndarray,
        buffer_size_limit_bytes: Optional[int] = None,
        dest_owned: bool = False,
    ) -> List[ReadReq]:
        reqs: List[ReadReq] = []
        for chunk in entry.chunks:
            view = arr_out[
                tuple(
                    slice(o, o + s) for o, s in zip(chunk.offsets, chunk.sizes)
                )
            ]
            reqs.extend(
                ArrayIOPreparer.prepare_read(
                    chunk.array, view, buffer_size_limit_bytes, dest_owned
                )
            )
        return reqs


# ---------------------------------------------------------------------------
# Opaque objects
# ---------------------------------------------------------------------------


class ObjectBufferStager(BufferStager):
    def __init__(self, obj: Any) -> None:
        self.obj = obj
        self._buf: Optional[bytes] = None

    def capture(self, cache: dict) -> None:
        """Objects are snapshotted by pickling them NOW: deferred
        staging would otherwise serialize a mutable object (a metrics
        dict, a dataloader state) after training resumed mutating it.
        Objects are metadata-sized in practice; the pickle cost sits in
        the visible span by design — consistency over latency here."""
        if self._buf is None:
            self._buf = pickle_save_as_bytes(self.obj)
            self.obj = None

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        if self._buf is not None:
            return self._buf
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            executor, pickle_save_as_bytes, self.obj
        )

    def get_staging_cost_bytes(self) -> int:
        if self._buf is not None:
            return len(self._buf)
        return sys.getsizeof(self.obj)


class ObjectBufferConsumer(BufferConsumer):
    """Objects can't be filled in place; the deserialized value is routed to
    a callback (the reference's "box" pattern, snapshot.py:582-591)."""

    def __init__(self, callback: Callable[[Any], None], size_hint: int = 1024) -> None:
        self.callback = callback
        self.size_hint = size_hint

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        obj = await loop.run_in_executor(executor, pickle_load_from_bytes, bytes(buf))
        self.callback(obj)

    def get_consuming_cost_bytes(self) -> int:
        return self.size_hint


class ObjectIOPreparer:
    @staticmethod
    def prepare_write(
        obj: Any,
        logical_path: str,
        rank: int,
        replicated: bool,
    ) -> Tuple[ObjectEntry, List[WriteReq]]:
        location = get_storage_path(logical_path, rank, replicated)
        entry = ObjectEntry(
            location=location,
            serializer=Serializer.PICKLE.value,
            obj_type=obj_type_name(obj),
            replicated=replicated,
        )
        return entry, [WriteReq(path=location, buffer_stager=ObjectBufferStager(obj))]

    @staticmethod
    def prepare_read(
        entry: ObjectEntry, callback: Callable[[Any], None]
    ) -> List[ReadReq]:
        return [
            ReadReq(
                path=entry.location,
                buffer_consumer=ObjectBufferConsumer(callback),
            )
        ]


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


class PrimitivePreparer:
    """Inline-able builtins (reference io_preparer.py:858-869). Note
    ``bool`` resolves before ``int`` because ``PrimitiveEntry.from_object``
    dispatches on the exact type name."""

    @staticmethod
    def should_inline(obj: Any) -> bool:
        return type(obj) in (int, float, str, bool, bytes)

    @staticmethod
    def prepare_write(obj: Any, replicated: bool) -> PrimitiveEntry:
        return PrimitiveEntry.from_object(obj, replicated=replicated)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------


def _is_dense_array(obj: Any) -> bool:
    if is_jax_array(obj):
        return not is_sharded_array(obj)
    return isinstance(obj, np.ndarray) and obj.dtype in SUPPORTED_DTYPES


def prepare_write(
    obj: Any,
    logical_path: str,
    rank: int,
    replicated: bool = False,
    is_async_snapshot: bool = False,
    array_prepare_func: Optional[ArrayPrepareFunc] = None,
    incremental: Optional[Any] = None,
) -> Tuple[Entry, List[WriteReq]]:
    """Reference parity: io_preparer.py:872-927 (dispatch order preserved).

    ``incremental`` is a per-leaf :class:`incremental.LeafIncrementalPlan`
    consulted chunk-by-chunk: unchanged chunks become base-referencing
    entries with no write request (and no stager, hence no D2H)."""
    if PrimitivePreparer.should_inline(obj):
        return PrimitivePreparer.prepare_write(obj, replicated), []
    if is_sharded_array(obj):
        from .sharded_io_preparer import ShardedArrayIOPreparer

        return ShardedArrayIOPreparer.prepare_write(
            obj, logical_path, is_async_snapshot, array_prepare_func,
            incremental=incremental,
        )
    if _is_dense_array(obj):
        if ChunkedArrayIOPreparer.should_chunk(obj, incremental=incremental):
            return ChunkedArrayIOPreparer.prepare_write(
                obj, logical_path, rank, replicated, is_async_snapshot,
                array_prepare_func, incremental=incremental,
            )
        return ArrayIOPreparer.prepare_write(
            obj, logical_path, rank, replicated, is_async_snapshot,
            array_prepare_func, incremental=incremental,
        )
    return ObjectIOPreparer.prepare_write(obj, logical_path, rank, replicated)


def capture_write_reqs(write_reqs: List[WriteReq]) -> int:
    """Device-snapshot capture pass over a take's write plan: every
    stager pins a consistent copy of its source (``BufferStager.capture``
    — on-device clones for jax leaves, host copies for mutable numpy
    leaves, eager pickles for objects) so ``async_take`` may return
    before any staging ran. One shared cache keyed by the source
    object: a leaf sliced into many chunk/shard stagers is snapshotted
    once. Returns the number of distinct sources captured."""
    cache: dict = {}
    for req in write_reqs:
        req.buffer_stager.capture(cache)
    return len(cache)


def prepare_read(
    entry: Entry,
    obj_out: Optional[Any] = None,
    buffer_size_limit_bytes: Optional[int] = None,
    callback: Optional[Callable[[Any], None]] = None,
    dest_owned: bool = False,
) -> List[ReadReq]:
    """Reference parity: io_preparer.py:930-966.

    Dense/chunked entries require an ``np.ndarray`` destination (callers
    allocate via :meth:`ArrayIOPreparer.empty_array_from_entry`); object
    entries require a ``callback``; primitives produce no reads.
    ``dest_owned`` declares the destination framework-allocated, enabling
    direct (zero-copy) storage reads into it; destinations owned by the
    application must keep copy-on-success semantics.
    """
    if isinstance(entry, PrimitiveEntry):
        return []
    if isinstance(entry, ArrayEntry):
        if not isinstance(obj_out, np.ndarray):
            raise ValueError(
                f"Reading {entry.location} requires an np.ndarray destination "
                f"(got {type(obj_out)})"
            )
        return ArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes, dest_owned
        )
    if isinstance(entry, ChunkedArrayEntry):
        if not isinstance(obj_out, np.ndarray):
            raise ValueError(
                f"Reading a chunked entry requires an np.ndarray destination "
                f"(got {type(obj_out)})"
            )
        return ChunkedArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes, dest_owned
        )
    if isinstance(entry, ObjectEntry):
        if callback is None:
            raise ValueError("Reading an object entry requires a callback")
        return ObjectIOPreparer.prepare_read(entry, callback)
    from .manifest import ShardedArrayEntry

    if isinstance(entry, ShardedArrayEntry):
        from .sharded_io_preparer import ShardedArrayIOPreparer

        return ShardedArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes, dest_owned
        )
    raise TypeError(f"prepare_read does not handle entry type {type(entry)}")
