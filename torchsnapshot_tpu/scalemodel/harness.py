"""Scale-model storm harness: simulated ranks over the real code paths.

One simulated rank = one thread running the same SPMD program a real
process would: publish its peer endpoint, then per step run a *save
storm* (path broadcast + manifest gather + commit barrier — the
``Snapshot.take`` coordination skeleton) and a *restore storm* (nonce
broadcast, a real :class:`~torchsnapshot_tpu.fanout.FanoutRestoreContext`
owner-table exchange round over mocked shard blobs served by the
in-memory storage plugin, then the round barrier). A *preemption storm*
kills configured ranks mid-round with the production ``report_error``
discipline and expects every survivor to abandon via
``BarrierError``/``FanoutError`` within seconds, not the store timeout.

The device state is mocked (deterministic per-source-rank byte
patterns, verified after every exchange); the coordination is not —
the storms exercise the exact barrier/store/exchange implementations
shipped to production, so a topology regression shows up here at world
256 instead of on a pod at world 1024.

Attribution: each rank accumulates wall time per structure (collective
broadcast/gather, barrier arrive+depart, fan-out exchange, endpoint
resolve); the harness reports the straggler (max) and mean per
structure plus the registry's ``coordination_*`` counter deltas over
the storm window and the total store requests observed by the optional
:class:`CountingStore` wrapper.
"""

from __future__ import annotations

import asyncio
import dataclasses
import threading
import time
import uuid
from types import SimpleNamespace
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

from .. import dist_store, telemetry
from ..dist_store import (
    InProcessStore,
    LinearBarrier,
    ProcessGroup,
    ShardedStore,
    Store,
    StoreBarrier,
    TCPStore,
    TreeBarrier,
    lookup_endpoints,
    publish_endpoint,
)
from ..fanout import FanoutRestoreContext
from ..pg_wrapper import PGWrapper
from ..resharding import assign_shard_owners
from ..storage_plugins.memory import MemoryStoragePlugin

_ENDPOINT_SERVICE = "scalemodel"


class SimulatedPreemption(RuntimeError):
    """The injected rank-death fault: raised inside a configured rank's
    round, reported into the round barrier exactly like a production
    failure (snapshot.py's ``_reporting_to`` discipline)."""


# ---------------------------------------------------------------------------
# Store adapters
# ---------------------------------------------------------------------------


class CountingStore(Store):
    """Request-counting delegate: every primitive (and every batched op,
    counted as ONE request — it is one wire round trip) bumps a per-op
    counter. The instrument behind the poll-backoff and batching
    request-count pins: correctness claims ride the real store, traffic
    claims ride these counters."""

    def __init__(self, inner: Store) -> None:
        self.inner = inner
        self.counts: Dict[str, int] = {}
        # key -> how many requests touched it (batched ops count each
        # key they carry): summed across ranks, the per-key maximum is
        # the hot-key fan-in — the O(world) wall the tree barrier
        # bounds at O(fanout) and the linear barrier concentrates on
        # its leader keys.
        self.key_touches: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _bump(self, op: str, keys) -> None:
        with self._lock:
            self.counts[op] = self.counts.get(op, 0) + 1
            for key in keys:
                self.key_touches[key] = self.key_touches.get(key, 0) + 1

    @property
    def total_requests(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def set(self, key: str, value: bytes) -> None:
        self._bump("set", (key,))
        self.inner.set(key, value)

    def try_get(self, key: str) -> Optional[bytes]:
        self._bump("try_get", (key,))
        return self.inner.try_get(key)

    def add(self, key: str, amount: int) -> int:
        self._bump("add", (key,))
        return self.inner.add(key, amount)

    def delete(self, key: str) -> None:
        self._bump("delete", (key,))
        self.inner.delete(key)

    def multi_set(self, items: Dict[str, bytes]) -> None:
        self._bump("multi_set", items.keys())
        self.inner.multi_set(items)

    def multi_get(self, keys: Sequence[str]) -> Dict[str, Optional[bytes]]:
        self._bump("multi_get", keys)
        return self.inner.multi_get(keys)

    def multi_delete(self, keys) -> None:
        keys = list(keys)
        self._bump("multi_delete", keys)
        self.inner.multi_delete(keys)


class PerKeyStore(Store):
    """Baseline adapter: exposes ONLY the four primitives, so every
    ``multi_*`` degrades to the ``Store`` base class's per-key loop —
    one round trip per key, the pre-batching wire behavior. The bench's
    "per-key baseline" axis is this wrapper over the same store."""

    def __init__(self, inner: Store) -> None:
        self.inner = inner

    def set(self, key: str, value: bytes) -> None:
        self.inner.set(key, value)

    def try_get(self, key: str) -> Optional[bytes]:
        return self.inner.try_get(key)

    def add(self, key: str, amount: int) -> int:
        return self.inner.add(key, amount)

    def delete(self, key: str) -> None:
        self.inner.delete(key)


# ---------------------------------------------------------------------------
# Configuration / result
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StormConfig:
    """One storm's shape. The ``barrier``/``batched``/``store_shards``
    axes are exactly the structures the tentpole replaced — a bench run
    compares (linear, per-key, 1 shard) against (tree, batched, N)."""

    world_size: int
    steps: int = 1
    # Storm steps run before timing starts: absorbs thread spawn /
    # connect skew so per-structure times are steady-state coordination,
    # not harness startup (the first step's barrier IS the start skew).
    warmup_steps: int = 0
    barrier: str = "tree"  # "tree" | "linear"
    barrier_fanout: int = 16
    batched: bool = True  # False: PerKeyStore hides the multi_* ops
    store: str = "inprocess"  # "inprocess" | "tcp"
    store_shards: int = 1
    shard_bytes: int = 4096
    save_storm: bool = True
    # False strips the save storm to its commit barrier (no broadcast/
    # gather): the pure-barrier storm growth curves are measured on.
    save_collectives: bool = True
    restore_storm: bool = True
    endpoint_round: bool = True
    kill_ranks: FrozenSet[int] = frozenset()
    kill_step: int = 0
    timeout_s: float = 60.0
    count_requests: bool = True
    # The pre-PR poll shape: fixed 5 ms interval instead of exponential
    # backoff. Baseline storms set it so the O(world) idle-QPS wall the
    # backoff removed stays measurable; never used in production.
    legacy_poll: bool = False


@dataclasses.dataclass
class StormResult:
    config: StormConfig
    wall_s: float
    # Per-structure wall time: straggler (max across ranks) and mean.
    max_s: Dict[str, float]
    mean_s: Dict[str, float]
    # Total store requests observed by the CountingStore wrappers.
    store_requests: int
    store_request_ops: Dict[str, int]
    # coordination_* registry counter deltas over the storm window
    # (process-global — run storms one at a time).
    counters: Dict[str, float]
    # rank -> repr(error) for every rank that raised; injected victims
    # land here alongside survivors that (correctly) aborted.
    errors: Dict[int, str]
    # Ranks whose exchanges completed with verified bytes.
    verified_ranks: int
    hung_ranks: int
    # The hottest key's fleet-wide touch count (and which key): the
    # per-key fan-in the tree barrier bounds at O(fanout) where the
    # linear barrier concentrates O(world) waiters on its leader keys.
    # ``hot_data_*`` excludes ``/error`` keys — the error channel is
    # deliberately one shared key every rank polls (poison must reach
    # everyone), so it is O(world) fan-in by design in BOTH topologies
    # and would mask the structural difference.
    hot_key_touches: int = 0
    hot_key: str = ""
    hot_data_key_touches: int = 0
    hot_data_key: str = ""

    @property
    def coordination_s(self) -> float:
        """The straggler's total coordination wall — the storm's
        headline number."""
        return sum(self.max_s.values())

    def survivors_aborted_cleanly(self) -> bool:
        """Under injected rank death: every survivor raised (abandoned)
        rather than hanging to the store timeout."""
        survivors = set(range(self.config.world_size)) - set(
            self.config.kill_ranks
        )
        return self.hung_ranks == 0 and all(
            r in self.errors for r in survivors
        )


# ---------------------------------------------------------------------------
# Mock checkpoint state
# ---------------------------------------------------------------------------


def _shard_pattern(src_rank: int, nbytes: int) -> bytes:
    """Deterministic mock shard bytes for source rank ``src_rank`` —
    what exchange verification checks slices against."""
    unit = src_rank.to_bytes(4, "little", signed=False)
    return (unit * (nbytes // 4 + 1))[:nbytes]


def _seed_blobs(
    world: int, shard_bytes: int, plugin_name: str
) -> Dict[str, Tuple[int, int]]:
    """Seed one mock saved shard blob per source rank into the shared
    in-memory plugin; returns the fan-out windows table."""
    plugin = MemoryStoragePlugin(plugin_name)
    windows: Dict[str, Tuple[int, int]] = {}
    for src in range(world):
        loc = f"step/state/w_{src}.dist"
        plugin._blobs[loc] = _shard_pattern(src, shard_bytes)
        windows[loc] = (0, shard_bytes)
    return windows


def _needs_reqs(rank: int, world: int, windows: Dict[str, Tuple[int, int]]):
    """This rank's mock read plan: its own full shard plus the second
    half of its ring neighbor's — a reshard-shaped pattern that forces
    cross-rank traffic and sub-window slicing through the exchange.
    ``FanoutRestoreContext`` only reads ``path``/``byte_range``."""
    own = f"step/state/w_{rank}.dist"
    neighbor = f"step/state/w_{(rank + 1) % world}.dist"
    lo, hi = windows[neighbor]
    half = lo + (hi - lo) // 2
    return [
        SimpleNamespace(path=own, byte_range=windows[own]),
        SimpleNamespace(path=neighbor, byte_range=(half, hi)),
    ]


def _verify_exchange(
    ctx: FanoutRestoreContext, reqs, shard_bytes: int
) -> None:
    """Every requested window must be byte-identical to the seeded
    pattern — the exchange moved real bytes, not just keys."""
    for req in reqs:
        (lo, hi), data = ctx.cache[req.path]
        a, b = req.byte_range
        src = int(req.path.rsplit("_", 1)[1].split(".")[0])
        expect = _shard_pattern(src, shard_bytes)[a:b]
        got = bytes(data[a - lo : b - lo])
        if got != expect:
            raise AssertionError(
                f"exchange corruption: {req.path}[{a}:{b}] mismatched "
                f"({len(got)} bytes vs {len(expect)} expected)"
            )


# ---------------------------------------------------------------------------
# The storm
# ---------------------------------------------------------------------------


def _build_stores(
    cfg: StormConfig,
) -> Tuple[List[Store], List[Any], List[CountingStore]]:
    """One store per simulated rank (plus the server handles to close,
    plus the wire-level counters). TCP mode gives every rank its own
    client socket(s) — the real wire contention profile; in-process
    mode shares lock-guarded dicts — the protocol-only profile fast
    enough for 1000 ranks in a unit test.

    Counting wraps each WIRE client (i.e. every ShardedStore member
    individually, below the routing layer): a batched op that touches
    two shards costs two wire round trips and must be charged as two —
    counting above the router would undercount exactly the tuned
    sharded configs the bench compares."""
    closers: List[Any] = []
    counters: List[CountingStore] = []

    def counted(wire: Store) -> Store:
        if not cfg.count_requests:
            return wire
        counter = CountingStore(wire)
        counters.append(counter)
        return counter

    if cfg.store == "tcp":
        servers = []
        for _ in range(max(1, cfg.store_shards)):
            srv = TCPStore("127.0.0.1", 0, is_server=True)
            servers.append(srv)
            closers.append(srv)
        stores: List[Store] = []
        for _ in range(cfg.world_size):
            clients = []
            for srv in servers:
                client = TCPStore("127.0.0.1", srv.port, is_server=False)
                closers.append(client)
                clients.append(counted(client))
            stores.append(
                clients[0] if len(clients) == 1 else ShardedStore(clients)
            )
        return stores, closers, counters
    if cfg.store_shards > 1:
        shared: Store = ShardedStore(
            [counted(InProcessStore()) for _ in range(cfg.store_shards)]
        )
    else:
        shared = counted(InProcessStore())
    return [shared] * cfg.world_size, closers, counters


def _make_barrier(
    cfg: StormConfig, prefix: str, store: Store, rank: int
) -> StoreBarrier:
    if cfg.barrier == "linear":
        return LinearBarrier(prefix, store, rank, cfg.world_size)
    return TreeBarrier(
        prefix, store, rank, cfg.world_size, fanout=cfg.barrier_fanout
    )


def _rank_program(
    cfg: StormConfig,
    rank: int,
    store: Store,
    windows: Dict[str, Tuple[int, int]],
    owners: Dict[str, int],
    plugin_name: str,
    timers: Dict[str, float],
    out: Dict[str, Any],
) -> None:
    pg = PGWrapper(
        ProcessGroup(store=store, rank=rank, world_size=cfg.world_size)
    )
    plugin = MemoryStoragePlugin(plugin_name)
    loop = asyncio.new_event_loop()
    # Fleet metrics plane (knob-gated, default OFF): each simulated
    # rank publishes a bounded wire-health snapshot under __obs/ so
    # `python -m torchsnapshot_tpu.telemetry fleet` renders a live
    # per-rank table from a running storm.
    from .. import knobs as _knobs
    from ..telemetry import wire as _wire

    fleet: Optional[_wire.FleetReporter] = None
    if _knobs.is_fleet_obs_enabled():
        fleet = _wire.FleetReporter(
            store, "rank", str(rank), world=cfg.world_size
        )
    try:
        if cfg.endpoint_round:
            publish_endpoint(
                store, _ENDPOINT_SERVICE, rank, "sim-host", 40000 + rank
            )
        for step in range(cfg.warmup_steps + cfg.steps):
            if step == cfg.warmup_steps:
                for k in list(timers):
                    timers[k] = 0.0
            if fleet is not None:
                try:
                    fleet.publish(phase=f"step:{step}")
                except Exception:  # noqa: BLE001 - never stalls the storm
                    pass
            if cfg.save_storm:
                # The Snapshot.take coordination skeleton: one path/nonce
                # broadcast, the manifest gather to rank 0, the commit
                # barrier.
                if cfg.save_collectives:
                    t0 = time.perf_counter()
                    pg.broadcast_object(f"step_{step}")
                    pg.gather_object({"rank": rank, "entries": 1})
                    timers["collective_s"] += time.perf_counter() - t0
                barrier = _make_barrier(
                    cfg, f"__storm/{step}/commit", store, rank
                )
                t0 = time.perf_counter()
                barrier.arrive(cfg.timeout_s)
                barrier.depart(cfg.timeout_s)
                timers["barrier_s"] += time.perf_counter() - t0
            if cfg.restore_storm:
                prefix = f"__storm/{step}/restore"
                barrier = _make_barrier(cfg, prefix, store, rank)
                ctx = FanoutRestoreContext(
                    owners, windows, store, rank, cfg.world_size
                )
                reqs = _needs_reqs(rank, cfg.world_size, windows)
                try:
                    if rank in cfg.kill_ranks and step == cfg.kill_step:
                        raise SimulatedPreemption(
                            f"rank {rank} preempted at step {step}"
                        )
                    t0 = time.perf_counter()
                    ctx.exchange(
                        reqs,
                        plugin,
                        loop,
                        rendezvous_prefix=prefix,
                        timeout=cfg.timeout_s,
                    )
                    timers["exchange_s"] += time.perf_counter() - t0
                    _verify_exchange(ctx, reqs, cfg.shard_bytes)
                    out["verified"] = out.get("verified", 0) + 1
                except BaseException as e:
                    # The production _reporting_to discipline: poison
                    # the round barrier so peers abandon in seconds.
                    try:
                        barrier.report_error(e)
                    except Exception:  # noqa: BLE001 - already failing
                        pass
                    raise
                finally:
                    ctx.clear()
                t0 = time.perf_counter()
                barrier.arrive(cfg.timeout_s)
                barrier.depart(cfg.timeout_s)
                timers["barrier_s"] += time.perf_counter() - t0
        if cfg.endpoint_round:
            # Restore-setup shape: resolve EVERY rank's endpoint (one
            # batched round trip on a batched store; world sequential
            # lookups through PerKeyStore — the measured difference).
            t0 = time.perf_counter()
            endpoints = lookup_endpoints(
                store, _ENDPOINT_SERVICE, range(cfg.world_size)
            )
            timers["endpoint_s"] += time.perf_counter() - t0
            if not cfg.kill_ranks and len(endpoints) != cfg.world_size:
                raise AssertionError(
                    f"rank {rank}: resolved {len(endpoints)} of "
                    f"{cfg.world_size} endpoints"
                )
    finally:
        if fleet is not None:
            try:
                fleet.close()
            except Exception:  # noqa: BLE001
                pass
        loop.close()


def run_storm(cfg: StormConfig) -> StormResult:
    """Run one storm to completion and attribute it. Never raises for
    per-rank failures (they land in ``result.errors``); raises only for
    harness-level misuse."""
    if cfg.world_size < 1:
        raise ValueError("world_size must be >= 1")
    plugin_name = f"scalemodel-{uuid.uuid4().hex}"
    windows = _seed_blobs(cfg.world_size, cfg.shard_bytes, plugin_name)
    owners = assign_shard_owners(windows, cfg.world_size)
    # Counting sits at the WIRE (inside _build_stores, per shard
    # member): a PerKeyStore above fans every multi_* into per-key
    # requests and the baseline is charged for exactly that traffic; a
    # sharded batch is charged one request per touched shard.
    stores, closers, counters = _build_stores(cfg)
    rank_stores: List[Store] = [
        s if cfg.batched else PerKeyStore(s) for s in stores
    ]

    timers: List[Dict[str, float]] = [
        {"collective_s": 0.0, "barrier_s": 0.0, "exchange_s": 0.0,
         "endpoint_s": 0.0}
        for _ in range(cfg.world_size)
    ]
    outs: List[Dict[str, Any]] = [{} for _ in range(cfg.world_size)]
    errors: Dict[int, str] = {}
    errors_lock = threading.Lock()

    def _run(rank: int) -> None:
        try:
            _rank_program(
                cfg,
                rank,
                rank_stores[rank],
                windows,
                owners,
                plugin_name,
                timers[rank],
                outs[rank],
            )
        except BaseException as e:  # noqa: BLE001 - recorded, not raised
            with errors_lock:
                errors[rank] = repr(e)

    counter_baseline = telemetry.metrics().counters_snapshot()
    threads = [
        threading.Thread(
            target=_run, args=(r,), name=f"simrank-{r}", daemon=True
        )
        for r in range(cfg.world_size)
    ]
    prev_profile = None
    if cfg.legacy_poll:
        prev_profile = dist_store._set_poll_profile(0.005, 0.005)
    t_start = time.perf_counter()
    try:
        for t in threads:
            t.start()
        join_deadline = time.monotonic() + cfg.timeout_s + 30.0
        hung = 0
        for t in threads:
            t.join(timeout=max(0.1, join_deadline - time.monotonic()))
            if t.is_alive():
                hung += 1
        wall_s = time.perf_counter() - t_start
    finally:
        if prev_profile is not None:
            dist_store._set_poll_profile(*prev_profile)
    deltas = telemetry.metrics().counters_delta_since(counter_baseline)
    coord_counters = {
        k: round(v, 6)
        for k, v in deltas.items()
        if k.startswith("coordination_")
    }

    try:
        MemoryStoragePlugin.drop_store(plugin_name)
    finally:
        for c in closers:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass

    structures = ("collective_s", "barrier_s", "exchange_s", "endpoint_s")
    max_s = {
        k: round(max(t[k] for t in timers), 6) for k in structures
    }
    mean_s = {
        k: round(sum(t[k] for t in timers) / cfg.world_size, 6)
        for k in structures
    }
    request_ops: Dict[str, int] = {}
    key_touches: Dict[str, int] = {}
    for c in counters:
        for op, n in c.counts.items():
            request_ops[op] = request_ops.get(op, 0) + n
        for key, n in c.key_touches.items():
            key_touches[key] = key_touches.get(key, 0) + n
    hot_key, hot_touches = "", 0
    hot_data_key, hot_data_touches = "", 0
    for key, n in key_touches.items():
        if n > hot_touches:
            hot_key, hot_touches = key, n
        if n > hot_data_touches and not key.endswith("/error"):
            hot_data_key, hot_data_touches = key, n
    return StormResult(
        config=cfg,
        wall_s=round(wall_s, 6),
        max_s=max_s,
        mean_s=mean_s,
        store_requests=sum(request_ops.values()),
        store_request_ops=request_ops,
        hot_key_touches=hot_touches,
        hot_key=hot_key,
        hot_data_key_touches=hot_data_touches,
        hot_data_key=hot_data_key,
        counters=coord_counters,
        errors=errors,
        verified_ranks=sum(o.get("verified", 0) > 0 for o in outs),
        hung_ranks=hung,
    )
