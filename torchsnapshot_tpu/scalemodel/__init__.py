"""Thousand-rank scale model for the coordination plane.

Hundreds (tests) to a thousand (slow sweep / bench) of simulated ranks
— threads with mocked device state — drive the REAL coordination code
paths (``dist_store`` barriers and collectives, ``pg_wrapper``,
``fanout`` owner-table exchange rounds, the peer tier's endpoint
registry) through save/restore/preemption storms, attributing
coordination wall time per structure vs world size. This is what lets
the O(world) walls (leader-centric barriers, per-key store scans,
single-hub sockets) be *measured* and their fixes (TreeBarrier, batched
``multi_*`` store ops, ShardedStore) be held to curves instead of
vibes: ``benchmarks/coordination_scaling.py`` runs the same storms as
bench leg 10, and ``tests/test_scalemodel.py`` pins correctness under
injected rank death. See docs/scaling.md.
"""

from .cdn_storm import (  # noqa: F401
    CdnStormConfig,
    CdnStormResult,
    build_step_chunks,
    run_cdn_storm,
)
from .harness import (  # noqa: F401
    CountingStore,
    PerKeyStore,
    SimulatedPreemption,
    StormConfig,
    StormResult,
    run_storm,
)
