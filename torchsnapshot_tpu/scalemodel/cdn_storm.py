"""CDN subscriber storm: a serving fleet tracking a publishing trainer.

One publisher thread announces ``steps`` synthetic checkpoint steps
(content-addressed chunk sets with a configurable per-step churn
fraction — a rolling update replaces some chunks, keeps the rest) while
``fleet_size`` subscriber threads run the REAL
:class:`~torchsnapshot_tpu.cdn.CdnSubscriber` machinery: each runs its
own peer-cache TCP server, polls the topic head with the world-scaled
pacer, elects chunk owners, pulls novel chunks peer-to-peer, and
hot-swaps via :class:`~torchsnapshot_tpu.cdn.WeightSwapper`.

The storm's pins (bench leg 11, tests/test_cdn_storm.py):

- **read amplification** — durable reads / unique chunks published,
  counted by the wrapped ``durable_fetch``. Owner election makes this
  ~1.0 regardless of fleet size (each unique chunk leaves durable
  storage once; timeouts under load may add a small epsilon).
- **staleness** — publish-to-swap seconds per subscriber per step; the
  storm reports the distribution (median/p90/max).
- **dedup ratio** — fleet bytes-on-wire vs. fleet bytes-in-steps: a
  rolling update ships only churned chunks, so wire bytes stay well
  under step bytes once the fleet holds a baseline.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..cas import digest_key
from ..cdn import CdnPublisher, CdnSubscriber, WeightSwapper
from ..dist_store import InProcessStore, Store
from ..knobs import override_cdn_pull_timeout_seconds


@dataclasses.dataclass
class CdnStormConfig:
    fleet_size: int
    steps: int = 3
    # Bootstrap steps published (and applied) before measurement: the
    # fleet's first sync pulls the FULL chunk set (cold start), while
    # the staleness pin is about the steady state where only churned
    # chunks ship. Staleness samples from warmup steps are excluded;
    # byte/read accounting still covers the whole schedule.
    warmup_steps: int = 1
    chunks_per_step: int = 8
    chunk_bytes: int = 4096
    # Fraction of the chunk set replaced each step (a rolling update);
    # 1.0 = every step all-new, 0.0 = pure re-announce.
    churn_fraction: float = 0.25
    publish_interval_s: float = 0.05
    pull_timeout_s: float = 2.0
    # Per-subscriber wait for the whole storm to complete.
    timeout_s: float = 60.0
    topic: str = "storm"
    swap: bool = True


@dataclasses.dataclass
class CdnStormResult:
    config: CdnStormConfig
    wall_s: float
    # Durable-read accounting (the ~1x pin).
    durable_reads: int
    unique_chunks_published: int
    read_amplification: float
    # Fleet byte split (the dedup pin).
    bytes_on_wire: int
    bytes_in_steps: int
    bytes_from_peer: int
    bytes_from_durable: int
    # Publish-to-swap staleness distribution across all (sub, step).
    staleness_median_s: float
    staleness_p90_s: float
    staleness_max_s: float
    staleness_samples: int
    # Convergence: subscribers whose final applied seq == steps.
    converged_subscribers: int
    peer_fallbacks: int
    errors: Dict[int, str]
    # Wire split (telemetry/wire.py): per-tier pull-latency quantiles
    # pooled across the fleet ({tier: {p50_s, p95_s, samples}}) and the
    # process's per-op wire report split (frames/bytes/rpcs + per-RPC
    # table) — what bench leg 11's RESULT line cites for "where did the
    # bytes ride and how long did a pull take per tier".
    pull_latency: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )
    wire: Optional[Dict[str, object]] = None

    @property
    def dedup_ratio(self) -> float:
        """bytes_on_wire / bytes_in_steps — < 1 means the fleet shipped
        less than the steps' logical size (held chunks re-served)."""
        if self.bytes_in_steps <= 0:
            return 0.0
        return self.bytes_on_wire / self.bytes_in_steps

    def converged(self) -> bool:
        return self.converged_subscribers == self.config.fleet_size


def _make_chunk(seed: int, nbytes: int) -> Tuple[str, bytes]:
    """Deterministic unique chunk bytes + self-describing CAS key."""
    unit = seed.to_bytes(8, "little", signed=False)
    data = (unit * (nbytes // 8 + 1))[:nbytes]
    key = digest_key(("crc32", zlib.crc32(data), len(data)))
    return key, data


def build_step_chunks(
    cfg: CdnStormConfig,
) -> Tuple[List[Dict[str, int]], Dict[str, bytes]]:
    """The storm's publish schedule: per-step chunk sets with churn,
    plus the backing blob map the counting ``durable_fetch`` serves."""
    blobs: Dict[str, bytes] = {}
    schedule: List[Dict[str, int]] = []
    keys: List[str] = []
    seed = 0
    for step in range(cfg.warmup_steps + cfg.steps):
        if step == 0:
            replace = cfg.chunks_per_step
        else:
            replace = max(
                1, int(round(cfg.chunks_per_step * cfg.churn_fraction))
            )
        kept = keys[: cfg.chunks_per_step - replace]
        fresh: List[str] = []
        for _ in range(replace):
            key, data = _make_chunk(seed, cfg.chunk_bytes)
            seed += 1
            blobs[key] = data
            fresh.append(key)
        keys = fresh + kept
        schedule.append({k: len(blobs[k]) for k in keys})
    return schedule, blobs


def run_cdn_storm(
    cfg: CdnStormConfig, store: Optional[Store] = None
) -> CdnStormResult:
    store = store if store is not None else InProcessStore()
    schedule, blobs = build_step_chunks(cfg)
    unique_chunks = len(blobs)
    bytes_in_steps = sum(sum(c.values()) for c in schedule)

    durable_lock = threading.Lock()
    durable_reads = {"n": 0}

    def durable_fetch(key: str) -> bytes:
        with durable_lock:
            durable_reads["n"] += 1
        return blobs[key]

    # Subscribers read the pull timeout from the knob at call time; the
    # storm pins it for its own window and restores the caller's value.
    cleanup = contextlib.ExitStack()
    cleanup.enter_context(
        override_cdn_pull_timeout_seconds(cfg.pull_timeout_s)
    )
    subs: List[CdnSubscriber] = []
    errors: Dict[int, str] = {}
    errors_lock = threading.Lock()
    started = time.monotonic()
    try:
        subs = [
            CdnSubscriber(
                store,
                cfg.topic,
                i,
                cfg.fleet_size,
                durable_fetch=durable_fetch,
            )
            for i in range(cfg.fleet_size)
        ]

        import numpy as np

        total_bytes = cfg.chunks_per_step * cfg.chunk_bytes
        total_steps = cfg.warmup_steps + cfg.steps
        deadline = time.monotonic() + cfg.timeout_s

        def subscriber_main(sub: CdnSubscriber) -> None:
            swapper = (
                WeightSwapper({"w": np.zeros(total_bytes, np.uint8)})
                if cfg.swap
                else None
            )
            try:
                while (
                    sub.applied_seq < total_steps
                    and time.monotonic() < deadline
                ):
                    sub.track_once(swapper, timeout=0.25)
            except BaseException as e:  # noqa: BLE001 - recorded, not raised
                with errors_lock:
                    errors[sub.subscriber_id] = repr(e)

        threads = [
            threading.Thread(
                target=subscriber_main, args=(s,), daemon=True
            )
            for s in subs
        ]
        for t in threads:
            t.start()

        publisher = CdnPublisher(store, cfg.topic, publisher_id="storm")
        for step, chunks in enumerate(
            schedule[: cfg.warmup_steps], start=1
        ):
            publisher.publish(step, chunks)
            time.sleep(cfg.publish_interval_s)
        # Warmup barrier: wait for the fleet to finish its cold
        # bootstrap, then snapshot per-sub sample counts so the
        # staleness distribution covers steady-state steps only.
        while (
            any(s.applied_seq < cfg.warmup_steps for s in subs)
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        time.sleep(cfg.publish_interval_s)
        warmup_samples = [len(s.stats.staleness_s) for s in subs]
        for step, chunks in enumerate(
            schedule[cfg.warmup_steps :], start=cfg.warmup_steps + 1
        ):
            publisher.publish(step, chunks)
            time.sleep(cfg.publish_interval_s)

        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()) + 1.0)
        wall_s = time.monotonic() - started

        staleness = sorted(
            s
            for sub, base in zip(subs, warmup_samples)
            for s in sub.stats.staleness_s[base:]
        )

        def pct(frac: float) -> float:
            if not staleness:
                return 0.0
            return staleness[
                min(len(staleness) - 1, int(len(staleness) * frac))
            ]

        pulls_by_tier: Dict[str, List[float]] = {}
        for sub in subs:
            for tier, samples in sub.stats.pull_latency_s.items():
                pulls_by_tier.setdefault(tier, []).extend(samples)
        pull_latency: Dict[str, Dict[str, float]] = {}
        for tier, samples in sorted(pulls_by_tier.items()):
            samples.sort()

            def tier_pct(frac: float) -> float:
                return samples[
                    min(len(samples) - 1, int(len(samples) * frac))
                ]

            pull_latency[tier] = {
                "p50_s": round(tier_pct(0.5), 6),
                "p95_s": round(tier_pct(0.95), 6),
                "samples": len(samples),
            }

        from ..telemetry import metrics
        from ..telemetry.report import wire_from_deltas

        return CdnStormResult(
            config=cfg,
            wall_s=round(wall_s, 3),
            durable_reads=durable_reads["n"],
            unique_chunks_published=unique_chunks,
            read_amplification=(
                durable_reads["n"] / unique_chunks if unique_chunks else 0.0
            ),
            bytes_on_wire=sum(s.stats.bytes_on_wire for s in subs),
            bytes_in_steps=bytes_in_steps * cfg.fleet_size,
            bytes_from_peer=sum(s.stats.bytes_from_peer for s in subs),
            bytes_from_durable=sum(
                s.stats.bytes_from_durable for s in subs
            ),
            staleness_median_s=round(pct(0.5), 6),
            staleness_p90_s=round(pct(0.9), 6),
            staleness_max_s=round(staleness[-1], 6) if staleness else 0.0,
            staleness_samples=len(staleness),
            converged_subscribers=sum(
                1 for s in subs if s.applied_seq >= total_steps
            ),
            peer_fallbacks=sum(s.stats.peer_fallbacks for s in subs),
            errors=errors,
            pull_latency=pull_latency,
            # The whole storm shares one process registry, so the
            # counters ARE the storm's deltas in a fresh bench process;
            # a long-lived caller sees its own prior traffic folded in.
            wire=wire_from_deltas(metrics().counters_snapshot()),
        )
    finally:
        for sub in subs:
            try:
                sub.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
        cleanup.close()
