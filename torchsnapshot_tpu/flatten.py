"""Reversible flattening of nested containers into ``{path: leaf}`` maps.

Reference parity: torchsnapshot/flatten.py:18-215. Same behavioral contract:

- ``/`` separates hierarchy levels; ``%``/``/`` inside user keys are
  percent-encoded (``%25``/``%2F``) so paths are unambiguous.
- Exactly ``list``, ``dict`` and ``OrderedDict`` instances flatten; a dict
  whose keys are not all str/int, or whose stringified keys collide, stays an
  opaque leaf (it will be pickled whole).
- ``inflate`` reconstructs the original nesting from the container manifest
  plus the flattened leaves, recovering int dict keys.

The implementation is iterative (explicit work stack for flatten, deepest-
first assembly for inflate) rather than recursive.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Tuple
from urllib.parse import unquote

from .manifest import DictEntry, Entry, ListEntry, Manifest, OrderedDictEntry


def _encode(s: str) -> str:
    """Escape ``%`` then ``/`` (subset of RFC 3986 sufficient for
    reversibility; decode is a full ``unquote``)."""
    return s.replace("%", "%25").replace("/", "%2F")


def _decode(s: str) -> str:
    return unquote(s)


def _is_flattenable_dict(d: Dict[Any, Any]) -> bool:
    keys = list(d.keys())
    # bool is an int subclass but str(True) can't be recovered as a key by
    # inflate; bool-keyed dicts stay opaque leaves.
    if not all(
        isinstance(k, (str, int)) and not isinstance(k, bool) for k in keys
    ):
        return False
    return len({str(k) for k in keys}) == len(keys)


def flatten(obj: Any, prefix: str) -> Tuple[Manifest, Dict[str, Any]]:
    """Flatten ``obj`` under an encoded ``prefix``.

    Returns ``(container_manifest, {path: leaf})``. See module docstring for
    the contract; matches the reference doctest at flatten.py:29-44.
    """
    manifest: Manifest = {}
    flattened: Dict[str, Any] = {}
    stack: List[Tuple[str, Any]] = [(_encode(prefix), obj)]
    while stack:
        path, node = stack.pop()
        node_type = type(node)
        if node_type is list:
            manifest[path] = ListEntry()
            # Reversed push keeps result insertion order stable (cosmetic).
            for idx in reversed(range(len(node))):
                stack.append((f"{path}/{idx}", node[idx]))
        elif node_type in (dict, OrderedDict) and _is_flattenable_dict(node):
            keys = list(node.keys())
            if node_type is dict:
                manifest[path] = DictEntry(keys=keys)
            else:
                manifest[path] = OrderedDictEntry(keys=keys)
            for key in reversed(keys):
                stack.append((f"{path}/{_encode(str(key))}", node[key]))
        else:
            flattened[path] = node
    return manifest, flattened


def inflate(
    manifest: Manifest, flattened: Dict[str, Any], prefix: str
) -> Any:
    """Rebuild the nested object flattened under ``prefix``.

    Containers are instantiated from their entries, then populated deepest-
    first so children exist before their parents consume them.
    """
    prefix = _encode(prefix)
    manifest = {k: v for k, v in manifest.items() if k.split("/", 1)[0] == prefix}
    flattened = {k: v for k, v in flattened.items() if k.split("/", 1)[0] == prefix}

    if prefix in flattened:
        # flatten() of a non-flattenable object yields ({}, {prefix: obj}).
        return flattened[prefix]
    if prefix not in manifest:
        raise AssertionError(
            f"{prefix} is absent from both the container manifest and the "
            f"flattened leaves.\nmanifest: {manifest}\nflattened: {flattened}"
        )

    containers: Dict[str, Any] = {
        path: _new_container(entry) for path, entry in manifest.items()
    }

    # Attach every value (leaf or container) to its parent container,
    # processing deepest paths first so containers are complete when their
    # parents pick them up. (list order / dict key fidelity is handled by
    # _attach.)
    items = list(containers.items()) + list(flattened.items())
    pending: Dict[str, Dict[str, Any]] = {}
    for path, value in items:
        if path == prefix:
            continue
        parent, _, key = path.rpartition("/")
        pending.setdefault(parent, {})[key] = value

    for parent, values in pending.items():
        if parent not in containers:
            raise AssertionError(
                f"Path {parent!r} has children but no container entry "
                f"(entries: {list(manifest)})."
            )
        _attach(parent, containers[parent], values)
    return containers[prefix]


def _new_container(entry: Entry) -> Any:
    if isinstance(entry, ListEntry):
        return []
    if isinstance(entry, OrderedDictEntry):
        return OrderedDict.fromkeys(entry.keys)
    if isinstance(entry, DictEntry):
        # Pre-seeding with None preserves the original key order.
        return dict.fromkeys(entry.keys)
    raise RuntimeError(f"Not a container entry: {type(entry)} ({entry.type}).")


def _attach(path: str, container: Any, values: Dict[str, Any]) -> None:
    if isinstance(container, list):
        container.extend(v for _, v in sorted(values.items(), key=lambda kv: int(kv[0])))
        return
    if isinstance(container, dict):
        for raw_key, value in values.items():
            key: Any = _decode(raw_key)
            if key not in container and _looks_like_int(key):
                key = int(key)
            if key not in container:
                raise RuntimeError(
                    f"{key!r} is not a key of container {path!r} "
                    f"(keys: {list(container.keys())})."
                )
            container[key] = value
        return
    raise AssertionError(f"Unrecognized container type: {type(container)}.")


def _looks_like_int(s: str) -> bool:
    body = s[1:] if s[:1] in ("-", "+") and len(s) > 1 else s
    return body.isdigit()
