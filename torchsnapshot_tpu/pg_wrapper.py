"""Uniform object-collective interface for snapshot coordination.

Reference parity: torchsnapshot/pg_wrapper.py:15-89 (``PGWrapper`` over
``torch.distributed``). The TPU-native design moves *only metadata* through
these collectives (manifests, plans, paths — never array data; reference
behavior is identical, §2.11 of SURVEY.md), so they ride a small KV-store
("coordinator") rather than ICI: in multi-process runs that's the store from
``dist_store.py`` (TCP store or the JAX coordination service); in
single-process runs everything degenerates to no-ops, mirroring the
reference's uninitialized-process-group behavior.
"""

from __future__ import annotations

import logging
import threading
import weakref
from typing import Any, List, Optional, Sequence

from .dist_store import Store, make_barrier

logger: logging.Logger = logging.getLogger(__name__)

# Shared op-seq storage for store objects that reject attribute assignment
# (__slots__/frozen): falls back to identity-keyed weak references. Values
# are per-rank dicts: {rank: [seq]}.
_OP_SEQ_REFS: "weakref.WeakKeyDictionary[Any, dict]" = (
    weakref.WeakKeyDictionary()
)
# Guards the check-then-set on the store's per-rank counter dict: wrappers
# for different ranks may be constructed concurrently over one store
# object (thread-based multi-rank harnesses).
_OP_SEQ_LOCK = threading.Lock()


class PGWrapper:
    """Object collectives with a world-size-1 fast path.

    ``pg`` may be ``None`` (single process), an existing :class:`PGWrapper`,
    or a ``(store, rank, world_size)`` triple / :class:`ProcessGroup`-like
    object exposing ``store``/``rank``/``world_size``.
    """

    def __init__(self, pg: Optional[Any] = None) -> None:
        # The op sequence is SHARED across every wrapper over the same
        # underlying (store, rank) — attached to the store object, keyed by
        # rank (see _shared_op_seq_ref): keyed store ops are only cleaned
        # up by the *last* rank to finish one, so a fresh wrapper
        # restarting at op 1 would overwrite a key a slow peer has not
        # read yet (e.g. a manager broadcast followed by Snapshot.take,
        # which builds its own wrapper). Call sequences are SPMD-identical
        # across ranks, so the shared counter stays aligned everywhere.
        if pg is None:
            self.store: Optional[Store] = None
            self.rank = 0
            self.world_size = 1
            self._op_seq_ref = [0]
        elif isinstance(pg, PGWrapper):
            self.store = pg.store
            self.rank = pg.rank
            self.world_size = pg.world_size
            self._op_seq_ref = pg._op_seq_ref
        else:
            self.store = pg.store
            self.rank = int(pg.rank)
            self.world_size = int(pg.world_size)
            self._op_seq_ref = _shared_op_seq_ref(pg)

    def get_rank(self) -> int:
        return self.rank

    def get_world_size(self) -> int:
        return self.world_size

    def _next_prefix(self, op: str) -> str:
        self._op_seq_ref[0] += 1
        return f"__pg/{op}/{self._op_seq_ref[0]}"

    def barrier(self) -> None:
        if self.world_size == 1:
            return
        assert self.store is not None
        # Rides make_barrier like every snapshot-phase rendezvous: the
        # O(log world) tree by default (no key with more than fanout
        # waiters — at a thousand ranks the old single go-key release
        # was a thundering herd on one hub socket), LinearBarrier
        # behind the same kill switch.
        b = make_barrier(
            self._next_prefix("barrier"), self.store, self.rank,
            self.world_size,
        )
        b.arrive()
        b.depart()

    def all_gather_object(self, obj: Any) -> List[Any]:
        """Gather one picklable object per rank, returned in rank order."""
        if self.world_size == 1:
            return [obj]
        assert self.store is not None
        return self.store.exchange(
            self._next_prefix("ag"), self.rank, self.world_size, obj
        )

    def gather_object(self, obj: Any, dst: int = 0) -> Optional[List[Any]]:
        """Gather one picklable object per rank to ``dst`` (rank order);
        returns None on every other rank. Non-destination ranks pay
        O(own object) store traffic — use this instead of
        :meth:`all_gather_object` whenever only one rank consumes the
        result (e.g. the manifest gather: rank 0 alone writes metadata)."""
        if self.world_size == 1:
            return [obj]
        assert self.store is not None
        return self.store.gather(
            self._next_prefix("ga"), self.rank, self.world_size, obj, dst
        )

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        """Broadcast ``obj`` from ``src``; other ranks' inputs are ignored."""
        if self.world_size == 1:
            return obj
        assert self.store is not None
        return self.store.broadcast(
            self._next_prefix("bc"), self.rank, self.world_size, obj, src
        )

    def agree_object(self, obj: Any) -> Any:
        """Rank 0 decides, everyone follows: broadcast rank 0's ``obj``
        and return it on every rank (other ranks' inputs are ignored;
        world-size-1 returns ``obj`` untouched). The blessed way to turn
        a knob/env reading into a job-wide decision *before* gating any
        collective work on it — the result is rank-uniform by
        construction, so a guard over it can never skew a rendezvous
        (snaplint's collective-under-conditional rule treats agreement
        results as laundered taint for exactly this reason)."""
        return self.broadcast_object(obj)

    def scatter_object_list(self, objs: Optional[Sequence[Any]], src: int = 0) -> Any:
        """Rank ``src`` provides one object per rank; each rank receives its
        own. (The reference emulates this over broadcast for NCCL,
        pg_wrapper.py:83-87; over a store it is a direct exchange.)"""
        if self.world_size == 1:
            assert objs is not None
            return objs[0]
        assert self.store is not None
        return self.store.scatter(
            self._next_prefix("sc"), self.rank, self.world_size, objs, src
        )


def _shared_op_seq_ref(pg: Any) -> List[int]:
    """One op-seq counter per ``(store, rank)``, surviving wrapper and pg
    churn. Store-key collisions are scoped to the *store*, not the pg: two
    ProcessGroup objects wrapping the same store (e.g. two
    ``jax_process_group()`` calls, one handed to CheckpointManager and one
    to Snapshot) must share one ``__pg/*`` namespace counter. The rank is
    part of the key because each rank mirrors the global op sequence
    through its own call stream (relevant when a test harness runs several
    ranks as threads over one store object). Attribute attachment first;
    weak-ref registry for frozen/slots stores; only truly un-referenceable
    keys degrade to per-wrapper sequences (loudly — aliasing re-appears
    then)."""
    key = getattr(pg, "store", None)
    if key is None:
        key = pg
    rank = int(getattr(pg, "rank", 0))
    with _OP_SEQ_LOCK:
        refs = getattr(key, "_ts_op_seq_refs", None)
        if refs is None:
            refs = {}
            try:
                key._ts_op_seq_refs = refs
            except Exception:
                try:
                    refs = _OP_SEQ_REFS.setdefault(key, {})
                except TypeError:
                    logger.warning(
                        "Store %r accepts neither attributes nor weak "
                        "references; store-key sequences degrade to "
                        "per-wrapper and may alias across wrappers",
                        type(key).__name__,
                    )
                    return [0]
        return refs.setdefault(rank, [0])
