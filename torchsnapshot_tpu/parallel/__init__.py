from .overlap import Box  # noqa: F401
from .pipeline import (  # noqa: F401
    pipeline_stage_shardings,
    pipelined_apply,
    stack_stage_params,
)
