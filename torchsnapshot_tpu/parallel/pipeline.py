"""Pipeline parallelism: a GPipe schedule over a ``pp`` mesh axis.

Reference parity: none — the reference (torchsnapshot) recognizes PP state
only as generic per-rank entries (SURVEY.md §2.12: "TP / PP / EP as such:
only insofar as their state is ShardedTensor or per-rank"). This module
exists because the checkpointer claims to cover any layout a parallel
workload produces, and pipeline stages are the one layout a GSPMD-sharded
flagship model alone never exercises.

TPU-first design — a pipeline is a *schedule*, not a sharding, so it is
expressed as an explicit per-device program:

- Stage parameters are ONE stacked pytree: every leaf gains a leading
  ``n_stages`` dim sharded ``P('pp', ...)`` (``stack_stage_params``).
  For the checkpointer this is just another NamedSharding array — the
  sharded preparer persists each stage's slice from the device that owns
  it, and elastic restore across different pp degrees falls out of the
  existing overlap-based resharding.
- ``pipelined_apply`` runs the schedule under ``jax.shard_map``: at tick
  ``t`` device ``r`` computes microbatch ``t - r``; activations hop to the
  next stage with ``lax.ppermute`` inside a ``lax.scan`` (static trip
  count ``n_micro + n_stages - 1`` — the classic GPipe trapezoid with
  ``n_stages - 1`` bubble ticks).
- The whole schedule is differentiable: reverse-mode through the scan
  IS the backward pipeline (activations of all ticks are saved — GPipe
  memory semantics; swap in ``jax.checkpoint`` on the stage fn to trade
  recompute for memory).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

StageFn = Callable[[Any, jax.Array], jax.Array]


def stack_stage_params(per_stage: list, mesh: Optional[Mesh] = None) -> Any:
    """Stack per-stage parameter pytrees into one pytree whose leaves have
    a leading ``n_stages`` dim, sharded over ``pp`` when a mesh is given.

    The stacked form is what trains, pipelines, and checkpoints: one
    ``jax.Array`` per leaf, stage ``i``'s slice resident on the devices of
    mesh row ``pp=i``.
    """
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage
    )
    if mesh is None:
        return stacked
    return jax.tree_util.tree_map(
        jax.device_put, stacked, pipeline_stage_shardings(stacked, mesh)
    )


def pipelined_apply(
    stage_fn: StageFn,
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_microbatches: int,
    axis_name: str = "pp",
) -> jax.Array:
    """Run ``x`` through ``n_stages`` copies of ``stage_fn`` as a GPipe
    pipeline over the mesh's ``axis_name`` axis.

    Args:
        stage_fn: ``(params_for_one_stage, activation) -> activation`` with
            activation shape preserved (embed before / readout after the
            pipeline — the hopping tensor must have one static shape).
        stage_params: stacked pytree from :func:`stack_stage_params`
            (leaves ``(n_stages, ...)`` sharded over ``axis_name``).
        x: ``(batch, ...)`` activations entering stage 0; ``batch`` must
            divide by ``n_microbatches``.

    Returns:
        ``(batch, ...)`` output of the last stage, replicated over the
        ``pp`` axis.
    """
    n_stages = mesh.shape[axis_name]
    leaves = jax.tree_util.tree_leaves(stage_params)
    if leaves and leaves[0].shape[0] != n_stages:
        # per_device keeps only its slice's first stage — a mismatched
        # stacking would silently drop stages, not error.
        raise ValueError(
            f"stage_params are stacked for {leaves[0].shape[0]} stages but "
            f"mesh axis {axis_name!r} has {n_stages} devices"
        )
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} must divide by n_microbatches={n_microbatches}"
        )
    mb = batch // n_microbatches
    xs = x.reshape(n_microbatches, mb, *x.shape[1:])
    n_ticks = n_microbatches + n_stages - 1

    def per_device(params: Any, xs_local: jax.Array) -> jax.Array:
        # (1, ...) stage slice → this device's stage params.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        r = lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            act, out_buf = carry
            # Stage 0 ingests microbatch t (while any remain); deeper
            # stages consume the activation that just hopped in.
            inp = jnp.where(
                r == 0,
                xs_local[jnp.clip(t, 0, n_microbatches - 1)],
                act,
            )
            y = stage_fn(params, inp)
            # The last stage finishes microbatch t - (n_stages - 1).
            done = t - (n_stages - 1)
            write = jnp.logical_and(
                r == n_stages - 1,
                jnp.logical_and(done >= 0, done < n_microbatches),
            )
            slot = jnp.clip(done, 0, n_microbatches - 1)
            updated = lax.dynamic_update_slice(
                out_buf,
                y[None].astype(out_buf.dtype),
                (slot,) + (0,) * y.ndim,
            )
            out_buf = jnp.where(write, updated, out_buf)
            act = lax.ppermute(y, axis_name, perm)
            return (act, out_buf), None

        zero_act = jnp.zeros_like(xs_local[0])
        out0 = jnp.zeros_like(xs_local)
        (_, out_buf), _ = lax.scan(
            tick, (zero_act, out0), jnp.arange(n_ticks)
        )
        # Only the last stage holds real outputs; psum replicates them
        # (every other stage contributes zeros).
        out_buf = lax.psum(
            jnp.where(r == n_stages - 1, out_buf, jnp.zeros_like(out_buf)),
            axis_name,
        )
        return out_buf

    spec_params = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stage_params
    )
    from ..utils import shard_map_compat

    out = shard_map_compat(
        per_device,
        mesh=mesh,
        in_specs=(spec_params, P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, xs)
    return out.reshape(batch, *x.shape[1:])


def pipeline_stage_shardings(
    stage_params: Any, mesh: Mesh, axis_name: str = "pp"
) -> Any:
    """NamedSharding pytree for stacked stage params (checkpoint restore
    destinations)."""
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(
            mesh, P(axis_name, *([None] * (leaf.ndim - 1)))
        ),
        stage_params,
    )
