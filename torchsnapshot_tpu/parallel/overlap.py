"""N-dimensional shard-overlap math for elastic resharding.

Reference parity: ``_shards_get_overlap_region_wrt_saved_tensor``
(io_preparer.py:200-247) — but generalized. The reference only handles
enumerable 1-d chunk specs; GSPMD shardings produce arbitrary N-d
hyper-rectangles (mesh axes over any dims, replicated × sharded mixes,
uneven remainders), so overlap here is a per-dimension interval
intersection over N-d boxes.

A *box* is ``(offsets, sizes)`` — the hyper-rectangle
``[offsets[d], offsets[d] + sizes[d])`` per dimension.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Box:
    offsets: Tuple[int, ...]
    sizes: Tuple[int, ...]

    @classmethod
    def from_index(
        cls, index: Sequence[slice], shape: Sequence[int]
    ) -> "Box":
        """Build a box from a jax ``devices_indices_map`` index (a tuple of
        slices with possibly-None bounds)."""
        offsets = []
        sizes = []
        for slc, dim in zip(index, shape):
            start = 0 if slc.start is None else int(slc.start)
            stop = int(dim) if slc.stop is None else int(slc.stop)
            offsets.append(start)
            sizes.append(stop - start)
        # 0-d arrays / fully-replicated indices shorter than rank:
        for dim in shape[len(index) :]:
            offsets.append(0)
            sizes.append(int(dim))
        return cls(tuple(offsets), tuple(sizes))

    @property
    def ndim(self) -> int:
        return len(self.offsets)

    def numel(self) -> int:
        n = 1
        for s in self.sizes:
            n *= s
        return n

    def to_index(self) -> Tuple[slice, ...]:
        return tuple(
            slice(o, o + s) for o, s in zip(self.offsets, self.sizes)
        )


@dataclass(frozen=True)
class Overlap:
    """The intersection of a saved box and a destination box, expressed in
    each one's local coordinates."""

    src_slices: Tuple[slice, ...]  # into the saved shard's local array
    dst_slices: Tuple[slice, ...]  # into the destination box's local array


def box_overlap(saved: Box, dst: Box) -> Optional[Overlap]:
    """Per-dimension interval intersection; None when disjoint."""
    if saved.ndim != dst.ndim:
        raise ValueError(
            f"Rank mismatch: saved box has {saved.ndim} dims, destination "
            f"has {dst.ndim}"
        )
    src_slices: List[slice] = []
    dst_slices: List[slice] = []
    for d in range(saved.ndim):
        lo = max(saved.offsets[d], dst.offsets[d])
        hi = min(
            saved.offsets[d] + saved.sizes[d], dst.offsets[d] + dst.sizes[d]
        )
        if hi <= lo:
            return None
        src_slices.append(slice(lo - saved.offsets[d], hi - saved.offsets[d]))
        dst_slices.append(slice(lo - dst.offsets[d], hi - dst.offsets[d]))
    return Overlap(tuple(src_slices), tuple(dst_slices))


def subdivide_box(box: Box, max_bytes: int, itemsize: int) -> List[Box]:
    """Split a box along dim 0 into pieces of at most ``max_bytes``
    (reference subdivide_shard, io_preparer.py:168-198; rows larger than the
    budget stay whole)."""
    if box.numel() * itemsize <= max_bytes or box.ndim == 0 or box.sizes[0] <= 1:
        return [box]
    row_elems = box.numel() // box.sizes[0]
    rows_per_piece = max(1, max_bytes // max(1, row_elems * itemsize))
    pieces = []
    for start in range(0, box.sizes[0], rows_per_piece):
        rows = min(rows_per_piece, box.sizes[0] - start)
        pieces.append(
            Box(
                offsets=(box.offsets[0] + start,) + box.offsets[1:],
                sizes=(rows,) + box.sizes[1:],
            )
        )
    return pieces
