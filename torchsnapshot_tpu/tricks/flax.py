"""Flax integration: checkpoint ``TrainState`` (and any flax module state)
with zero boilerplate.

Reference parity: the ``tricks/`` integration layer — the reference ships
a DeepSpeed engine bridge (tricks/deepspeed.py:19-104) that adapts an
external training framework's state objects to its Stateful protocol.
Flax is the framework of record on TPU; its ``TrainState`` is an
immutable pytree dataclass, so the adapter holds the current state and
swaps in the restored one (same pattern as
:class:`~torchsnapshot_tpu.state_dict.PyTreeState`, specialized to keep
the non-array fields — ``apply_fn``, ``tx`` — out of the checkpoint).
"""

from __future__ import annotations

from typing import Any, Dict

from ..state_dict import pytree_to_state_dict, state_dict_to_pytree


class TrainStateStateful:
    """Adapt a ``flax.training.train_state.TrainState`` (or any
    ``.replace()``-able dataclass pytree with ``params``/``opt_state``/
    ``step`` fields) to the Stateful protocol.

    Usage::

        tss = TrainStateStateful(train_state)
        Snapshot.take(path, {"train": tss})
        ...
        Snapshot(path).restore({"train": tss})
        train_state = tss.state   # restored TrainState, same apply_fn/tx
    """

    _FIELDS = ("params", "opt_state", "step")

    def __init__(self, state: Any) -> None:
        for f in self._FIELDS:
            if not hasattr(state, f):
                raise TypeError(
                    f"{type(state).__name__} has no {f!r} field; "
                    f"TrainStateStateful expects a flax-style train state"
                )
        if not hasattr(state, "replace"):
            raise TypeError(
                f"{type(state).__name__} has no .replace(); "
                f"TrainStateStateful expects a dataclass pytree"
            )
        self.state = state

    def state_dict(self) -> Dict[str, Any]:
        return {
            f: pytree_to_state_dict(getattr(self.state, f))
            for f in self._FIELDS
        }

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        restored = {
            f: state_dict_to_pytree(state_dict[f], getattr(self.state, f))
            for f in self._FIELDS
        }
        self.state = self.state.replace(**restored)
