"""Framework integrations (reference parity: torchsnapshot/tricks/).

- :mod:`.flax` — ``TrainStateStateful`` for flax train states.
- :mod:`.orbax` — checkpoint migration to/from orbax format.
- :mod:`.torch` — ``TorchStateful`` bridge for torch modules/optimizers
  (the migration path for users of the reference).

Submodules are imported lazily by users (``from torchsnapshot_tpu.tricks
import flax``) so optional dependencies stay optional.
"""
