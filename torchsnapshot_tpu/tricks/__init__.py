"""Framework integrations (reference parity: torchsnapshot/tricks/).

- :mod:`.flax` — ``TrainStateStateful`` for flax train states.
- :mod:`.orbax` — checkpoint migration to/from orbax format.

Submodules are imported lazily by users (``from torchsnapshot_tpu.tricks
import flax``) so optional dependencies stay optional.
"""
