"""Read snapshots written by the reference TorchSnapshot library.

The other half of the migration story: ``tricks.torch`` lets a torch
training loop adopt this checkpointer going forward, but migrating users
also carry *existing* checkpoints written by the reference
(``torchsnapshot==0.0.3``). This module reads that on-disk format
directly — ``.snapshot_metadata`` YAML manifest plus blob files — and
returns numpy arrays / Python values ready for ``jax.device_put``, so a
reference user can resume from their old checkpoints without keeping a
torch training stack around to re-save them.

Format coverage (the reference's documented schema — entry taxonomy
reference ``manifest.py:27-290``, path grammar ``snapshot.py:897-900``,
percent-escaping ``flatten.py:204-211``):

- ``Tensor`` entries, both serializers: ``buffer_protocol`` (raw
  little-endian bytes; decoded with numpy alone, bf16 via ml_dtypes) and
  ``torch_save`` (decoded with torch — imported lazily, only if such an
  entry is actually read).
- ``ShardedTensor`` / ``ChunkedTensor``: shards/chunks are assembled
  into one full dense array (offsets/sizes boxes; global shape from the
  entry for chunked, from the shard envelope for sharded).
- ``object`` entries (``torch_save`` pickles): returned as loaded; torch
  tensors inside are converted to numpy.
- Inline primitives (int/str/bool/bytes/float — float from its
  base64-packed exact form, reference ``manifest.py:263-265``).
- Containers (dict/OrderedDict/list) are inflated back into nested
  structures, including int-key recovery and percent-decoding.
- ``byte_range`` blob windows (batched slabs, reference
  ``batcher.py:173``) via ranged storage reads.
- Rank availability rules (reference ``manifest.py:333-371``): per-rank
  entries for the requested rank, replicated entries from any rank,
  ShardedTensor shards merged across all ranks.

Reads ride this package's storage plugins, so ``fs://``-style local
paths and ``s3://`` / ``gs://`` snapshots all work.

Not supported (never produced by the reference either — its quantized
tensors serialize via ``torch_save``): the ``per_tensor_qtensor`` /
``per_channel_qtensor`` serializers; reading one raises with that
explanation.

Usage::

    from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
        ReferenceSnapshotReader,
    )

    reader = ReferenceSnapshotReader("/path/to/old/snapshot")
    state = reader.read_state(rank=0)      # {"model": {...}, "optim": ...}
    arr = reader.read_object("0/model/lin.weight")   # one leaf
    params = jax.tree.map(jax.device_put, state["model"])
"""

from __future__ import annotations

import base64
import io
import math
import struct
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..flatten import _decode, _looks_like_int
from ..io_types import ReadIO
from ..manifest import _Loader, yaml
from ..storage_plugin import url_to_storage_plugin

_METADATA_FNAME = ".snapshot_metadata"

# The reference persists dtypes as "torch.<name>" strings (its
# serialization.py dtype table). Mapped to numpy equivalents; bf16 via
# ml_dtypes (imported lazily — only bf16 snapshots need it).
_TORCH_DTYPE_STRINGS: Dict[str, str] = {
    "torch.float64": "float64",
    "torch.float32": "float32",
    "torch.float16": "float16",
    "torch.complex128": "complex128",
    "torch.complex64": "complex64",
    "torch.int64": "int64",
    "torch.int32": "int32",
    "torch.int16": "int16",
    "torch.int8": "int8",
    "torch.uint8": "uint8",
    "torch.bool": "bool",
}

_PRIMITIVE_TYPES = ("int", "str", "bool", "bytes", "float")
_CONTAINER_TYPES = ("list", "dict", "OrderedDict")


def _np_dtype(torch_dtype_str: str) -> np.dtype:
    if torch_dtype_str == "torch.bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(_TORCH_DTYPE_STRINGS[torch_dtype_str])
    except KeyError:
        raise ValueError(
            f"unsupported reference dtype string {torch_dtype_str!r} "
            f"(quantized dtypes have no dense numpy equivalent)"
        ) from None


def _primitive_value(entry: Dict[str, Any]) -> Any:
    """Decode an inline primitive entry (reference manifest.py:195-290)."""
    kind = entry["type"]
    raw = entry["serialized_value"]
    if kind == "int":
        return int(raw)
    if kind == "str":
        return raw
    if kind == "bool":
        if raw not in ("True", "False"):
            raise ValueError(f"malformed bool primitive: {raw!r}")
        return raw == "True"
    if kind == "bytes":
        return base64.b64decode(raw.encode("utf-8"))
    if kind == "float":
        # Exact round-trip: the reference packs the double and base64s it.
        return struct.unpack("d", base64.b64decode(raw.encode("utf-8")))[0]
    raise ValueError(f"not a primitive entry type: {kind!r}")


def _entry_boxes(entry: Dict[str, Any]):
    """Normalize a tensor-bearing entry into
    ``([(offsets, sizes, tensor_entry)], global_shape, np_dtype)``.

    For ShardedTensor the global shape is the shard envelope (the entry
    records no global shape); ChunkedTensor and Tensor declare theirs.
    One definition shared by the dense ``_assemble`` path and
    ``read_sharded``, so envelope/dtype inference cannot diverge."""
    kind = entry.get("type")
    if kind == "Tensor":
        shape = tuple(int(d) for d in entry["shape"])
        return (
            [(tuple(0 for _ in shape), shape, entry)],
            shape,
            _np_dtype(entry["dtype"]),
        )
    if kind in ("ShardedTensor", "ChunkedTensor"):
        raw = entry["shards"] if kind == "ShardedTensor" else entry["chunks"]
        if not raw:
            raise ValueError("entry has no shards/chunks")
        boxes = [
            (
                tuple(int(o) for o in b["offsets"]),
                tuple(int(s) for s in b["sizes"]),
                b["tensor"],
            )
            for b in raw
        ]
        if kind == "ChunkedTensor":
            shape = tuple(int(d) for d in entry["shape"])
            dtype = _np_dtype(entry["dtype"])
        else:
            ndim = len(boxes[0][0])
            shape = tuple(
                max(o[d] + s[d] for o, s, _ in boxes) for d in range(ndim)
            )
            dtype = _np_dtype(boxes[0][2]["dtype"])
        return boxes, shape, dtype
    raise ValueError(f"entry type {kind!r} is not a tensor entry")


class ReferenceSnapshotReader:
    """Random and bulk access to a reference-format snapshot.

    ``path`` accepts the same URL grammar as the rest of this package
    (bare paths are local filesystem; ``s3://`` / ``gs://`` supported).

    The storage plugin and its event loop are created lazily on first
    read and reused for the reader's lifetime (one S3/GCS session for a
    whole ``read_state``, not one per blob); ``close()`` releases them,
    and the reader works as a context manager.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._metadata: Optional[Dict[str, Any]] = None
        self._loop: Optional[Any] = None
        self._storage: Optional[Any] = None

    def close(self) -> None:
        if self._loop is not None:
            loop, storage = self._loop, self._storage
            self._loop = self._storage = None
            try:
                if storage is not None:
                    loop.run_until_complete(storage.close())
            finally:
                loop.close()

    def __enter__(self) -> "ReferenceSnapshotReader":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    def __del__(self) -> None:  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter-shutdown noise
            pass

    # -- metadata ------------------------------------------------------

    @property
    def metadata(self) -> Dict[str, Any]:
        """The parsed ``.snapshot_metadata`` document:
        ``{"version": str, "world_size": int, "manifest": {path: entry}}``.
        Entries are kept as plain dicts (the YAML form is the format
        contract — reference manifest.py:32-35)."""
        if self._metadata is None:
            raw = self._read_blob(_METADATA_FNAME, None)
            doc = yaml.load(bytes(raw).decode("utf-8"), Loader=_Loader)
            if not isinstance(doc, dict) or "manifest" not in doc:
                raise ValueError(
                    f"{self.path}/{_METADATA_FNAME} is not a TorchSnapshot "
                    f"metadata document"
                )
            self._metadata = doc
        return self._metadata

    @property
    def world_size(self) -> int:
        return int(self.metadata.get("world_size", 1))

    def manifest_for_rank(self, rank: int) -> Dict[str, Any]:
        """Logical-path → entry view for ``rank`` under the reference's
        availability rules (manifest.py:333-371): the rank's own entries,
        replicated entries from every rank, and ShardedTensor entries
        merged across ranks (shards sorted by offsets)."""
        own: Dict[str, Any] = {}
        others: List[Tuple[int, str, Dict[str, Any]]] = []
        for path, entry in self.metadata["manifest"].items():
            rnk_str, _, logical = path.partition("/")
            rnk = int(rnk_str)
            if rnk == rank:
                own[logical] = dict(entry)
            else:
                others.append((rnk, logical, entry))
        for _, logical, entry in others:
            if entry.get("type") == "ShardedTensor":
                if logical in own and own[logical].get("type") == "ShardedTensor":
                    merged = own[logical]["shards"] + entry["shards"]
                    own[logical] = {
                        "type": "ShardedTensor",
                        "shards": sorted(merged, key=lambda s: s["offsets"]),
                    }
                elif logical not in own:
                    own[logical] = dict(entry)
            elif entry.get("replicated") and logical not in own:
                own[logical] = dict(entry)
        # Container chains for adopted entries: a replicated/sharded leaf
        # from another rank needs its ancestor containers present for
        # inflation; adopt them (keys pruned to adopted children at
        # population time, so stale keys are harmless).
        by_rank: Dict[int, Dict[str, Any]] = {}
        for rnk, logical, entry in others:
            by_rank.setdefault(rnk, {})[logical] = entry
        for logical in list(own):
            parts = logical.split("/")
            for i in range(1, len(parts)):
                parent = "/".join(parts[:i])
                if parent in own:
                    continue
                for manifest in by_rank.values():
                    p = manifest.get(parent)
                    if p is not None and p.get("type") in _CONTAINER_TYPES:
                        own[parent] = dict(p)
                        break
        return own

    # -- reads ---------------------------------------------------------

    def read_object(self, path: str, rank: Optional[int] = None) -> Any:
        """Read one manifest path. ``path`` is the reference's
        ``read_object`` grammar: ``"RANK/logical/path"`` (rank prefix
        optional when ``rank`` is given)."""
        if rank is None:
            rank_str, _, logical = path.partition("/")
            rank = int(rank_str)
        else:
            logical = path
        manifest = self.manifest_for_rank(rank)
        if logical not in manifest:
            raise KeyError(
                f"{logical!r} not in the rank-{rank} manifest "
                f"(available: {sorted(manifest)[:10]}...)"
            )
        return self._materialize(manifest[logical])

    def read_state(self, rank: int = 0) -> Dict[str, Any]:
        """Read the full app state visible to ``rank`` as one nested
        structure: ``{app_state_key: nested value}`` — the shape the
        reference's ``restore`` would hand each stateful's
        ``load_state_dict``."""
        manifest = self.manifest_for_rank(rank)
        leaves = {
            p: self._materialize(e)
            for p, e in manifest.items()
            if e.get("type") not in _CONTAINER_TYPES
        }
        return self._inflate(manifest, leaves)

    def read_sharded(
        self,
        path: str,
        sharding: Any,
        rank: Optional[int] = None,
        global_shape: Optional[Tuple[int, ...]] = None,
    ) -> Any:
        """Place one tensor entry directly into a sharded ``jax.Array``.

        The TPU-native migration path for large sharded state (old FSDP /
        model-parallel checkpoints): each addressable device's shard box
        is assembled from only the persisted shards overlapping it (the
        same N-d box algebra the native resharding restore uses,
        ``parallel/overlap.py``), so the full array is never materialized
        on the host — peak host memory is one device shard plus the
        overlapping source pieces. Accepts ``Tensor``, ``ShardedTensor``
        and ``ChunkedTensor`` entries; any ``jax.sharding.Sharding`` for
        an N-d layout works, including layouts different from the one the
        checkpoint was saved under (resharding-on-read).

        ``global_shape``: pass the expected full shape when known. A
        ``ShardedTensor`` entry records no global shape — it is inferred
        as the shard envelope — so a snapshot missing its TAIL shards
        would silently infer a smaller array; an explicit shape turns
        that into a loud shard-coverage error.
        """
        import jax

        from ..resharding import Box, box_overlap

        if rank is None:
            rank_str, _, logical = path.partition("/")
            rank = int(rank_str)
        else:
            logical = path
        manifest = self.manifest_for_rank(rank)
        if logical not in manifest:
            raise KeyError(f"{logical!r} not in the rank-{rank} manifest")
        raw_boxes, shape, dtype = _entry_boxes(manifest[logical])
        # Dedup identical persisted boxes (a DP-replicated checkpoint can
        # record the same shard box from several ranks).
        seen = set()
        boxes = []
        for offsets, sizes, tentry in raw_boxes:
            if (offsets, sizes) not in seen:
                seen.add((offsets, sizes))
                boxes.append((Box(offsets, sizes), tentry))
        if global_shape is not None:
            global_shape = tuple(int(d) for d in global_shape)
            if len(global_shape) != len(shape) or any(
                g < s for g, s in zip(global_shape, shape)
            ):
                raise ValueError(
                    f"{logical!r}: global_shape {global_shape} is "
                    f"incompatible with the persisted extent {shape}"
                )
            shape = global_shape

        # Group devices by destination box (Box is a frozen, hashable
        # dataclass): replicated / partially-replicated layouts assemble
        # each distinct box once and place the same host array on every
        # device sharing it.
        groups: Dict[Any, List[Any]] = {}
        for device, index in sharding.addressable_devices_indices_map(
            shape
        ).items():
            groups.setdefault(Box.from_index(index, shape), []).append(device)

        def _row_range(i: int, ov) -> Optional[Tuple[int, int]]:
            """When the overlap is a row slab of source box ``i`` — full
            extent in every trailing dim, raw little-endian layout —
            return the (start, end) BYTE window of those rows within the
            source blob, composing with any byte_range the entry already
            has (batched slabs). The common FSDP dim-0 resharding case
            then moves only the overlapping rows from storage instead of
            whole source shards. The window math itself is the shared
            slab geometry (``resharding.row_slab_byte_window``) the
            native restore ranges with — one definition, so slab
            detection cannot diverge between the bridge and the core
            path; only the reference-dict plumbing (serializer tag,
            torch dtype strings) lives here."""
            from ..resharding import row_slab_byte_window

            sbox, tentry = boxes[i]
            if tentry.get("serializer") != "buffer_protocol":
                return None
            row_bytes = _np_dtype(tentry["dtype"]).itemsize
            for d in range(1, sbox.ndim):
                row_bytes *= sbox.sizes[d]
            base = tentry.get("byte_range")
            base = int(base[0]) if base else 0
            return row_slab_byte_window(sbox.sizes, ov, row_bytes, base)

        # Plan overlaps up front. Row-slab overlaps become ranged reads
        # (no full source piece is ever loaded for them); the rest load
        # their source piece once, with eviction when no remaining group
        # needs it — peak host memory stays at one assembled box + its
        # live sources (NOT the whole array).
        plans = {}
        uses = dict.fromkeys(range(len(boxes)), 0)
        for dst_box in groups:
            plan = []
            for i, (sbox, _) in enumerate(boxes):
                ov = box_overlap(sbox, dst_box)
                if ov is not None:
                    rng = _row_range(i, ov)
                    plan.append((i, ov, rng))
                    if rng is None:
                        uses[i] += 1
            plans[dst_box] = plan

        pieces: Dict[int, Any] = {}  # box index -> loaded source ndarray

        def _piece(i: int):
            if i not in pieces:
                box, tentry = boxes[i]
                pieces[i] = self._read_tensor(tentry).reshape(box.sizes)
            return pieces[i]

        host_arrays = []
        put_devices = []
        for dst_box, devices in groups.items():
            local = np.zeros(dst_box.sizes, dtype=dtype)
            covered = np.zeros(dst_box.sizes, dtype=bool)
            # All of this box's ranged windows fetch concurrently.
            ranged = [
                (i, ov, rng)
                for i, ov, rng in plans[dst_box]
                if rng is not None
            ]
            datas = (
                self._read_blobs(
                    [(boxes[i][1]["location"], rng) for i, _, rng in ranged]
                )
                if ranged
                else []
            )
            for (i, ov, rng), data in zip(ranged, datas):
                sbox, tentry = boxes[i]
                if len(data) != rng[1] - rng[0]:
                    raise ValueError(
                        f"blob {tentry['location']!r} returned {len(data)} "
                        f"bytes for window [{rng[0]}, {rng[1]}) — blob is "
                        f"shorter than the manifest claims"
                    )
                r = ov.src_slices[0]
                sub = np.frombuffer(
                    data, dtype=_np_dtype(tentry["dtype"])
                ).reshape((r.stop - r.start,) + tuple(sbox.sizes[1:]))
                local[ov.dst_slices] = sub
                covered[ov.dst_slices] = True
            for i, ov, rng in plans[dst_box]:
                if rng is not None:
                    continue
                local[ov.dst_slices] = _piece(i)[ov.src_slices]
                covered[ov.dst_slices] = True
                uses[i] -= 1
                if uses[i] == 0:
                    pieces.pop(i, None)
            if not covered.all():
                raise ValueError(
                    f"{logical!r}: persisted shards cover only "
                    f"{int(covered.sum())} of {dst_box.numel()} elements of "
                    f"a destination shard — the snapshot's shard set has "
                    f"holes"
                )
            del covered
            for device in devices:
                host_arrays.append(local)
                put_devices.append(device)
        # One batched transfer: a per-device device_put loop pays the
        # dispatch latency N times over (the native restore's batching
        # rationale, sharded_io_preparer.py).
        shards = jax.device_put(host_arrays, put_devices)
        return jax.make_array_from_single_device_arrays(
            shape, sharding, shards
        )

    # -- internals -----------------------------------------------------

    def _read_blob(
        self, location: str, byte_range: Optional[Tuple[int, int]]
    ) -> memoryview:
        return self._read_blobs([(location, byte_range)])[0]

    def _read_blobs(
        self, requests: List[Tuple[str, Optional[Tuple[int, int]]]]
    ) -> List[memoryview]:
        """Issue several reads CONCURRENTLY in the reader's event loop —
        one gather, not len(requests) sequential round trips (each small
        ranged GET against s3/gs pays full request latency)."""
        import asyncio

        if self._loop is None:
            self._loop = asyncio.new_event_loop()
            self._storage = url_to_storage_plugin(self.path)

        async def _go() -> List[memoryview]:
            ios = [
                ReadIO(path=loc, byte_range=br) for loc, br in requests
            ]
            await asyncio.gather(*(self._storage.read(io) for io in ios))
            for io in ios:
                # Explicit (not an assert): the check must survive
                # ``python -O``, or a plugin that completed read() without
                # filling buf surfaces later as an opaque TypeError.
                if io.buf is None:
                    raise RuntimeError(
                        f"storage plugin "
                        f"{type(self._storage).__name__} completed read() "
                        f"without populating the buffer for {io.path!r}"
                    )
            return [io.buf for io in ios]

        return self._loop.run_until_complete(_go())

    def _materialize(self, entry: Dict[str, Any]) -> Any:
        kind = entry.get("type")
        if kind in _PRIMITIVE_TYPES:
            return _primitive_value(entry)
        if kind == "Tensor":
            return self._read_tensor(entry)
        if kind in ("ShardedTensor", "ChunkedTensor"):
            return self._assemble(entry)
        if kind == "object":
            return self._read_torch_object(entry)
        raise ValueError(f"cannot materialize entry type {kind!r}")

    def _read_tensor(self, entry: Dict[str, Any]) -> np.ndarray:
        byte_range = entry.get("byte_range")
        if byte_range is not None:
            byte_range = (int(byte_range[0]), int(byte_range[1]))
        data = self._read_blob(entry["location"], byte_range)
        serializer = entry["serializer"]
        shape = tuple(entry["shape"])
        if serializer == "buffer_protocol":
            dtype = _np_dtype(entry["dtype"])
            need = dtype.itemsize * math.prod(int(d) for d in shape)
            if len(data) != need:
                hint = ""
                if len(data) == 0 and shape == () and entry["dtype"] == (
                    "torch.bfloat16"
                ):
                    # Reference bug, verified against it directly: its 0-d
                    # bf16 zero-copy path (serialization.py:216-233) writes
                    # an EMPTY blob, and its own restore fails on it too —
                    # the value was destroyed at save time.
                    hint = (
                        " (known reference bug: 0-d bfloat16 tensors are "
                        "saved as empty blobs and are unrecoverable — the "
                        "reference's own restore fails on them as well)"
                    )
                raise ValueError(
                    f"blob {entry['location']!r} holds {len(data)} bytes "
                    f"but entry dtype={entry['dtype']} shape={list(shape)} "
                    f"needs {need}{hint}"
                )
            # Zero-copy over the read buffer (read-only is fine: consumers
            # copy on device_put / window assignment).
            arr = np.frombuffer(data, dtype=dtype)
            return arr.reshape(shape)
        if serializer == "torch_save":
            t = self._torch_load(data)
            return _torch_to_numpy(t).reshape(shape)
        raise NotImplementedError(
            f"serializer {serializer!r} is not supported: the reference "
            f"defines the qtensor codecs but never emits them (its "
            f"quantized tensors serialize via torch_save — reference "
            f"serialization.py:148-159)"
        )

    def _assemble(self, entry: Dict[str, Any]) -> np.ndarray:
        """Assemble a sharded/chunked entry's boxes into one dense
        array (full host materialization — ``read_sharded`` is the
        bounded-memory alternative). Interior holes in the shard set
        raise (matching ``read_sharded``'s covered-mask check) instead
        of silently zero-filling — a hole means the snapshot lost
        shards, and zeros here would convert into corrupt-but-valid
        native snapshots downstream (tricks/convert.py reads through
        this path)."""
        boxes, shape, dtype = _entry_boxes(entry)
        out = np.zeros(shape, dtype=dtype)
        covered = np.zeros(shape, dtype=bool)
        for offsets, sizes, tentry in boxes:
            piece = self._read_tensor(tentry).reshape(sizes)
            window = tuple(
                slice(o, o + s) for o, s in zip(offsets, sizes)
            )
            out[window] = piece
            covered[window] = True
        if not covered.all():
            raise ValueError(
                f"persisted shards cover only {int(covered.sum())} of "
                f"{out.size} elements of a "
                f"{entry.get('type', 'sharded')} entry — the snapshot's "
                f"shard set has holes"
            )
        return out

    def _read_torch_object(self, entry: Dict[str, Any]) -> Any:
        data = self._read_blob(entry["location"], None)
        obj = self._torch_load(data)
        return _torch_to_numpy(obj)

    def _torch_load(self, data: memoryview) -> Any:
        try:
            import torch
        except ImportError:
            raise RuntimeError(
                "this snapshot entry was serialized with torch_save; "
                "install torch (CPU is enough) to read it"
            ) from None
        # weights_only=False: torch>=2.6 flipped the default, which
        # rejects numpy payloads and user classes — the very things the
        # reference pickles into object entries. This reads the user's
        # OWN checkpoint (same trust model as the reference-era
        # torch.load), so full unpickling is the correct behavior here.
        return torch.load(
            io.BytesIO(bytes(data)), map_location="cpu", weights_only=False
        )

    def _inflate(
        self, manifest: Dict[str, Any], leaves: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Rebuild the nested structure from container entries + leaf
        values (the reference's inflate semantics: list order by int
        index, dict keys percent-decoded with int recovery)."""
        missing = object()  # placeholder: distinguishes "not loaded" from None
        containers: Dict[str, Any] = {}
        for path, entry in manifest.items():
            kind = entry.get("type")
            if kind == "list":
                containers[path] = []
            elif kind in ("dict", "OrderedDict"):
                # Pre-seed with the entry's recorded keys: preserves the
                # original item order and native int keys (reference
                # flatten.py:157-162). Keys with nothing available for
                # this rank are pruned after population.
                cls = OrderedDict if kind == "OrderedDict" else dict
                containers[path] = cls(
                    (k, missing) for k in entry.get("keys", [])
                )
        root: Dict[str, Any] = {}

        def _place(path: str, value: Any) -> None:
            parent, _, key = path.rpartition("/")
            key = _decode(key)
            if not parent:
                root[key] = value
                return
            container = containers.get(parent)
            if container is None:
                # Parent container entry missing (partial manifests):
                # surface the leaf under its full path instead of dropping.
                root[path] = value
                return
            if isinstance(container, list):
                container.append((int(key), value))
            else:
                if key not in container and _looks_like_int(key):
                    key = int(key)
                container[key] = value

        # Two passes — containers first so leaf placement always finds
        # its parent; deepest-first placement of containers into their
        # own parents, then leaves in any order.
        for path in sorted(containers, key=lambda p: -p.count("/")):
            _place(path, containers[path])
        for path, value in leaves.items():
            _place(path, value)

        # Settle only the containers THIS inflater created (tracked by
        # identity): our lists hold (index, value) pairs to order, our
        # dicts hold placeholder keys to prune. A list or dict arriving
        # as a leaf VALUE (e.g. inside a pickled object entry) is user
        # data and must pass through untouched.
        container_ids = {id(c) for c in containers.values()}

        def _settle(obj: Any) -> Any:
            if id(obj) not in container_ids:
                return obj
            if isinstance(obj, list):
                return [_settle(v) for _, v in sorted(obj, key=lambda e: e[0])]
            for k in [k for k, v in obj.items() if v is missing]:
                del obj[k]
            for k, v in obj.items():
                obj[k] = _settle(v)
            return obj

        return {k: _settle(v) for k, v in root.items()}


def _torch_to_numpy(obj: Any) -> Any:
    """Torch tensors (anywhere in a container) → numpy; everything else
    passes through."""
    try:
        import torch
    except ImportError:  # no torch → nothing to convert
        return obj
    if isinstance(obj, torch.Tensor):
        t = obj.detach().cpu()
        if t.dtype == torch.bfloat16:
            import ml_dtypes

            return (
                t.contiguous()
                .view(torch.uint16)
                .numpy()
                .view(ml_dtypes.bfloat16)
            )
        if t.is_quantized:
            t = t.dequantize()
        if not t.is_contiguous():
            t = t.contiguous()
        return t.numpy()
    if isinstance(obj, dict):
        return type(obj)((k, _torch_to_numpy(v)) for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return type(obj)(_torch_to_numpy(v) for v in obj)
    return obj


def read_reference_snapshot(path: str, rank: int = 0) -> Dict[str, Any]:
    """One-call convenience: the full nested state visible to ``rank``."""
    return ReferenceSnapshotReader(path).read_state(rank=rank)
