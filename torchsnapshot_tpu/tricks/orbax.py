"""Orbax interop: handler-level interception plus format migration.

Reference parity: the reference's tricks layer *intercepts* an external
checkpoint system's save path (tricks/deepspeed.py:19-104 —
``_save_zero_checkpoint``/``_load_zero_checkpoint`` are rerouted to
torchsnapshot so the engine's existing call sites write the new format
transparently). On TPU the incumbent is orbax, and the equivalent
interception point is the ``CheckpointHandler``:
:func:`snapshot_checkpoint_handler` returns a handler that plugs into
``ocp.Checkpointer`` / ``ocp.CheckpointManager``, so EXISTING orbax call
sites — ``checkpointer.save(path, args=...)``, manager ``.save(step,
args=...)`` retention loops, all of it — produce this framework's
snapshot format without the trainer changing a line beyond handler
construction.

The migration helpers below convert existing checkpoint *directories*
between the two formats (one pytree at a time, through host memory).

Orbax is import-gated: the package works without it, these functions
don't.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


def _run_outside_event_loop(fn):
    """Run ``fn`` off any running asyncio loop. Orbax's
    ``CheckpointManager``/``AsyncCheckpointer`` invoke handler
    save/restore from inside ``asyncio.run``; the snapshot pipeline
    drives its own event loop with ``run_until_complete``, which
    asyncio forbids while another loop runs on the thread. A fresh
    thread has no running loop, so the pipeline keeps its
    single-ownership loop semantics and the caller's loop is never
    touched."""
    import asyncio

    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return fn()  # no loop on this thread: the common sync path

    import threading

    result: list = []
    error: list = []

    def target() -> None:
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 - re-raised below
            error.append(e)

    thread = threading.Thread(
        target=target, name="ts-orbax-handler", daemon=True
    )
    thread.start()
    thread.join()
    if error:
        raise error[0]
    return result[0]


def _import_orbax():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise RuntimeError(
            "orbax interop requires orbax-checkpoint (pip install "
            "orbax-checkpoint)"
        ) from e
    return ocp


class _RawState:
    """Stateful that accepts whatever structure the snapshot holds —
    the template-free restore path (orbax ``restore(path)`` semantics:
    nested dicts/lists of arrays come back without an ``item``)."""

    def __init__(self) -> None:
        self.value: Any = None

    def state_dict(self):
        return {}

    def load_state_dict(self, state_dict) -> None:
        self.value = state_dict


_handler_cache: Optional[Tuple[Any, Any, Any]] = None


def _build_handler_classes() -> Tuple[Any, Any, Any]:
    global _handler_cache
    if _handler_cache is not None:
        return _handler_cache
    import dataclasses

    ocp = _import_orbax()
    from ..snapshot import Snapshot
    from ..state_dict import PyTreeState

    class SnapshotCheckpointHandler(ocp.CheckpointHandler):
        """Writes/reads this framework's snapshot format behind orbax's
        handler protocol. ``directory`` is whatever orbax hands over
        (including its atomic temporary dir — orbax still performs its own
        finalize/rename, layering its atomicity on top of the snapshot
        commit marker).

        Usage::

            handler = snapshot_checkpoint_handler()
            with ocp.Checkpointer(handler) as ckptr:
                ckptr.save(path, args=SnapshotSave(tree))       # new format
                tree = ckptr.restore(path)                       # raw
                tree = ckptr.restore(path, args=SnapshotRestore(template))
        """

        def __init__(self, key: str = "state", pg: Optional[Any] = None):
            self._key = key
            self._pg = pg

        def save(self, directory, *args, **kwargs) -> None:
            ckpt_args = kwargs.get("args") or (args[0] if args else None)
            item = getattr(ckpt_args, "item", ckpt_args)
            _run_outside_event_loop(
                lambda: Snapshot.take(
                    str(directory),
                    {self._key: PyTreeState(item)},
                    pg=self._pg,
                )
            )

        def restore(self, directory, *args, **kwargs) -> Any:
            ckpt_args = kwargs.get("args") or (args[0] if args else None)
            template = getattr(ckpt_args, "item", ckpt_args)
            snap = Snapshot(str(directory), pg=self._pg)
            if template is None:
                raw = _RawState()
                _run_outside_event_loop(
                    lambda: snap.restore({self._key: raw})
                )
                if raw.value is None:
                    # Nothing under this key: a key mismatch or a non-
                    # snapshot directory must fail AT the checkpoint
                    # boundary, not as a None-tree crash in the trainer.
                    raise ValueError(
                        f"snapshot at {directory} has no app-state key "
                        f"{self._key!r}; was it saved with a different "
                        f"handler key?"
                    )
                return raw.value
            stateful = PyTreeState(template)
            _run_outside_event_loop(
                lambda: snap.restore({self._key: stateful})
            )
            return stateful.tree

        def metadata(self, directory) -> Optional[Any]:
            return None

        def finalize(self, directory) -> None:
            pass

        def close(self) -> None:
            pass

    @ocp.args.register_with_handler(SnapshotCheckpointHandler, for_save=True)
    @dataclasses.dataclass
    class SnapshotSave(ocp.args.CheckpointArgs):
        item: Any

    @ocp.args.register_with_handler(
        SnapshotCheckpointHandler, for_restore=True
    )
    @dataclasses.dataclass
    class SnapshotRestore(ocp.args.CheckpointArgs):
        item: Any = None

    _handler_cache = (SnapshotCheckpointHandler, SnapshotSave, SnapshotRestore)
    return _handler_cache


def snapshot_checkpoint_handler(key: str = "state", pg: Optional[Any] = None):
    """An orbax ``CheckpointHandler`` that writes THIS framework's format.

    Drop it into an existing orbax setup and every save/restore at that
    call site transparently becomes a snapshot (the deepspeed-trick
    interception pattern, reference tricks/deepspeed.py:19-104)::

        import orbax.checkpoint as ocp
        from torchsnapshot_tpu.tricks.orbax import snapshot_checkpoint_handler

        ckptr = ocp.Checkpointer(snapshot_checkpoint_handler())
        ckptr.save(path, args=snapshot_save_args(tree))
        tree = ckptr.restore(path)
    """
    cls, _, _ = _build_handler_classes()
    return cls(key=key, pg=pg)


def snapshot_save_args(item: Any):
    """``ocp.args`` save wrapper for :func:`snapshot_checkpoint_handler`."""
    _, save_cls, _ = _build_handler_classes()
    return save_cls(item)


def snapshot_restore_args(item: Optional[Any] = None):
    """``ocp.args`` restore wrapper (``item`` = optional template)."""
    _, _, restore_cls = _build_handler_classes()
    return restore_cls(item)


def load_orbax_pytree(orbax_path: str, item: Optional[Any] = None) -> Any:
    """Restore an orbax checkpoint as a host pytree.

    ``item`` (optional) is a template pytree of the expected structure;
    without it orbax restores raw (dicts + arrays).
    """
    ocp = _import_orbax()
    with ocp.PyTreeCheckpointer() as ckptr:
        if item is None:
            return ckptr.restore(orbax_path)
        return ckptr.restore(orbax_path, item=item)


def migrate_orbax_to_snapshot(
    orbax_path: str,
    snapshot_path: str,
    item: Optional[Any] = None,
    key: str = "state",
) -> None:
    """Read an orbax checkpoint and write it as a Snapshot at
    ``snapshot_path`` under app-state key ``key``."""
    from ..snapshot import Snapshot
    from ..state_dict import PyTreeState

    tree = load_orbax_pytree(orbax_path, item=item)
    Snapshot.take(snapshot_path, {key: PyTreeState(tree)})


def migrate_snapshot_to_orbax(
    snapshot_path: str,
    orbax_path: str,
    item: Any,
    key: str = "state",
) -> Any:
    """Restore app-state ``key`` from a Snapshot into ``item``'s structure
    and save it as an orbax checkpoint. Returns the restored pytree."""
    ocp = _import_orbax()
    from ..snapshot import Snapshot
    from ..state_dict import PyTreeState

    stateful = PyTreeState(item)
    Snapshot(snapshot_path).restore({key: stateful})
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(orbax_path, stateful.tree)
    return stateful.tree
