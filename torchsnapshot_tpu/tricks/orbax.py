"""Orbax interop: migrate checkpoints between orbax and Snapshot formats.

Reference parity: the reference's tricks layer bridges an external
checkpoint system into its own take/restore path (tricks/deepspeed.py —
``_save_zero_checkpoint``/``_load_zero_checkpoint`` are rerouted to
torchsnapshot). On TPU the incumbent checkpointer is orbax; teams
switching to this framework have orbax checkpoint dirs to carry over, and
tooling they still run may expect orbax layout. These helpers convert in
both directions through host memory (one pytree at a time).

Orbax is import-gated: the package works without it, these two functions
don't.
"""

from __future__ import annotations

from typing import Any, Optional


def _import_orbax():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise RuntimeError(
            "orbax interop requires orbax-checkpoint (pip install "
            "orbax-checkpoint)"
        ) from e
    return ocp


def load_orbax_pytree(orbax_path: str, item: Optional[Any] = None) -> Any:
    """Restore an orbax checkpoint as a host pytree.

    ``item`` (optional) is a template pytree of the expected structure;
    without it orbax restores raw (dicts + arrays).
    """
    ocp = _import_orbax()
    with ocp.PyTreeCheckpointer() as ckptr:
        if item is None:
            return ckptr.restore(orbax_path)
        return ckptr.restore(orbax_path, item=item)


def migrate_orbax_to_snapshot(
    orbax_path: str,
    snapshot_path: str,
    item: Optional[Any] = None,
    key: str = "state",
) -> None:
    """Read an orbax checkpoint and write it as a Snapshot at
    ``snapshot_path`` under app-state key ``key``."""
    from ..snapshot import Snapshot
    from ..state_dict import PyTreeState

    tree = load_orbax_pytree(orbax_path, item=item)
    Snapshot.take(snapshot_path, {key: PyTreeState(tree)})


def migrate_snapshot_to_orbax(
    snapshot_path: str,
    orbax_path: str,
    item: Any,
    key: str = "state",
) -> Any:
    """Restore app-state ``key`` from a Snapshot into ``item``'s structure
    and save it as an orbax checkpoint. Returns the restored pytree."""
    ocp = _import_orbax()
    from ..snapshot import Snapshot
    from ..state_dict import PyTreeState

    stateful = PyTreeState(item)
    Snapshot(snapshot_path).restore({key: stateful})
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(orbax_path, stateful.tree)
    return stateful.tree
