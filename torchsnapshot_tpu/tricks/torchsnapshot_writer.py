"""Write reference-format (TorchSnapshot 0.0.3) snapshots from JAX state.

The reverse of :mod:`.torchsnapshot_reader`, completing bidirectional
migration: a team moving to this framework keeps an escape hatch back to
their torch tooling — evaluation scripts, checkpoint inspectors, or a
rollback of the migration itself — because anything this writer emits is
restorable by the *actual* reference library
(``torchsnapshot.Snapshot(path).restore(...)``), which the interop test
exercises.

This is a migration utility, not a second checkpointer: single-process,
world_size 1, synchronous, no batching/partitioning — the native
``Snapshot.take`` remains the production path. Format per the reference
schema (entry taxonomy ``manifest.py:27-290``, flatten/percent-escaping
``flatten.py:204-211``, dtype strings ``serialization.py:56-79``):

- numpy / ``jax.Array`` leaves → ``Tensor`` entries. Dtypes in the
  reference's buffer-protocol set (f64/f32/f16/bf16/i64/i32/i16/i8/u8/
  bool) are written as raw little-endian bytes readable with no torch at
  all; complex64/128 — which the reference only round-trips via
  ``torch_save`` — are written with that serializer (torch required).
  Dtypes the reference cannot represent at all (fp8, uint16/32/64) are
  rejected with a clear error rather than silently widened.
- int/str/bool/bytes/float leaves → inline primitive entries (float in
  the reference's exact base64-packed form).
- anything else → ``object`` entries via ``torch.save`` (torch required;
  the reference's object path is torch_save pickles).

Usage::

    from torchsnapshot_tpu.tricks.torchsnapshot_writer import (
        write_reference_snapshot,
    )

    write_reference_snapshot(
        "/ckpts/export_for_torch",
        {"model": {"w": params["w"], "bias": params["bias"]},
         "progress": {"step": 100}},
    )
    # torch side:  torchsnapshot.Snapshot(path).restore(app_state)
"""

from __future__ import annotations

import base64
import io
import struct
from typing import Any, Dict

import numpy as np

from ..event_loop import run_in_fresh_event_loop
from ..flatten import DictEntry, ListEntry, OrderedDictEntry, flatten
from ..io_types import WriteIO
from ..manifest import yaml, _Dumper
from ..storage_plugin import url_to_storage_plugin

_METADATA_FNAME = ".snapshot_metadata"

# numpy dtype name → reference dtype string, buffer-protocol subset
# (reference serialization.py:146-159: complex is NOT buffer-protocol
# there; it round-trips via torch_save).
_BUFFER_PROTOCOL_DTYPES: Dict[str, str] = {
    "float64": "torch.float64",
    "float32": "torch.float32",
    "float16": "torch.float16",
    "bfloat16": "torch.bfloat16",
    "int64": "torch.int64",
    "int32": "torch.int32",
    "int16": "torch.int16",
    "int8": "torch.int8",
    "uint8": "torch.uint8",
    "bool": "torch.bool",
}
_TORCH_SAVE_DTYPES: Dict[str, str] = {
    "complex128": "torch.complex128",
    "complex64": "torch.complex64",
}


def write_reference_snapshot(path: str, app_state: Dict[str, Any]) -> None:
    """Write ``app_state`` (``{key: nested pytree-like value}``) as a
    world_size-1 reference-format snapshot at ``path`` (fs/s3/gs URL)."""
    manifest: Dict[str, Any] = {}
    pending = []  # (logical_path, leaf) — serialized one at a time below

    for key, value in app_state.items():
        containers, leaves = flatten(value, prefix=key)
        for cpath, centry in containers.items():
            manifest[f"0/{cpath}"] = _container_to_reference(centry)
        pending.extend(leaves.items())

    async def _go() -> None:
        storage = url_to_storage_plugin(path)
        try:
            # Serialize each leaf inside the loop and drop its bytes
            # after the write: peak memory is one leaf, not the whole
            # checkpoint (this is the multi-GB rollback-export path).
            for lpath, leaf in pending:
                entry, blob = _prepare_leaf(lpath, leaf)
                manifest[f"0/{lpath}"] = entry
                if blob is not None:
                    await storage.write(
                        WriteIO(path=path_location(lpath), buf=blob)
                    )
            doc = {"version": "0.0.3", "world_size": 1, "manifest": manifest}
            metadata = yaml.dump(doc, sort_keys=False, Dumper=_Dumper)
            # Metadata last: its presence is the reference's commit marker.
            await storage.write(
                WriteIO(path=_METADATA_FNAME, buf=metadata.encode("utf-8"))
            )
        finally:
            await storage.close()

    run_in_fresh_event_loop(_go())


def _container_to_reference(entry: Any) -> Dict[str, Any]:
    if isinstance(entry, ListEntry):
        return {"type": "list"}
    if isinstance(entry, OrderedDictEntry):
        return {"type": "OrderedDict", "keys": list(entry.keys)}
    if isinstance(entry, DictEntry):
        return {"type": "dict", "keys": list(entry.keys)}
    raise TypeError(f"unexpected container entry {entry!r}")


def _prepare_leaf(path: str, leaf: Any) -> tuple:
    """Returns ``(manifest_entry, blob_bytes_or_None)``."""
    if isinstance(leaf, bool):  # before int: bool is an int subclass
        return _primitive("bool", str(leaf)), None
    if isinstance(leaf, int):
        return _primitive("int", str(leaf)), None
    if isinstance(leaf, float):
        packed = base64.b64encode(struct.pack("d", leaf)).decode("utf-8")
        return _primitive("float", packed, readable=str(leaf)), None
    if isinstance(leaf, str):
        return _primitive("str", leaf), None
    if isinstance(leaf, bytes):
        return (
            _primitive("bytes", base64.b64encode(leaf).decode("utf-8")),
            None,
        )

    arr = _as_numpy(leaf)
    if arr is not None:
        return _tensor_entry(path, arr)

    # Generic object → torch_save pickle (the reference's object path).
    torch = _require_torch(f"object leaf at {path!r}")
    buf = io.BytesIO()
    torch.save(leaf, buf)
    entry = {
        "type": "object",
        "location": path_location(path),
        "serializer": "torch_save",
        "obj_type": type(leaf).__name__,
        "replicated": False,
    }
    return entry, buf.getvalue()


def path_location(path: str) -> str:
    return f"0/{path}"


def _as_numpy(leaf: Any):
    """numpy/jax arrays (and 0-d numpy scalars) → contiguous ndarray;
    None for non-array leaves."""
    if isinstance(leaf, np.ndarray):
        return np.ascontiguousarray(leaf)
    if isinstance(leaf, np.generic):
        return np.ascontiguousarray(np.asarray(leaf))
    # jax.Array without importing jax eagerly: anything exposing
    # __array__ plus .dtype/.shape quacks close enough.
    if hasattr(leaf, "__array__") and hasattr(leaf, "dtype") and hasattr(
        leaf, "shape"
    ):
        return np.ascontiguousarray(np.asarray(leaf))
    return None


def _tensor_entry(path: str, arr: np.ndarray) -> tuple:
    # The reference format is raw LITTLE-endian bytes (and torch cannot
    # ingest big-endian numpy arrays at all): normalize non-native byte
    # order before serializing, or a '>f4' array — whose dtype.name is
    # still plain 'float32' — round-trips byte-swapped.
    import sys

    if arr.dtype.byteorder == ">" or (
        arr.dtype.byteorder == "=" and sys.byteorder == "big"
    ):
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    name = arr.dtype.name
    if name in _BUFFER_PROTOCOL_DTYPES:
        entry = {
            "type": "Tensor",
            "location": path_location(path),
            "serializer": "buffer_protocol",
            "dtype": _BUFFER_PROTOCOL_DTYPES[name],
            "shape": list(arr.shape),
            "replicated": False,
            "byte_range": None,
        }
        return entry, arr.tobytes()
    if name in _TORCH_SAVE_DTYPES:
        torch = _require_torch(f"complex leaf at {path!r}")
        buf = io.BytesIO()
        torch.save(torch.from_numpy(np.ascontiguousarray(arr)), buf)
        entry = {
            "type": "Tensor",
            "location": path_location(path),
            "serializer": "torch_save",
            "dtype": _TORCH_SAVE_DTYPES[name],
            "shape": list(arr.shape),
            "replicated": False,
            "byte_range": None,
        }
        return entry, buf.getvalue()
    raise ValueError(
        f"dtype {name!r} (leaf {path!r}) has no representation in the "
        f"reference's format (its dtype table is fixed — reference "
        f"serialization.py:32-103); cast to a supported dtype first "
        f"(e.g. fp8 -> bfloat16, uint32 -> int64)"
    )


def _primitive(
    kind: str, serialized: str, readable: str = None
) -> Dict[str, Any]:
    return {
        "type": kind,
        "serialized_value": serialized,
        "replicated": False,
        "readable": readable,
    }


def _require_torch(what: str):
    try:
        import torch

        return torch
    except ImportError:
        raise RuntimeError(
            f"writing {what} requires torch (the reference format "
            f"serializes it via torch_save)"
        ) from None
