"""PyTorch migration bridge: checkpoint torch state dicts with this
framework.

Reference parity: the reference *is* a torch library; its users hold
``nn.Module``/optimizer state dicts (reference snapshot.py:175-243 takes
them directly). This bridge lets those users keep their torch training
loop and switch the checkpointing layer: tensors are exposed to the
snapshot pipeline as numpy views (zero-copy for CPU tensors) and restored
in place with ``Tensor.copy_``, so restore stays ~1x memory like the
reference's ``_load_stateful`` (snapshot.py:682-692).

Usage::

    from torchsnapshot_tpu.tricks.torch import TorchStateful

    app_state = {"model": TorchStateful(model), "optim": TorchStateful(optim)}
    Snapshot.take(path, app_state)
    ...
    Snapshot(path).restore(app_state)   # tensors restored in place

Snapshots written this way are also readable from a pure-JAX process (the
manifest records plain dense arrays), which is the actual migration path:
save from the torch trainer, restore into the jax one.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def _torch():
    import torch

    return torch


def _to_numpy(value: Any) -> Any:
    """Torch tensors → numpy (zero-copy for dense CPU tensors); containers
    recursed; everything else passes through (the generic object path
    handles it)."""
    torch = _torch()
    if isinstance(value, torch.Tensor):
        t = value.detach()
        if t.device.type != "cpu":
            t = t.cpu()
        if t.dtype == torch.bfloat16:
            # numpy has no bf16: reinterpret the storage as uint16 and let
            # the snapshot dtype table carry "bfloat16" via ml_dtypes.
            import ml_dtypes

            return t.contiguous().view(torch.uint16).numpy().view(
                ml_dtypes.bfloat16
            )
        if not t.is_contiguous():
            t = t.contiguous()
        return t.numpy()
    if isinstance(value, dict):
        return {k: _to_numpy(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        out = [_to_numpy(v) for v in value]
        return out if isinstance(value, list) else tuple(out)
    return value


def _load_into(dst: Any, src: Any, mutate: bool = True) -> Any:
    """Merge restored values back into the original structure. With
    ``mutate`` the tensors are ``copy_``-ed in place (plain-dict statefuls,
    where nothing else will apply the values); without it fresh tensors are
    returned and the single copy is left to ``load_state_dict``."""
    torch = _torch()
    if isinstance(dst, torch.Tensor):
        src_np = np.asarray(src)
        if src_np.dtype.name == "bfloat16":
            t = torch.from_numpy(src_np.view(np.uint16).copy()).view(
                torch.bfloat16
            )
        else:
            t = torch.from_numpy(np.ascontiguousarray(src_np))
        t = t.to(dst.dtype).reshape(dst.shape)
        if not mutate:
            return t
        with torch.no_grad():
            dst.copy_(t)
        return dst
    if isinstance(dst, dict) and isinstance(src, dict):
        # Destination-only keys are preserved: a snapshot taken before a
        # field existed must not silently erase the field on restore.
        merged_dict = {
            k: _load_into(dst[k], src[k], mutate) if k in dst else src[k]
            for k in src
        }
        if mutate:
            # A caller holding the original dict must see restored
            # non-tensor leaves (step counters, lr floats) too — update
            # the destination in place instead of returning a new dict.
            dst.update(merged_dict)
            return dst
        for k in dst:
            if k not in src:
                merged_dict[k] = dst[k]
        return merged_dict
    if isinstance(dst, (list, tuple)) and isinstance(src, (list, tuple)):
        merged = [_load_into(d, s, mutate) for d, s in zip(dst, src)]
        merged += list(src[len(dst):]) if len(src) > len(dst) else list(
            dst[len(src):]
        )
        if mutate and isinstance(dst, list):
            dst[: len(merged)] = merged
            return dst
        return merged if isinstance(dst, list) else tuple(merged)
    return src


class TorchStateful:
    """Adapt anything with ``state_dict()/load_state_dict()`` (module,
    optimizer, lr scheduler) — or a plain state dict — to this framework's
    Stateful protocol, converting tensors ⇄ numpy at the boundary."""

    def __init__(self, obj: Any) -> None:
        self.obj = obj
        self._has_protocol = hasattr(obj, "state_dict") and hasattr(
            obj, "load_state_dict"
        )

    def _current(self) -> Dict[str, Any]:
        return self.obj.state_dict() if self._has_protocol else self.obj

    def state_dict(self) -> Dict[str, Any]:
        return _to_numpy(self._current())

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        if self._has_protocol:
            # load_state_dict performs the one copy into live tensors;
            # _load_into only shapes/dtypes the restored values.
            self.obj.load_state_dict(
                _load_into(self._current(), state_dict, mutate=False)
            )
        else:
            self.obj = _load_into(self._current(), state_dict, mutate=True)
