"""Convert a reference-format (TorchSnapshot 0.0.3) snapshot to the
native format, as a one-shot CLI::

    python -m torchsnapshot_tpu.tricks.convert OLD_SNAPSHOT NEW_SNAPSHOT \
        [--rank N] [--verify]

Reads the old checkpoint with :mod:`.torchsnapshot_reader` (the rank-N
view: replicated entries, merged shards) and re-saves it with the native
``Snapshot.take`` — after which the full native feature set applies to
it (incremental chaining, integrity digests, fsck, manager retention).
``--verify`` walks the source manifest first and fails fast on missing
or truncated blobs, so a half-copied checkpoint is caught before the
converted snapshot exists (the native commit-marker discipline: the
destination appears only on success).

Array leaves convert losslessly (bf16 included); non-array leaves
(primitives, pickled objects) ride the native object path.
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List

from ..snapshot import Snapshot
from ..state_dict import PyTreeState
from .torchsnapshot_reader import ReferenceSnapshotReader, _np_dtype


def verify_source(reader: ReferenceSnapshotReader, rank: int) -> List[str]:
    """Shallow integrity walk of the source (the native fsck's
    existence/length pass, applied to the reference format): every blob
    a leaf entry points at must exist and cover the entry's byte need.
    Probes with a one-byte ranged read of the last required byte — no
    blob is materialized, the same never-OOM discipline as
    ``fsck._shallow_check`` — so verifying a multi-GB checkpoint moves
    ~one byte per blob. Returns problem descriptions (empty = clean)."""
    problems: List[str] = []
    # (location, need) → verdict, so shared slabs probe once per need.
    checked: Dict[tuple, str] = {}

    def _probe(location: str, need: int) -> str:
        key = (location, need)
        if key not in checked:
            try:
                # Reading [need-1, need) succeeds iff the blob exists and
                # holds at least ``need`` bytes (the FS plugin fails short
                # ranged reads; need 0 degenerates to an existence check).
                reader._read_blob(location, (max(need - 1, 0), max(need, 0)))
                checked[key] = ""
            except FileNotFoundError:
                checked[key] = f"missing blob {location}"
            except OSError:
                # Truncation contract shared by every plugin: fs/memory
                # raise EIO natively, and the s3/gs plugins normalize
                # out-of-range ranged reads (botocore InvalidRange /
                # google InvalidResponse 416) to OSError(EIO) the same
                # way they normalize 404 to FileNotFoundError.
                checked[key] = (
                    f"blob {location} is shorter than the {need} bytes "
                    f"its entry needs"
                )
            except Exception as e:  # noqa: BLE001 - verification must
                # report, not crash: an unnormalized backend error (auth,
                # throttling that exhausted retries) still belongs in the
                # problem list the caller was promised.
                checked[key] = f"blob {location} unreadable: {e!r}"
        return checked[key]

    for logical, entry in reader.manifest_for_rank(rank).items():
        kind = entry.get("type")
        tensors = []
        if kind in ("Tensor", "object"):
            tensors = [entry]
        elif kind == "ShardedTensor":
            tensors = [s["tensor"] for s in entry["shards"]]
        elif kind == "ChunkedTensor":
            tensors = [c["tensor"] for c in entry["chunks"]]
        for t in tensors:
            br = t.get("byte_range")
            if br:
                need = int(br[1])
            elif t.get("serializer") == "buffer_protocol":
                # Raw little-endian layout: exact size is dtype x shape.
                need = _np_dtype(t["dtype"]).itemsize
                for dim in t.get("shape", []):
                    need *= int(dim)
            else:
                need = 1  # torch_save streams: exact size unknowable here
            verdict = _probe(t["location"], need)
            if verdict:
                problems.append(f"{logical}: {verdict}")
    return problems


def dropped_rank_entries(
    reader: ReferenceSnapshotReader, rank: int
) -> Dict[int, List[str]]:
    """Other ranks' PER-RANK entries that a rank-``rank`` conversion
    cannot carry: non-replicated, non-sharded leaves owned by another
    rank (availability rules make replicated + sharded state complete
    from any rank; per-rank state is genuinely private)."""
    dropped: Dict[int, List[str]] = {}
    for path, entry in reader.metadata["manifest"].items():
        rnk_str, _, logical = path.partition("/")
        rnk = int(rnk_str)
        kind = entry.get("type")
        if (
            rnk != rank
            and kind not in ("list", "dict", "OrderedDict", "ShardedTensor")
            and not entry.get("replicated")
        ):
            dropped.setdefault(rnk, []).append(logical)
    return dropped


def convert(
    src: str, dst: str, rank: int = 0, verify: bool = False
) -> None:
    reader = ReferenceSnapshotReader(src)
    try:
        dropped = dropped_rank_entries(reader, rank)
        if dropped:
            detail = "; ".join(
                f"rank {r}: {len(paths)} entries (e.g. {paths[0]!r})"
                for r, paths in sorted(dropped.items())
            )
            print(
                f"convert: WARNING — per-rank state of other ranks is NOT "
                f"carried by a --rank {rank} conversion: {detail}. Convert "
                f"each rank separately before retiring the source.",
                file=sys.stderr,
            )
        if verify:
            problems = verify_source(reader, rank)
            if problems:
                raise RuntimeError(
                    "source snapshot failed verification:\n  "
                    + "\n  ".join(problems)
                )
        state = reader.read_state(rank=rank)
    finally:
        reader.close()
    app_state = {key: PyTreeState(value) for key, value in state.items()}
    # record_digests: the converted snapshot must be a valid
    # incremental_base for the user's next take (the docstring's
    # "incremental chaining" promise).
    Snapshot.take(dst, app_state, record_digests=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Convert a TorchSnapshot-format snapshot to the "
        "native format."
    )
    parser.add_argument("src", help="reference-format snapshot (fs/s3/gs)")
    parser.add_argument("dst", help="destination for the native snapshot")
    parser.add_argument(
        "--rank",
        type=int,
        default=0,
        help="which rank's view to convert (default 0; replicated and "
        "sharded state is complete from any rank)",
    )
    parser.add_argument(
        "--verify",
        action="store_true",
        help="walk the source manifest first; fail on missing/truncated "
        "blobs before writing anything",
    )
    args = parser.parse_args(argv)
    try:
        convert(args.src, args.dst, rank=args.rank, verify=args.verify)
    except Exception as e:  # noqa: BLE001 - CLI boundary
        print(f"convert: {e}", file=sys.stderr)
        return 1
    print(f"converted {args.src} (rank {args.rank}) -> {args.dst}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
