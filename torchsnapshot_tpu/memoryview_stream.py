"""File-like, zero-copy reader over a memoryview.

Reference parity: torchsnapshot/memoryview_stream.py:12-81 — uploads hand
storage clients a file-like object so multi-GB staged buffers are
streamed instead of copied into a ``bytes`` (S3 put_object bodies,
storage_plugins/s3.py). Read-only, seekable; ``read`` returns memoryview
slices (clients treat them as bytes-like) so no byte is duplicated.
"""

from __future__ import annotations

import io
from typing import Optional


class MemoryviewStream(io.RawIOBase):
    def __init__(self, mv: memoryview) -> None:
        super().__init__()
        self._mv = mv if mv.format == "B" and mv.ndim == 1 else mv.cast("B")
        self._pos = 0

    # ------------------------------------------------------------------
    # io.RawIOBase interface
    # ------------------------------------------------------------------

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seek(self, pos: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            new_pos = pos
        elif whence == io.SEEK_CUR:
            new_pos = self._pos + pos
        elif whence == io.SEEK_END:
            new_pos = len(self._mv) + pos
        else:
            raise ValueError(f"invalid whence: {whence}")
        if new_pos < 0:
            raise ValueError(f"negative seek position {new_pos}")
        self._pos = new_pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, size: Optional[int] = -1) -> memoryview:
        if self.closed:
            raise ValueError("I/O operation on closed stream")
        start = min(self._pos, len(self._mv))
        if size is None or size < 0:
            end = len(self._mv)
        else:
            end = min(start + size, len(self._mv))
        out = self._mv[start:end]
        if end > start:
            self._pos = end
        return out

    def readinto(self, b) -> int:
        chunk = self.read(len(b))
        n = len(chunk)
        b[:n] = chunk
        return n

    def readall(self) -> bytes:  # pragma: no cover - RawIOBase fallback
        return bytes(self.read(-1))

    def __len__(self) -> int:
        return len(self._mv)
