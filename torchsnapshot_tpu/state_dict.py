"""Stateful adapters for plain values and pure pytrees.

Reference parity: torchsnapshot/state_dict.py:13-41 (``StateDict``).
TPU-native addition: :class:`PyTreeState`, which adapts an *immutable* JAX
pytree (flax params, optax optimizer state, namedtuple trees, ...) into the
``Stateful`` protocol. The reference has no equivalent because torch state is
mutable in place; JAX state is replaced, not mutated, so the adapter holds the
current tree and swaps it on ``load_state_dict``.
"""

from __future__ import annotations

from collections import UserDict
from typing import Any, Dict, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")


class StateDict(UserDict):
    """Dict wrapper that makes plain values participate in checkpointing.

    ``state_dict()`` returns the underlying data; ``load_state_dict``
    replaces it wholesale.
    """

    def state_dict(self) -> Dict[str, Any]:
        return self.data

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self.data = dict(state_dict)


def _node_children(tree: Any) -> Optional[List[Tuple[str, Any]]]:
    """Return ``[(str_key, child)]`` for a pytree node's immediate subtrees,
    or ``None`` if ``tree`` is a leaf.

    Uses a one-level flatten (``is_leaf`` fires for everything except the
    node itself), so namedtuples, flax FrozenDicts, and custom registered
    nodes all decompose without special cases.
    """
    import jax

    keyed = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: x is not tree
    )[0]
    if len(keyed) == 1 and keyed[0][0] == ():
        return None
    return [(_path_key_to_str(path[0]), child) for path, child in keyed]


def _path_key_to_str(key: Any) -> str:
    import jax

    tu = jax.tree_util
    if isinstance(key, tu.DictKey):
        return str(key.key)
    if isinstance(key, tu.SequenceKey):
        return str(key.idx)
    if isinstance(key, tu.GetAttrKey):
        return key.name
    if isinstance(key, tu.FlattenedIndexKey):
        return str(key.key)
    return str(key)


def pytree_to_state_dict(tree: Any) -> Any:
    """Convert an arbitrary pytree to nested dict/list/leaf structure.

    Dicts stay dicts and lists stay lists (so the result round-trips through
    ``flatten()`` naturally); every other pytree node (tuples, namedtuples,
    custom nodes) becomes a dict keyed by stringified field/index. Leaves
    pass through unchanged.
    """
    if isinstance(tree, dict):
        return {k: pytree_to_state_dict(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [pytree_to_state_dict(v) for v in tree]
    children = _node_children(tree)
    if children is None:
        return tree
    return {key: pytree_to_state_dict(child) for key, child in children}


def state_dict_to_pytree(state_dict: Any, target: Any) -> Any:
    """Rebuild a pytree with ``target``'s structure from a nested state dict.

    Inverse of :func:`pytree_to_state_dict`: ``target`` supplies the treedef
    (container/namedtuple types), ``state_dict`` supplies the leaf values.
    """
    import jax

    # Plain dicts/lists are handled natively (mirrors pytree_to_state_dict):
    # this preserves int and mixed-type dict keys, which jax's sorted
    # keypath flatten cannot represent.
    if isinstance(target, dict):
        if not isinstance(state_dict, dict):
            raise TypeError(
                f"Expected a dict to restore a dict node, got "
                f"{type(state_dict).__name__}"
            )
        return {
            k: state_dict_to_pytree(_lookup(state_dict, k), v)
            for k, v in target.items()
        }
    if isinstance(target, list):
        if isinstance(state_dict, dict):
            if len(state_dict) != len(target):
                raise ValueError(
                    f"Cannot restore a list of length {len(target)} from a "
                    f"dict-shaped state dict with {len(state_dict)} elements"
                )
            seq = [state_dict[str(i)] for i in range(len(target))]
        else:
            seq = list(state_dict)
        if len(seq) != len(target):
            raise ValueError(
                f"Cannot restore a list of length {len(target)} from a state "
                f"dict with {len(seq)} elements"
            )
        return [state_dict_to_pytree(s, v) for s, v in zip(seq, target)]

    children = _node_children(target)
    if children is None:
        return state_dict  # leaf position: take the restored value
    rebuilt = []
    for key, child in children:
        if isinstance(state_dict, dict):
            sub = _lookup(state_dict, key)
        elif isinstance(state_dict, (list, tuple)):
            sub = state_dict[int(key)]
        else:
            raise TypeError(
                f"Cannot index a {type(state_dict).__name__} with key {key!r} "
                f"while rebuilding a pytree node of type {type(target).__name__}"
            )
        rebuilt.append(state_dict_to_pytree(sub, child))
    node_def = jax.tree_util.tree_structure(target, is_leaf=lambda x: x is not target)
    return jax.tree_util.tree_unflatten(node_def, rebuilt)


def _lookup(state_dict: Dict[Any, Any], key: Any) -> Any:
    """Fetch ``key`` tolerating the str<->int aliasing that stringified
    pytree paths introduce."""
    if key in state_dict:
        return state_dict[key]
    alias: Any = None
    if isinstance(key, str):
        body = key[1:] if key[:1] in "+-" else key
        if body.isdigit():
            alias = int(key)
    elif isinstance(key, int):
        alias = str(key)
    if alias is not None and alias in state_dict:
        return state_dict[alias]
    raise KeyError(
        f"state dict is missing key {key!r} (available: {list(state_dict.keys())})"
    )


class PyTreeState(Generic[T]):
    """Adapt an immutable pytree into the ``Stateful`` protocol.

    Usage::

        app_state = {"params": PyTreeState(params), "opt": PyTreeState(opt_state)}
        Snapshot.take(path, app_state)
        ...
        snapshot.restore(app_state)
        params = app_state["params"].tree   # restored values, same treedef

    ``load_state_dict`` rebuilds restored leaves into the existing tree's
    structure, so namedtuple/custom-node trees (e.g. optax states) round-trip
    with their original types intact.
    """

    def __init__(self, tree: T) -> None:
        self.tree: T = tree

    def _is_facade(self) -> bool:
        """True when the tree serializes to a non-dict and needs the
        ``__leaf__`` facade. Decided from the live tree's top-level structure
        (O(1), no full conversion), so a user dict that happens to contain a
        ``__leaf__`` key is unambiguous."""
        if isinstance(self.tree, dict):
            return False
        if isinstance(self.tree, list):
            return True
        return _node_children(self.tree) is None

    def state_dict(self) -> Dict[str, Any]:
        sd = pytree_to_state_dict(self.tree)
        if not isinstance(sd, dict):
            # Single-leaf/list trees still need a dict facade for the protocol.
            return {"__leaf__": sd}
        return sd

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        if self._is_facade():
            self.tree = state_dict_to_pytree(state_dict["__leaf__"], self.tree)
            return
        self.tree = state_dict_to_pytree(state_dict, self.tree)
