"""Small-write coalescing into slab blobs.

Reference parity: torchsnapshot/batcher.py (482 LoC). Buffer-protocol write
requests under the slab threshold (knob, 128 MiB default) are packed into
``batched/{uuid}`` slabs; every affected ``ArrayEntry`` — standalone or
nested inside Chunked/Sharded entries — has its ``location``/``byte_range``
rewritten to point into the slab (reference batcher.py:202-352). On the read
side, multiple ranged reads of one location merge into a single spanning
read whose consumer hands each member its sub-slice (reference
batcher.py:355-474).

TPU-native simplifications vs the reference:

- Slab member sizes are computed exactly at *planning* time from
  dtype × shape arithmetic (buffer-protocol arrays have no serialization
  framing), so byte ranges are assigned before any staging happens — no
  placeholder rewriting pass.
- The device-slab path (reference GPUBatchedBufferStager,
  batcher.py:102-160) is a fused XLA program (ops/device_pack.py): slab
  members resident on device are bitcast+concatenated on device and leave
  via ONE D2H transfer. It is knob-gated off by default
  (``TORCHSNAPSHOT_TPU_DEVICE_PACK``): per-member ``copy_to_host_async``
  prefetches pipeline well on links that handle small async copies
  efficiently (measured faster on the dev-tunnel TPU), while the pack
  wins where per-transfer overhead dominates (10⁴⁺ tiny leaves,
  high-latency hosts). Both paths are bit-identical.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from concurrent.futures import Executor
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import _native, knobs, telemetry
from .telemetry import names as metric_names
from .telemetry.trace import get_recorder as _trace_recorder
from .io_types import (
    BufferConsumer,
    BufferList,
    BufferStager,
    BufferType,
    ReadReq,
    as_bytes_view,
    WriteReq,
)
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    ShardedArrayEntry,
)


logger: logging.Logger = logging.getLogger(__name__)


def _is_batchable(req: WriteReq) -> bool:
    """Buffer-protocol array stagers without a custom prepare hook produce
    exactly ``get_staging_cost_bytes()`` bytes (reference is_batchable,
    batcher.py:477-482)."""
    from .io_preparer import ArrayBufferStager

    stager = req.buffer_stager
    return (
        isinstance(stager, ArrayBufferStager)
        and stager.array_prepare_func is None
    )


def _array_entries_by_location(entries: List[Entry]) -> Dict[str, List[ArrayEntry]]:
    """Every ArrayEntry in the manifest, keyed by storage location —
    including those nested in chunked/sharded entries."""
    out: Dict[str, List[ArrayEntry]] = {}

    def add(ae: ArrayEntry) -> None:
        out.setdefault(ae.location, []).append(ae)

    for entry in entries:
        if isinstance(entry, ArrayEntry):
            add(entry)
        elif isinstance(entry, (ChunkedArrayEntry, ShardedArrayEntry)):
            shards = entry.chunks if isinstance(entry, ChunkedArrayEntry) else entry.shards
            for shard in shards:
                add(shard.array)
    return out


class BatchedBufferStager(BufferStager):
    """Stages member buffers into one slab bytearray.

    Device-resident members pack **on device** first: a fused jitted
    program bitcasts each to its uint8 memory image and concatenates, so
    a device group's members cost one dispatch + one D2H transfer instead
    of one per member — the TPU answer to the reference's
    GPUBatchedBufferStager (batcher.py:102-160), replacing its
    storage-level GPU copies with an XLA program. Host members (and any
    device member the pack cannot handle) are materialized sequentially
    on the executor, costing only the memcpy.
    """

    def __init__(self, members: List[Tuple[WriteReq, int, int]]) -> None:
        # (req, offset, size) triples; offsets pre-assigned at planning.
        self.members = members
        self.total = sum(size for _, _, size in members)
        # The group split (and the staging cost derived from it) is fixed
        # here: it depends on knob state and on stager.arr fields that
        # staging itself mutates, so admission and any later budget
        # arithmetic must see one consistent value. The vectorized-write
        # decision is pinned for the same reason: a knob flip between
        # admission and staging must not change what this stager costs
        # or returns.
        self._vectorized = knobs.is_write_vectorized_enabled()
        self._packed, self._rest = self._split_device_groups()
        pack_bytes = sum(size for items in self._packed for _, _, size in items)
        peak_member = max(
            (
                req.buffer_stager.get_staging_cost_bytes()
                for req, _, _ in self._rest
            ),
            default=0,
        )
        if self._vectorized:
            # Zero-pack: the members' own staged buffers ARE the output
            # (handed to the plugin as a BufferList) — no slab
            # allocation, no transient pack copies alongside it.
            self._staging_cost = self.total
        else:
            self._staging_cost = self.total + pack_bytes + peak_member

    def capture(self, cache: dict) -> None:
        """Device-snapshot capture recurses into the slab's members:
        each member stager pins its own source (shared ``cache``, so a
        leaf split across slabs still snapshots once). The group split
        computed at construction still holds — jax members clone to jax
        arrays on the same devices, so pack eligibility is unchanged
        (and the pack path degrades to sequential staging on any
        surprise, as it always has)."""
        for req, _, _ in self.members:
            req.buffer_stager.capture(cache)

    # Per-dispatch member cap: an N-ary concat program's trace/compile
    # time grows with N, and one compile per distinct slab layout must
    # stay cheap.
    _PACK_GROUP_MAX = 128

    def _split_device_groups(self):
        """Partition members into device-pack groups (>= 2 jax members on
        one device set, knob-gated) and the remainder staged
        member-by-member."""
        if not knobs.is_device_pack_enabled():
            return [], list(self.members)
        from .io_preparer import ArrayBufferStager, is_jax_array
        from .ops.device_pack import device_group_key, pack_supported

        groups: Dict[Tuple[int, ...], List[Tuple[WriteReq, int, int]]] = {}
        rest: List[Tuple[WriteReq, int, int]] = []
        for item in self.members:
            stager = item[0].buffer_stager
            arr = getattr(stager, "arr", None)
            if (
                isinstance(stager, ArrayBufferStager)
                and is_jax_array(arr)
                and pack_supported(arr.dtype)
            ):
                groups.setdefault(device_group_key(arr), []).append(item)
            else:
                rest.append(item)
        packed: List[List[Tuple[WriteReq, int, int]]] = []
        for key, items in groups.items():
            if len(items) < 2:
                rest.extend(items)
                continue
            for i in range(0, len(items), self._PACK_GROUP_MAX):
                chunk = items[i : i + self._PACK_GROUP_MAX]
                if len(chunk) >= 2:
                    packed.append(chunk)
                else:
                    rest.extend(chunk)
        return packed, rest

    def _pack_group_sync(
        self, items: List[Tuple[WriteReq, int, int]], view: memoryview
    ) -> None:
        """One dispatch + one D2H for a whole device group, scattered into
        the slab at the planned offsets. Falls back to per-member staging
        on any failure (pack is an optimization, never a requirement)."""
        from .ops.device_pack import pack_async

        try:
            specs = []
            for req, _, _ in items:
                stager = req.buffer_stager
                slc = stager.slc
                specs.append(
                    (
                        stager.arr,
                        (slc.start, slc.stop) if slc is not None else None,
                    )
                )
            host = np.asarray(pack_async(specs))  # the single D2H
            expected = sum(size for _, _, size in items)
            if host.nbytes != expected:
                raise RuntimeError(
                    f"device pack produced {host.nbytes} bytes, "
                    f"planned {expected}"
                )
            src = 0
            for req, offset, size in items:
                view[offset : offset + size] = host[src : src + size].data
                src += size
                req.buffer_stager.arr = None  # release HBM promptly
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "Device slab pack failed (%r); staging %d members "
                "individually",
                e,
                len(items),
            )
            for req, offset, size in items:
                # arr is cleared only after a member's bytes landed in the
                # slab; a mid-scatter failure must not re-stage those.
                if req.buffer_stager.arr is None:
                    continue
                buf = req.buffer_stager._stage_sync()
                self._copy_member(view, buf, req, offset, size)

    def _copy_member(
        self, view: memoryview, buf: BufferType, req: WriteReq, offset: int, size: int
    ) -> None:
        mv = as_bytes_view(buf)
        self._check_member_size(len(mv), req, size)
        view[offset : offset + size] = mv

    async def stage_buffer(self, executor: Optional[Executor] = None) -> BufferType:
        if self._vectorized:
            # Zero-pack path: no slab buffer exists, so no pack span is
            # emitted — the distinct span name is the observable pin
            # that the pack pass did not run.
            with _trace_recorder().span(
                metric_names.SPAN_BATCHER_STAGE_SLAB_VECTORIZED,
                members=len(self.members),
                bytes=self.total,
            ):
                return await self._stage_vectorized_impl(executor)
        # Recorder-only span (awaits inside): the slab's whole
        # pack+memcpy assembly as one timeline block.
        with _trace_recorder().span(
            metric_names.SPAN_BATCHER_STAGE_SLAB,
            members=len(self.members),
            bytes=self.total,
        ):
            return await self._stage_buffer_impl(executor)

    def _pack_group_vectorized(
        self, items: List[Tuple[WriteReq, int, int]]
    ) -> List[Tuple[int, memoryview]]:
        """Device-pack a group for the zero-pack path: one dispatch + one
        D2H yields a host buffer whose per-member slices become BufferList
        parts directly — no scatter into a slab. Falls back to per-member
        staging on any failure, like the packed path."""
        from .ops.device_pack import pack_async

        out: List[Tuple[int, memoryview]] = []
        try:
            specs = []
            for req, _, _ in items:
                stager = req.buffer_stager
                slc = stager.slc
                specs.append(
                    (
                        stager.arr,
                        (slc.start, slc.stop) if slc is not None else None,
                    )
                )
            host = np.asarray(pack_async(specs))  # the single D2H
            expected = sum(size for _, _, size in items)
            if host.nbytes != expected:
                raise RuntimeError(
                    f"device pack produced {host.nbytes} bytes, "
                    f"planned {expected}"
                )
            hostview = memoryview(host).cast("B")
            src = 0
            for req, offset, size in items:
                out.append((offset, hostview[src : src + size]))
                src += size
                req.buffer_stager.arr = None  # release HBM promptly
            return out
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "Device slab pack failed (%r); staging %d members "
                "individually",
                e,
                len(items),
            )
            for req, offset, size in items:
                if req.buffer_stager.arr is None:
                    # This member's bytes already landed in ``out``.
                    continue
                buf = req.buffer_stager._stage_sync()
                mv = as_bytes_view(buf)
                self._check_member_size(len(mv), req, size)
                out.append((offset, mv))
            return out

    async def _stage_vectorized_impl(
        self, executor: Optional[Executor] = None
    ) -> BufferList:
        """Zero-pack slab staging: stage every member, hand the staged
        buffers to the write path as a :class:`BufferList` in planned
        offset order. The plugin's vectorized kernel (pwritev + fused
        CRC) writes them without the gather_memcpy pack pass ever
        running — the one-full-memory-pass-per-staged-byte elimination
        this path exists for."""
        loop = asyncio.get_running_loop()
        parts: List[Tuple[int, memoryview]] = []
        pack_futures = [
            loop.run_in_executor(executor, self._pack_group_vectorized, items)
            for items in self._packed
        ]
        first_exc: Optional[BaseException] = None
        try:
            for req, offset, size in self._rest:
                buf = await req.buffer_stager.stage_buffer(executor)
                mv = as_bytes_view(buf)
                self._check_member_size(len(mv), req, size)
                parts.append((offset, mv))
        except BaseException as e:  # noqa: BLE001 - settle packs first
            first_exc = e
        for fut in pack_futures:
            try:
                parts.extend(await fut)
            except BaseException as pack_exc:  # noqa: BLE001
                if first_exc is None:
                    first_exc = pack_exc
                else:
                    logger.warning(
                        "Device pack failed while aborting slab staging: %r",
                        pack_exc,
                    )
        if first_exc is not None:
            raise first_exc
        parts.sort(key=lambda item: item[0])
        expect = 0
        for offset, mv in parts:
            if offset != expect:
                raise RuntimeError(
                    f"vectorized slab has a hole at byte {expect} "
                    f"(next member starts at {offset}); manifest byte "
                    f"ranges would be wrong"
                )
            expect = offset + mv.nbytes
        if expect != self.total:
            raise RuntimeError(
                f"vectorized slab staged {expect} bytes, planned "
                f"{self.total}"
            )
        telemetry.metrics().counter_inc(
            metric_names.BATCHER_PACK_BYTES_AVOIDED_TOTAL, self.total
        )
        return BufferList([mv for _, mv in parts])

    def _check_member_size(self, staged: int, req: WriteReq, size: int) -> None:
        if staged != size:
            raise RuntimeError(
                f"Slab member {req.path!r} staged {staged} bytes but "
                f"was planned at {size}; byte ranges in the manifest "
                f"would be wrong"
            )

    async def _stage_buffer_impl(
        self, executor: Optional[Executor] = None
    ) -> BufferType:
        # 4096-aligned allocation: a packed slab qualifies for the fs
        # plugin's O_DIRECT write path (alignment is the eligibility
        # gate; see docs/storage.md "Native write path").
        slab = _native.aligned_buffer(self.total)
        view = memoryview(slab)
        loop = asyncio.get_running_loop()
        packed, rest = self._packed, self._rest
        pack_futures = [
            loop.run_in_executor(executor, self._pack_group_sync, items, view)
            for items in packed
        ]
        # Every pack future MUST settle before this method returns or
        # raises, no matter which one fails first: the executor threads
        # hold the slab's exported memoryview and may still be writing
        # into it (bytearray deallocation with exported views aborts the
        # interpreter). Collect the first failure — from the rest loop or
        # any pack — settle everything, then raise it.
        first_exc: Optional[BaseException] = None
        try:
            for req, offset, size in rest:
                buf = await req.buffer_stager.stage_buffer(executor)
                # Large members copy with the multithreaded native memcpy;
                # small ones aren't worth the thread spawn.
                if size >= (8 << 20):
                    mv = as_bytes_view(buf)
                    if len(mv) == size and _native.gather_memcpy(
                        slab, [(mv, offset)], n_threads=4
                    ):
                        continue
                self._copy_member(view, buf, req, offset, size)
        except BaseException as e:  # noqa: BLE001 - settle packs first
            first_exc = e
        for fut in pack_futures:
            try:
                await fut
            except BaseException as pack_exc:  # noqa: BLE001
                if first_exc is None:
                    first_exc = pack_exc
                else:
                    logger.warning(
                        "Device pack failed while aborting slab staging: %r",
                        pack_exc,
                    )
        if first_exc is not None:
            raise first_exc
        return slab

    def get_staging_cost_bytes(self) -> int:
        # The pack path transiently holds each group's packed host buffer
        # alongside the slab before the scatter, groups run concurrently,
        # AND the rest loop stages one member at the same time — admit at
        # the sum so the scheduler's budget bounds the true peak. The
        # member term counts only non-packed members (a packed member's
        # bytes are already inside pack_bytes). A slab with no
        # pack-eligible members costs the same as with the knob off.
        # Computed once in __init__: staging mutates the fields it
        # depends on.
        return self._staging_cost


def batch_write_requests(
    entries: List[Entry], write_reqs: List[WriteReq]
) -> Tuple[List[Entry], List[WriteReq]]:
    """Coalesce sub-threshold buffer-protocol writes into slabs, rewriting
    the affected manifest entries in place."""
    threshold = knobs.get_slab_size_threshold_bytes()
    by_location = _array_entries_by_location(entries)

    small: List[Tuple[WriteReq, int]] = []
    kept: List[WriteReq] = []
    for req in write_reqs:
        size = req.buffer_stager.get_staging_cost_bytes()
        # Only coalesce writes whose manifest entry we can rewrite.
        if _is_batchable(req) and size < threshold and req.path in by_location:
            small.append((req, size))
        else:
            kept.append(req)

    if len(small) < 2:
        return entries, write_reqs

    # Greedy fill: pack in plan order until the slab would overflow.
    slabs: List[List[Tuple[WriteReq, int, int]]] = []
    current: List[Tuple[WriteReq, int, int]] = []
    offset = 0
    for req, size in small:
        if current and offset + size > threshold:
            slabs.append(current)
            current, offset = [], 0
        current.append((req, offset, size))
        offset += size
    if current:
        slabs.append(current)

    for members in slabs:
        if len(members) == 1:
            # A lone member gains nothing from slab indirection.
            kept.append(members[0][0])
            continue
        location = f"batched/{uuid.uuid4().hex}"
        for req, off, size in members:
            for ae in by_location[req.path]:
                ae.location = location
                ae.byte_range = [off, off + size]
        kept.append(
            WriteReq(path=location, buffer_stager=BatchedBufferStager(members))
        )
    return entries, kept


# ----------------------------------------------------------------------
# read side
# ----------------------------------------------------------------------


class BatchedBufferConsumer(BufferConsumer):
    """Feeds each member consumer its sub-slice of a spanning read
    (reference BatchedBufferConsumer, batcher.py:355-474)."""

    def __init__(self, members: List[ReadReq], base: int, span_bytes: int) -> None:
        self.members = members
        self.base = base
        self.span_bytes = span_bytes

    async def consume_buffer(
        self, buf: BufferType, executor: Optional[Executor] = None
    ) -> None:
        mv = as_bytes_view(buf)
        # Recorder-only span: the spanning read's fan-out to member
        # consumers, previously invisible on any timeline.
        with _trace_recorder().span(
            metric_names.SPAN_BATCHER_CONSUME_SPANNING,
            members=len(self.members),
            bytes=self.span_bytes,
        ):
            await asyncio.gather(
                *(
                    member.buffer_consumer.consume_buffer(
                        mv[member.byte_range[0] - self.base : member.byte_range[1] - self.base],
                        executor,
                    )
                    for member in self.members
                )
            )

    def get_consuming_cost_bytes(self) -> int:
        # The spanning buffer itself (gap bytes included) dominates; the
        # member copies consume into destinations already accounted for.
        return max(
            self.span_bytes,
            sum(m.buffer_consumer.get_consuming_cost_bytes() for m in self.members),
        )


def batch_read_requests(read_reqs: List[ReadReq]) -> List[ReadReq]:
    """Merge ranged reads of one *slab* into one spanning read.

    Only ``batched/`` locations are merged: other multi-read paths are
    budget-bounded chunk splits (io_preparer / sharded_io_preparer ranged
    reads), and re-merging those would reintroduce exactly the unbounded
    buffer the splitting exists to prevent.
    """
    groups: Dict[str, List[ReadReq]] = {}
    order: List[str] = []
    out: List[ReadReq] = []
    for req in read_reqs:
        if not req.path.startswith("batched/") or req.byte_range is None:
            out.append(req)
            continue
        if req.path not in groups:
            order.append(req.path)
        groups.setdefault(req.path, []).append(req)

    for path in order:
        members = groups[path]
        if len(members) == 1:
            out.append(members[0])
            continue
        base = min(m.byte_range[0] for m in members)
        end = max(m.byte_range[1] for m in members)
        out.append(
            ReadReq(
                path=path,
                buffer_consumer=BatchedBufferConsumer(members, base, end - base),
                byte_range=(base, end),
            )
        )
    return out
