"""Small-write coalescing into slab blobs.

Reference parity target: torchsnapshot/batcher.py (482 LoC) — buffer-protocol
write requests under the slab threshold are packed into ``batched/{uuid}``
slabs with entry locations/byte_ranges rewritten, and ranged reads are merged
into spanning reads. Lands in a later milestone; the env knob fails loudly
until then instead of silently not batching.
"""

from __future__ import annotations

from typing import List, Tuple

from .io_types import ReadReq, WriteReq
from .manifest import Entry


def batch_write_requests(
    entries: List[Entry], write_reqs: List[WriteReq]
) -> Tuple[List[Entry], List[WriteReq]]:
    raise NotImplementedError(
        "TORCHSNAPSHOT_TPU_ENABLE_BATCHING is set, but slab batching has not "
        "landed yet; unset the env var"
    )


def batch_read_requests(read_reqs: List[ReadReq]) -> List[ReadReq]:
    raise NotImplementedError(
        "TORCHSNAPSHOT_TPU_ENABLE_BATCHING is set, but slab batching has not "
        "landed yet; unset the env var"
    )
