"""ctypes bindings for the native I/O runtime (native/ts_io.cpp).

The shared library is compiled on first use with the host toolchain and
cached next to the source (falling back to a temp dir when the package is
installed read-only). Everything degrades gracefully: if no C++ compiler
is available or the build fails, ``lib()`` returns ``None`` and callers
use their pure-Python paths — behavior is identical, only slower.

Why ctypes and not a CPython extension: ctypes releases the GIL around
every foreign call, which is exactly what the scheduler's executor threads
need (N threads → N concurrent pwrite/pread streams), and it keeps the
package importable on machines with no toolchain.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import shutil
import subprocess
import tempfile
import threading
from typing import List, Optional, Sequence, Tuple

from . import knobs

logger = logging.getLogger(__name__)

_SRC_PATH = os.path.join(os.path.dirname(__file__), "native", "ts_io.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _compiler() -> Optional[str]:
    for cc in ("g++", "clang++", "c++"):
        path = shutil.which(cc)
        if path:
            return path
    return None


def _build_and_load() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_SRC_PATH):
        logger.warning("native source missing at %s", _SRC_PATH)
        return None
    cc = _compiler()
    if cc is None:
        logger.info("no C++ compiler found; using pure-Python I/O paths")
        return None
    with open(_SRC_PATH, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src + cc.encode()).hexdigest()[:16]
    candidates = [
        os.path.join(os.path.dirname(_SRC_PATH), f"_ts_io_{tag}.so"),
        os.path.join(
            tempfile.gettempdir(), f"torchsnapshot_tpu_{os.getuid()}",
            f"_ts_io_{tag}.so",
        ),
    ]
    for so_path in candidates:
        if os.path.exists(so_path):
            try:
                return ctypes.CDLL(so_path)
            except OSError:
                pass  # stale/corrupt cache: rebuild below
        out_dir = os.path.dirname(so_path)
        tmp_out = None
        try:
            os.makedirs(out_dir, exist_ok=True)
            # Build to a temp name then rename: concurrent processes racing
            # the build each atomically install a complete .so.
            fd, tmp_out = tempfile.mkstemp(suffix=".so", dir=out_dir)
            os.close(fd)
            cmd = [
                cc, "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
                _SRC_PATH, "-o", tmp_out,
            ]
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
            if proc.returncode != 0:
                logger.warning(
                    "native build failed (%s): %s", cc, proc.stderr[-2000:]
                )
                return None
            os.replace(tmp_out, so_path)
            tmp_out = None
            return ctypes.CDLL(so_path)
        except (OSError, subprocess.TimeoutExpired) as e:
            logger.debug("native build in %s failed: %s", out_dir, e)
            continue
        finally:
            if tmp_out is not None:
                try:
                    os.unlink(tmp_out)
                except OSError:
                    pass
    return None


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (disabled / unbuildable)."""
    global _lib, _load_attempted
    if knobs.is_native_disabled():
        return None
    if _load_attempted:
        return _lib
    with _lock:
        if not _load_attempted:
            l = _build_and_load()
            if l is not None:
                _declare(l)
                logger.info("native I/O runtime loaded")
            _lib = l
            _load_attempted = True
    return _lib


def _declare(l: ctypes.CDLL) -> None:
    l.ts_write_file.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
    ]
    l.ts_write_file.restype = ctypes.c_int
    l.ts_pread_range.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
    ]
    l.ts_pread_range.restype = ctypes.c_int
    l.ts_file_size.argtypes = [ctypes.c_char_p]
    l.ts_file_size.restype = ctypes.c_int64
    l.ts_gather_memcpy.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    l.ts_gather_memcpy.restype = None
    l.ts_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
    l.ts_crc32c.restype = ctypes.c_uint32
    l.ts_write_file_crc.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int,
    ]
    l.ts_write_file_crc.restype = ctypes.c_int
    l.ts_pread_crc.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    l.ts_pread_crc.restype = ctypes.c_int
    l.ts_pwritev_file_crc.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int,
    ]
    l.ts_pwritev_file_crc.restype = ctypes.c_int
    l.ts_write_file_crc_direct.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int,
    ]
    l.ts_write_file_crc_direct.restype = ctypes.c_int


def _raise_errno(rc: int, path: str) -> None:
    err = -rc
    raise OSError(err, os.strerror(err), path)


def _addr_of(mv: memoryview) -> int:
    """Address of a contiguous memoryview's first byte (no copy).

    The address stays valid only while ``mv`` is alive — callers keep the
    view referenced for the duration of the foreign call. Writable
    buffers resolve through ``ctypes.from_buffer`` (pure C, no wrapper
    object churn); read-only ones (bytes / serialized payloads, which
    ``from_buffer`` rejects) fall back to the ``np.frombuffer`` route.
    This runs once per chunk on the hottest path in the library, so the
    numpy import is hoisted to module scope (lazily, on first read-only
    caller) instead of being re-resolved per call.
    """
    if mv.nbytes == 0:
        return 0
    if not mv.readonly:
        return ctypes.addressof(ctypes.c_char.from_buffer(mv))
    global _np
    if _np is None:
        import numpy

        _np = numpy
    return int(_np.frombuffer(mv, dtype=_np.uint8).ctypes.data)


_np = None

# O_DIRECT alignment unit (matches kDirectAlign in ts_io.cpp): buffer
# addresses must sit on this boundary for the direct write path.
DIRECT_IO_ALIGNMENT = 4096


def aligned_buffer(nbytes: int, align: int = DIRECT_IO_ALIGNMENT) -> memoryview:
    """A writable ``nbytes`` view whose first byte sits on an ``align``
    boundary — what makes a staged slab O_DIRECT-eligible. The view
    keeps its backing bytearray alive; zero-size requests still return
    a (degenerate) view so callers never branch."""
    raw = bytearray(nbytes + align)
    base = ctypes.addressof(ctypes.c_char.from_buffer(raw))
    off = (-base) % align
    return memoryview(raw)[off : off + nbytes]


def is_direct_aligned(mv: memoryview) -> bool:
    """True when ``mv``'s first byte is O_DIRECT-aligned."""
    if mv.nbytes == 0:
        return False
    return _addr_of(mv) % DIRECT_IO_ALIGNMENT == 0


def write_file(path: str, buf, do_fsync: bool = False) -> bool:
    """Native whole-file write. Returns False when native is unavailable."""
    l = lib()
    if l is None:
        return False
    mv = memoryview(buf).cast("B")
    rc = l.ts_write_file(
        path.encode(), _addr_of(mv), mv.nbytes, 1 if do_fsync else 0
    )
    if rc != 0:
        _raise_errno(rc, path)
    return True


def pread_into(path: str, out, offset: int = 0) -> bool:
    """Read exactly len(out) bytes at offset into writable buffer ``out``."""
    l = lib()
    if l is None:
        return False
    mv = memoryview(out).cast("B")
    if mv.readonly:
        raise ValueError("pread_into requires a writable buffer")
    rc = l.ts_pread_range(path.encode(), _addr_of(mv), mv.nbytes, offset)
    if rc != 0:
        _raise_errno(rc, path)
    return True


def file_size(path: str) -> Optional[int]:
    l = lib()
    if l is None:
        return None
    size = l.ts_file_size(path.encode())
    if size < 0:
        _raise_errno(int(size), path)
    return int(size)


def gather_memcpy(
    dst, parts: Sequence[Tuple[object, int]], n_threads: int = 4
) -> bool:
    """Scatter ``parts`` = [(src_buffer, dst_offset), ...] into writable
    ``dst`` with a multithreaded native memcpy. Returns False when native
    is unavailable (caller falls back to Python slicing)."""
    l = lib()
    if l is None or not parts:
        return l is not None
    dst_mv = memoryview(dst).cast("B")
    if dst_mv.readonly:
        raise ValueError("gather_memcpy requires a writable destination")
    n = len(parts)
    srcs = (ctypes.c_void_p * n)()
    sizes = (ctypes.c_uint64 * n)()
    offsets = (ctypes.c_uint64 * n)()
    # Keep memoryviews alive (and pinned) for the duration of the call.
    keepalive: List[memoryview] = []
    for i, (src, off) in enumerate(parts):
        mv = memoryview(src).cast("B")
        keepalive.append(mv)
        if off + mv.nbytes > dst_mv.nbytes:
            raise ValueError(
                f"part {i} [{off}, {off + mv.nbytes}) exceeds dst size "
                f"{dst_mv.nbytes}"
            )
        srcs[i] = _addr_of(mv)
        sizes[i] = mv.nbytes
        offsets[i] = off
    l.ts_gather_memcpy(
        _addr_of(dst_mv), srcs, sizes, offsets, n, int(n_threads)
    )
    return True


def crc32c(buf, seed: int = 0) -> Optional[int]:
    """CRC32-C of a bytes-like object, or None when native is unavailable."""
    l = lib()
    if l is None:
        return None
    mv = memoryview(buf).cast("B")
    return int(l.ts_crc32c(_addr_of(mv), mv.nbytes, seed & 0xFFFFFFFF))


def pread_into_crc(
    path: str, out, page_size: int, offset: int = 0
) -> Optional[List[int]]:
    """Fused read + integrity pass: fills ``out`` and returns the CRC32-C
    of each ``page_size`` page, computed while the page is cache-hot from
    the read. None when native is unavailable."""
    l = lib()
    if l is None:
        return None
    mv = memoryview(out).cast("B")
    if mv.readonly:
        raise ValueError("pread_into_crc requires a writable buffer")
    n_pages = (mv.nbytes + page_size - 1) // page_size
    crcs = (ctypes.c_uint32 * max(1, n_pages))()
    rc = l.ts_pread_crc(
        path.encode(), _addr_of(mv), mv.nbytes, offset, page_size, crcs
    )
    if rc != 0:
        _raise_errno(rc, path)
    return [int(crcs[i]) for i in range(n_pages)]


def pwritev_file_crc(
    path: str,
    parts: Sequence[object],
    page_size: Optional[int] = None,
    do_fsync: bool = False,
) -> Optional[List[int]]:
    """Zero-pack vectorized write: gather ``parts`` (buffer-protocol
    objects, concatenated in order) straight into a fresh file with
    pwritev. With ``page_size`` set, additionally computes the CRC32-C
    of each page of the concatenated stream (pages cross part
    boundaries) in the same cache-hot pass and returns the page list;
    without it, returns ``[]`` on success. ``None`` when the native
    runtime is unavailable (nothing written)."""
    l = lib()
    if l is None:
        return None
    n = len(parts)
    bufs = (ctypes.c_void_p * max(1, n))()
    lens = (ctypes.c_uint64 * max(1, n))()
    keepalive: List[memoryview] = []
    total = 0
    for i, part in enumerate(parts):
        mv = memoryview(part)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        keepalive.append(mv)
        bufs[i] = _addr_of(mv)
        lens[i] = mv.nbytes
        total += mv.nbytes
    crcs = None
    if page_size is not None:
        n_pages = (total + page_size - 1) // page_size
        crcs = (ctypes.c_uint32 * max(1, n_pages))()
    rc = l.ts_pwritev_file_crc(
        path.encode(),
        bufs,
        lens,
        n,
        page_size or 0,
        crcs,
        1 if do_fsync else 0,
    )
    if rc != 0:
        _raise_errno(rc, path)
    if crcs is None:
        return []
    n_pages = (total + page_size - 1) // page_size
    return [int(crcs[i]) for i in range(n_pages)]


def write_file_crc_direct(
    path: str, buf, page_size: Optional[int] = None, do_fsync: bool = False
) -> Optional[List[int]]:
    """O_DIRECT fused write (+ optional integrity pass) for large aligned
    buffers: the 4096-aligned body bypasses the page cache, the unaligned
    tail is written buffered, and — with ``page_size`` set — each page's
    CRC32-C is computed in the same loop. ``page_size=None`` skips the
    CRC pass entirely (the kernel takes a NULL page array; no per-byte
    CRC cost when the caller doesn't record checksums) and returns ``[]``
    on success. ``None`` when the native runtime is unavailable. Raises
    ``OSError(EINVAL)`` on filesystems without O_DIRECT support (tmpfs)
    or for unaligned buffers — callers treat that as a sticky decline
    back to the buffered fused path."""
    l = lib()
    if l is None:
        return None
    mv = memoryview(buf)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    if page_size is None:
        n_pages = 0
        out = None
    else:
        n_pages = (mv.nbytes + page_size - 1) // page_size
        out = (ctypes.c_uint32 * max(1, n_pages))()
    rc = l.ts_write_file_crc_direct(
        path.encode(),
        _addr_of(mv),
        mv.nbytes,
        page_size or 0,
        out,
        1 if do_fsync else 0,
    )
    if rc != 0:
        _raise_errno(rc, path)
    return [int(out[i]) for i in range(n_pages)]


def write_file_crc(
    path: str, buf, page_size: int, do_fsync: bool = False
) -> Optional[List[int]]:
    """Fused write + integrity pass: writes ``buf`` to a fresh file and
    returns the CRC32-C of each ``page_size`` page (computed while the
    page is cache-hot from the write — one memory pass instead of two).
    None when native is unavailable."""
    l = lib()
    if l is None:
        return None
    mv = memoryview(buf).cast("B")
    n_pages = (mv.nbytes + page_size - 1) // page_size
    out = (ctypes.c_uint32 * max(1, n_pages))()
    rc = l.ts_write_file_crc(
        path.encode(),
        _addr_of(mv),
        mv.nbytes,
        page_size,
        out,
        1 if do_fsync else 0,
    )
    if rc != 0:
        _raise_errno(rc, path)
    return [int(out[i]) for i in range(n_pages)]
