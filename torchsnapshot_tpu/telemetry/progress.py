"""Live per-operation progress heartbeats.

PRs 2–3 made a *finished* checkpoint explainable (SnapshotReport,
flight-recorder traces); nothing showed a take *while it runs*. This
module publishes each live operation's state two ways:

- **In-memory, always on**: :func:`current_progress` returns a snapshot
  of every active operation's counters — the watchdog attaches it to
  stall reports ("how far did the op get"), and in-process pollers
  (notebooks, sidecar threads) read it for free.
- **Heartbeat file, knob-gated**: every
  ``TORCHSNAPSHOT_TPU_PROGRESS_SECONDS`` (default 1 s; <= 0 disables)
  the tracker atomically rewrites ``<snapshot>/.progress-rank<r>.json``
  (or, for object-store snapshots,
  ``TORCHSNAPSHOT_TPU_PROGRESS_DIR/progress-<digest>-<kind>-rank<r>.json``
  — digest = first 8 hex chars of sha1(snapshot path), so ops on
  different snapshots sharing the dir never clobber each other) so an
  *external* poller — a babysitter script, another host — can see a
  stuck rank before the in-process watchdog fires. Atomic tmp+rename: a concurrent reader never sees a
  torn document, and ``written_bytes`` is monotonically non-decreasing
  across reads of one operation.

Heartbeat schema (all fields always present; see docs/observability.md):

``kind, path, rank, phase, planned_items, planned_bytes, staged_bytes,
written_bytes, items_pending, items_staging, items_inflight,
items_done, budget_wait_s, budget_wait_frac, throughput_mb_s, eta_s,
elapsed_s, updated_unix_ts, terminal, error, mirror, pid,
schema_version``

``terminal`` is null while the op is live, ``"done"`` / ``"failed"``
once it settles. A successful op *removes* its heartbeat file; a failed
op leaves a terminal document behind; a crashed op leaves a non-terminal
one — which ``fsck --stats`` lists and the checkpoint doctor flags as
``interrupted-take`` evidence.

The scheduler's pipelines feed the tracker from their live counters
(``_PipelineStats`` + ``MemoryBudget``); a restore's several read
pipelines fold into one tracker via ``begin_pipeline`` offsets so the
published totals only ever grow.
"""

from __future__ import annotations

import collections
import glob
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .. import knobs

logger: logging.Logger = logging.getLogger(__name__)

PROGRESS_SCHEMA_VERSION = 1
SNAPSHOT_PROGRESS_PREFIX = ".progress-rank"

# Rolling throughput window: ETA is computed over the last N published
# (time, written_bytes) points, so it tracks the *current* rate, not
# the lifetime average a long budget wait would poison.
_RATE_WINDOW_POINTS = 16


def _path_digest(snapshot_path: Optional[str]) -> str:
    import hashlib

    return hashlib.sha1((snapshot_path or "").encode("utf-8")).hexdigest()[:8]


def progress_path_for(
    snapshot_path: Optional[str], rank: int, kind: str = ""
) -> Optional[str]:
    """Where this rank's heartbeat file goes, or None when the file
    heartbeat is disabled (interval knob <= 0) or the snapshot path has
    no local root and no progress dir is configured. Resolution order
    matches the report/trace sinks: explicit dir knob first, then the
    snapshot-adjacent file for local paths.

    The shared-dir form is disambiguated by a snapshot-path digest and
    the op kind: a dir serving several snapshots (or a take of step N+1
    overlapping step N's async commit) must never have ops clobbering —
    or, worse, ``finish()``-deleting — each other's heartbeats. The
    snapshot-adjacent form needs neither: the directory IS the snapshot,
    and one snapshot never runs two same-rank ops concurrently."""
    if knobs.get_progress_interval_seconds() <= 0:
        return None
    progress_dir = knobs.get_progress_dir()
    if progress_dir:
        disambig = f"{_path_digest(snapshot_path)}-{kind}-" if kind else ""
        return os.path.join(
            progress_dir, f"progress-{disambig}rank{rank}.json"
        )
    from .sink import local_fs_root

    root = local_fs_root(snapshot_path)
    if root is None:
        return None
    return os.path.join(root, f"{SNAPSHOT_PROGRESS_PREFIX}{rank}.json")


def find_progress_files(snapshot_path: str) -> List[str]:
    """Heartbeat files recorded for one snapshot (crash leftovers
    included): the snapshot-adjacent ``.progress-rank*.json`` plus, when
    a progress dir is configured, its files for THIS snapshot — matched
    by the path digest every dir-mode filename embeds, so a shared dir
    serving many snapshots is filtered by one glob, no per-file parse,
    and snapshot A's diagnosis never cites snapshot B's heartbeat."""
    out: List[str] = []
    from .sink import local_fs_root

    root = local_fs_root(snapshot_path)
    if root is not None:
        out.extend(
            sorted(
                glob.glob(
                    os.path.join(root, f"{SNAPSHOT_PROGRESS_PREFIX}*.json")
                )
            )
        )
    progress_dir = knobs.get_progress_dir()
    if progress_dir:
        out.extend(
            sorted(
                glob.glob(
                    os.path.join(
                        progress_dir,
                        f"progress-{_path_digest(snapshot_path)}-*.json",
                    )
                )
            )
        )
    return out


def remove_dir_heartbeats(snapshot_path: str) -> None:
    """Drop the shared progress dir's heartbeats for one snapshot —
    the manager-GC hook. The snapshot-adjacent heartbeats die with the
    step directory, but dir-mode leftovers (a crashed op's) have no
    other reaper and would otherwise accumulate across job restarts,
    each a standing interrupted-take verdict for a snapshot that no
    longer exists."""
    progress_dir = knobs.get_progress_dir()
    if not progress_dir:
        return
    digest = _path_digest(snapshot_path)
    for leftover in glob.glob(
        os.path.join(progress_dir, f"progress-{digest}-*.json")
    ):
        try:
            os.remove(leftover)
        except OSError:
            pass


def load_progress_file(path: str) -> Optional[Dict[str, Any]]:
    """Parse one heartbeat file; None when unreadable (a reader must
    never crash on a file being replaced under it)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class ProgressTracker:
    """One live checkpoint operation's progress state.

    Thread-safe: the scheduler's event-loop thread updates counters,
    the watchdog/current_progress read from other threads, and an
    async take's drain updates from the background commit thread.
    File publishing is interval-gated (``tick``); the in-memory state
    updates on every call regardless.
    """

    def __init__(self, kind: str, path: str, rank: int) -> None:
        self.kind = kind
        self.path = path
        self.rank = rank
        self._lock = threading.Lock()
        self._begin = time.monotonic()
        self._phase = "starting"
        self._terminal: Optional[str] = None
        self._error: Optional[str] = None
        # Totals folded in from pipelines that already finished; the
        # current pipeline's live counters add on top (a restore runs
        # one read pipeline per stateful).
        self._base = {
            "planned_items": 0,
            "planned_bytes": 0,
            "staged_bytes": 0,
            "written_bytes": 0,
            "items_done": 0,
            "budget_wait_s": 0.0,
        }
        self._cur = dict(self._base)
        self._cur_live = {"pending": 0, "staging": 0, "inflight": 0}
        self._rate_window: "collections.deque" = collections.deque(
            maxlen=_RATE_WINDOW_POINTS
        )
        self._file = progress_path_for(path, rank, kind=kind)
        self._min_interval = knobs.get_progress_interval_seconds()
        self._last_publish = 0.0
        # Serializes file publishes against each other AND against
        # finish(): the pipeline thread and the background refresher
        # share one pid-suffixed tmp file, so concurrent writers would
        # tear it — and a refresher publish racing finish()'s removal
        # must not resurrect the just-deleted heartbeat.
        self._publish_lock = threading.Lock()
        _register(self)
        # First heartbeat immediately: an external poller learns the op
        # exists (and its plan, once known) without waiting an interval.
        self._publish()

    # -- pipeline feed ---------------------------------------------------

    def begin_pipeline(
        self, items: int, planned_bytes: int, phase: Optional[str] = None
    ) -> None:
        """A new scheduler pipeline joins this op: fold the previous
        pipeline's final counters into the base and add the new plan."""
        with self._lock:
            for k in self._base:
                self._base[k] = self._cur[k]
            self._base["planned_items"] += items
            self._base["planned_bytes"] += planned_bytes
            self._cur = dict(self._base)
            self._cur_live = {"pending": items, "staging": 0, "inflight": 0}
            if phase is not None:
                self._phase = phase
        self._publish()

    def update_pipeline(
        self,
        pending: int,
        staging: int,
        inflight: int,
        done: int,
        staged_bytes: int,
        done_bytes: int,
        budget_wait_s: float,
    ) -> None:
        """Absolute counters from the *current* pipeline's stats; the
        published totals are base + these. Cheap (a lock and a few dict
        stores); the file write underneath is interval-gated."""
        with self._lock:
            self._cur["items_done"] = self._base["items_done"] + done
            self._cur["staged_bytes"] = self._base["staged_bytes"] + staged_bytes
            self._cur["written_bytes"] = (
                self._base["written_bytes"] + done_bytes
            )
            self._cur["budget_wait_s"] = (
                self._base["budget_wait_s"] + budget_wait_s
            )
            self._cur_live = {
                "pending": pending,
                "staging": staging,
                "inflight": inflight,
            }
            # The ETA window advances only when BYTES advanced: reads
            # must not shrink the window (rate-as-a-function-of-polling)
            # and a staging-only burst must not evict every
            # write-progress point and flap the published rate to zero
            # mid-drain.
            if (
                not self._rate_window
                or self._rate_window[-1][1] != self._cur["written_bytes"]
            ):
                self._rate_window.append(
                    (time.monotonic(), self._cur["written_bytes"])
                )
        self.tick()

    def set_phase(self, phase: str) -> None:
        with self._lock:
            self._phase = phase
        self._publish()

    # -- publishing ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The heartbeat document (also the current_progress() row).
        Read-only: polling must not perturb the rate window."""
        with self._lock:
            now = time.monotonic()
            written = self._cur["written_bytes"]
            rate_bps = 0.0
            if len(self._rate_window) >= 2:
                (t0, b0), (t1, b1) = self._rate_window[0], self._rate_window[-1]
                if t1 - t0 > 1e-6 and b1 > b0:
                    rate_bps = (b1 - b0) / (t1 - t0)
            remaining = max(0, self._cur["planned_bytes"] - written)
            eta_s = round(remaining / rate_bps, 1) if rate_bps > 0 else None
            elapsed = now - self._begin
            wait = self._cur["budget_wait_s"]
            doc = {
                "schema_version": PROGRESS_SCHEMA_VERSION,
                "kind": self.kind,
                "path": self.path,
                "rank": self.rank,
                "pid": os.getpid(),
                "phase": self._phase,
                "planned_items": self._cur["planned_items"],
                "planned_bytes": self._cur["planned_bytes"],
                "staged_bytes": self._cur["staged_bytes"],
                "written_bytes": written,
                "items_pending": self._cur_live["pending"],
                "items_staging": self._cur_live["staging"],
                "items_inflight": self._cur_live["inflight"],
                "items_done": self._cur["items_done"],
                "budget_wait_s": round(wait, 6),
                "budget_wait_frac": (
                    round(wait / elapsed, 4) if elapsed > 1e-6 else 0.0
                ),
                "throughput_mb_s": round(rate_bps / 1024**2, 3),
                "eta_s": eta_s,
                "elapsed_s": round(elapsed, 3),
                "updated_unix_ts": time.time(),
                # The writer's own heartbeat cadence: readers in OTHER
                # processes (the doctor's staleness check) must judge
                # freshness against the interval the writer used, not
                # their own knob value.
                "interval_s": self._min_interval,
                "terminal": self._terminal,
                "error": self._error,
            }
        doc["mirror"] = self._mirror_depth()
        return doc

    def _mirror_depth(self) -> Optional[Dict[str, Any]]:
        """The process mirror's queue depth for tiered paths (part of
        the heartbeat: durability backlog is live state too)."""
        try:
            from ..tiered.mirror import mirror_state_for_path

            m = mirror_state_for_path(self.path)
            if m is None:
                return None
            return {
                "blobs_pending": m["blobs_pending"],
                "snapshots_pending": m["snapshots_pending"],
                "upload_lag_s": m["upload_lag_s"],
            }
        except Exception:  # noqa: BLE001 - heartbeat must not fail the op
            return None

    def tick(self) -> None:
        """Interval-gated heartbeat rewrite; no-op when the file sink is
        disabled, the op settled, or the interval hasn't lapsed."""
        if self._file is None or self._terminal is not None:
            return
        now = time.monotonic()
        if now - self._last_publish < self._min_interval:
            return
        self._publish()

    def _publish(self, final: bool = False) -> None:
        if self._file is None:
            return
        with self._publish_lock:
            # Re-checked under the publish lock: a refresher tick that
            # lost the race with finish() must not rewrite (resurrect)
            # a heartbeat the settled op already removed.
            if self._terminal is not None and not final:
                return
            self._last_publish = time.monotonic()
            try:
                from .sink import atomic_write_text

                # Atomic replace: a concurrent reader never observes a
                # torn document.
                atomic_write_text(
                    self._file,
                    json.dumps(self.snapshot(), separators=(",", ":")),
                )
            except Exception as e:  # noqa: BLE001 - heartbeat must not
                # fail the op
                logger.warning("progress: heartbeat write failed: %r", e)

    def finish(self, error: Optional[BaseException] = None) -> None:
        """Settle the op: unregister from current_progress, and either
        remove the heartbeat file (success — a completed op leaves no
        leftovers) or rewrite it terminal with the error (failure — the
        doctor's evidence that the op *ended*, distinguishing a clean
        failure from a crash's non-terminal leftover)."""
        with self._lock:
            if self._terminal is not None:
                return
            self._terminal = "failed" if error is not None else "done"
            self._error = repr(error) if error is not None else None
        _unregister(self)
        if self._file is None:
            return
        try:
            if error is None:
                # Under the publish lock: an in-flight publish settles
                # first, so the removal is the last word on the file.
                with self._publish_lock:
                    try:
                        os.remove(self._file)
                    except FileNotFoundError:
                        pass
            else:
                self._publish(final=True)
        except Exception as e:  # noqa: BLE001
            logger.warning("progress: heartbeat finish failed: %r", e)


# ---------------------------------------------------------------------------
# Process-wide active-op table + heartbeat refresher
# ---------------------------------------------------------------------------

_ACTIVE: Dict[int, ProgressTracker] = {}
_ACTIVE_LOCK = threading.Lock()
_REFRESHER: Optional[threading.Thread] = None


def _refresh_loop() -> None:
    """Keep heartbeat files fresh while their ops are BLOCKED: pipeline
    events drive publishes normally, but a multi-minute storage write
    (or budget wait) produces none — and an external reader judges
    liveness by ``updated_unix_ts`` against the recorded ``interval_s``,
    so a silent writer looks exactly like a crash. The loop exits (and
    clears its slot under the table lock, so registration can never
    race a dying thread) once no file-publishing tracker remains."""
    global _REFRESHER
    while True:
        with _ACTIVE_LOCK:
            trackers = [t for t in _ACTIVE.values() if t._file is not None]
            if not trackers:
                _REFRESHER = None
                return
        for tracker in trackers:
            try:
                tracker.tick()
            except Exception:  # noqa: BLE001 - refresh must not die
                pass
        time.sleep(max(0.05, min(t._min_interval for t in trackers)))


def _register(tracker: ProgressTracker) -> None:
    global _REFRESHER
    with _ACTIVE_LOCK:
        _ACTIVE[id(tracker)] = tracker
        if tracker._file is not None and _REFRESHER is None:
            _REFRESHER = threading.Thread(
                target=_refresh_loop, name="ts-progress", daemon=True
            )
            _REFRESHER.start()


def _unregister(tracker: ProgressTracker) -> None:
    with _ACTIVE_LOCK:
        _ACTIVE.pop(id(tracker), None)


def track(kind: str, path: str, rank: int) -> ProgressTracker:
    """Start tracking one operation; callers must pair with
    ``finish()`` (success or failure) so current_progress never leaks
    settled ops."""
    return ProgressTracker(kind, path, rank)


def current_progress() -> List[Dict[str, Any]]:
    """Live snapshot of every active operation in this process — the
    always-on in-memory view (no knobs). Ordered by op start."""
    with _ACTIVE_LOCK:
        trackers = list(_ACTIVE.values())
    trackers.sort(key=lambda t: t._begin)
    return [t.snapshot() for t in trackers]


def reset_progress() -> None:
    """Drop the active-op table (tests simulating a fresh process)."""
    with _ACTIVE_LOCK:
        _ACTIVE.clear()
