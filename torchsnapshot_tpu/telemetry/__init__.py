"""Unified checkpoint telemetry.

One process-wide :class:`MetricsRegistry` (counters, gauges,
fixed-bucket histograms — thread/asyncio-safe, always recording) that
every layer instruments directly: the scheduler's phase completions and
memory-budget waits, the storage plugins' byte/latency counters, the
retry strategies' attempt counts, the tiered mirror's queue/lag gauges.
Each ``Snapshot.take``/``async_take``/``restore`` and each mirror job
additionally assembles a :class:`SnapshotReport` — a JSON-serializable
per-operation record, cross-rank aggregated via ``dist_store.Store.gather``
— and hands it to the knob-controlled sinks (JSONL event log,
Prometheus text file). ``python -m torchsnapshot_tpu.telemetry`` /
``tools/snapshot_stats.py`` render the event log as per-step tables.

Alongside the registry's aggregates, the **flight recorder**
(trace.py) keeps an always-on, bounded span timeline of the same
layers — exported per operation as Chrome trace JSON (knob-gated, like
the sinks), merged cross-rank by ``python -m torchsnapshot_tpu.telemetry
trace``, and patrolled by the stall watchdog (watchdog.py).

Above the per-op layers sits the **run ledger** (ledger.py —
crash-safe ``<root>/.ledger.jsonl`` of typed run events, rank-0-only,
resumable across restarts) and the **goodput engine** (goodput.py)
that attributes a whole run's wall time into train vs.
checkpoint-overhead buckets and storage-cost curves — ``python -m
torchsnapshot_tpu.telemetry goodput <root>``, ``goodput_*`` gauges,
and the doctor's ``goodput-degraded`` / ``recovery-cost-high`` rules.
See docs/goodput.md.

Three further layers make the telemetry *operable*: live per-rank
progress heartbeats for operations in flight (progress.py —
``current_progress()`` in-process, atomically-rewritten
``.progress-rank<r>.json`` files for external pollers), the rule-based
**checkpoint doctor** (doctor.py — ``python -m
torchsnapshot_tpu.telemetry doctor <snapshot>`` emits ranked,
evidence-cited verdicts from the recorded artifacts), and a rolling
per-manager step history with median±MAD trend regression detection
(history.py, ``doctor --trend``).

At the top of the stack, the **SLO engine** (slo.py) judges the
recorded signals against declared objectives with multi-window
burn-rate math at every committed step — ``slo_burn_rate{objective}``
gauges, edge-triggered ``slo-breach`` ledger events, the fleet table's
BURN column, the doctor's ``slo-burning`` rule — and **incident
bundles** (bundle.py) freeze a bounded, self-contained black box of
the evidence on SLO breach / watchdog stall / failed op, which
``doctor --bundle``, ``telemetry slo``, and ``telemetry diff``
re-analyze offline with the original root gone.

See docs/observability.md for the metric inventory, span inventory,
report schema, sink knobs, and CLI.
"""

from __future__ import annotations

from . import (
    bundle,
    critpath,
    doctor,
    goodput,
    history,
    ledger,
    names,
    progress,
    slo,
    trace,
    watchdog,
    wire,
)
from .registry import (
    DEFAULT_SECONDS_BUCKETS,
    MetricsRegistry,
    parse_series_key,
    series_key,
)
from .report import (
    SnapshotReport,
    aggregate_across_ranks,
    build_report,
    clock_offsets_from_gather,
    merge_pipeline_telemetry,
)
from .progress import current_progress
from .sink import (
    emit_report,
    events_path_for,
    last_report,
    load_events,
    render_prometheus,
    write_prometheus_textfile,
)

__all__ = [
    "DEFAULT_SECONDS_BUCKETS",
    "MetricsRegistry",
    "SnapshotReport",
    "aggregate_across_ranks",
    "build_report",
    "bundle",
    "clock_offsets_from_gather",
    "critpath",
    "current_progress",
    "doctor",
    "emit_report",
    "events_path_for",
    "goodput",
    "history",
    "ledger",
    "last_report",
    "load_events",
    "merge_pipeline_telemetry",
    "metrics",
    "names",
    "observe_io",
    "parse_series_key",
    "progress",
    "record_phase",
    "render_prometheus",
    "reset_metrics",
    "reset_trace",
    "safe_rate_mb_s",
    "series_key",
    "slo",
    "trace",
    "watchdog",
    "wire",
    "write_prometheus_textfile",
]

_REGISTRY = MetricsRegistry()

# Below this elapsed time a bytes/elapsed rate is numerical noise: the
# first report tick of an empty or instant phase would otherwise print
# an effectively-infinite MB/s. One threshold for every rate renderer
# (scheduler progress lines, snapshot-stats tables).
MIN_RATE_ELAPSED_S = 1e-3


def safe_rate_mb_s(nbytes: float, elapsed_s: float) -> float:
    """Throughput in MB/s, 0.0 when the elapsed time is zero or too
    small to carry signal (guards the div-by-~0 -> inf MB/s report)."""
    if elapsed_s < MIN_RATE_ELAPSED_S:
        return 0.0
    return nbytes / 1024**2 / elapsed_s


def metrics() -> MetricsRegistry:
    """The process-wide registry every instrumented layer records into."""
    return _REGISTRY


def reset_metrics() -> None:
    """Drop all recorded metrics (tests simulating a fresh process)."""
    _REGISTRY.reset()


def reset_trace() -> None:
    """Drop the flight recorder's ring and open-span table (tests
    simulating a fresh process)."""
    trace.get_recorder().reset()


def record_phase(phase: str, elapsed_s: float) -> None:
    """Publish one pipeline-phase completion: feeds the registry's phase
    histogram AND the last-writer-wins phase-timing channel that
    ``scheduler.last_phase_timings()`` serves as a compatibility shim."""
    _REGISTRY.record_phase_timing(phase, elapsed_s)
    _REGISTRY.histogram_observe(
        names.SNAPSHOT_PHASE_SECONDS, elapsed_s, phase=phase
    )


def observe_io(plugin: str, op: str, nbytes: int, seconds: float) -> None:
    """One storage operation's accounting (op: "write" | "read"); the
    shared instrumentation hook for the fs/s3/gcs plugins."""
    if op == "write":
        _REGISTRY.counter_inc(
            names.STORAGE_WRITE_BYTES_TOTAL, nbytes, plugin=plugin
        )
        _REGISTRY.counter_inc(names.STORAGE_WRITE_OPS_TOTAL, plugin=plugin)
        _REGISTRY.histogram_observe(
            names.STORAGE_WRITE_SECONDS, seconds, plugin=plugin
        )
    else:
        _REGISTRY.counter_inc(
            names.STORAGE_READ_BYTES_TOTAL, nbytes, plugin=plugin
        )
        _REGISTRY.counter_inc(names.STORAGE_READ_OPS_TOTAL, plugin=plugin)
        _REGISTRY.histogram_observe(
            names.STORAGE_READ_SECONDS, seconds, plugin=plugin
        )
