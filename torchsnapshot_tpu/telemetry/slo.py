"""Declared checkpoint SLOs, judged continuously with burn-rate math.

The stack records every signal a production fleet needs (SnapshotReports,
the run ledger, step history, the fleet wire plane) but none of it says
whether the service is keeping its *promises*. This module declares those
promises as a registry of objectives over signals already recorded —
nothing here instruments an op — and re-judges them on rank 0 at every
committed manager step:

- ``take-visible-stall``: visible training stall per take stays under
  the async visible budget.
- ``restore-wall``: restores serve within the restore wall budget.
- ``mirror-durability-lag``: fast-tier-only exposure per step stays
  under the mirror lag budget.
- ``cdn-staleness``: publish-to-swap staleness per subscriber swap
  stays under the CDN staleness budget.
- ``goodput-overhead``: checkpoint overhead per commit interval stays
  under the overhead fraction budget.
- ``coordination-fraction``: coordination's share of a take's wall
  stays under the coordination fraction budget.

Each objective is judged with multi-window burn-rate math (the SRE
workbook's alerting model): a sample is *bad* when it exceeds the
objective's target; ``burn = bad-fraction / error-budget-fraction`` over
a window, so burn 1.0 means the error budget is being spent exactly at
the sustainable rate. Two windows fire on different failure shapes — a
short window with a high threshold catches cliffs (a plugin suddenly
slow, a tier gone) within a few steps, and a long window with threshold
~1.0 catches drift the short window averages away. An objective
*breaches* when either window's burn crosses its threshold; targets,
windows, thresholds and the budget are all knobs, and a non-positive
target disables that objective alone.

``evaluate_step`` is the manager's post-commit hook: it refreshes the
``slo_burn_rate{objective}`` gauges, posts an edge-triggered
``slo-breach`` ledger event per objective episode (one record when an
objective *starts* burning, not one per evaluated step), and asks
telemetry/bundle.py for one incident bundle per evaluation that saw a
fresh breach. ``python -m torchsnapshot_tpu.telemetry slo <root>``
renders the same judgment offline, including against a bundle dir. The
``slo-burning`` doctor rule re-runs ``evaluate`` over gathered
evidence, so doctor verdicts reproduce bit-for-bit from a relocated
bundle with the original root gone.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
)

from .. import knobs
from . import names

logger = logging.getLogger(__name__)

# One sample: (unix_ts, observed value in the objective's unit).
Sample = Tuple[float, float]


def _num(value: Any) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


def _ledger_samples(
    event: str, field: str
) -> Callable[[Sequence[Dict[str, Any]], Sequence[Dict[str, Any]]], List[Sample]]:
    """Extractor for objectives whose samples are one numeric field of
    one typed ledger event (the common case)."""

    def extract(
        ledger_records: Sequence[Dict[str, Any]],
        history_records: Sequence[Dict[str, Any]],
    ) -> List[Sample]:
        out: List[Sample] = []
        for rec in ledger_records:
            if rec.get("event") != event:
                continue
            value = _num(rec.get(field))
            ts = _num(rec.get("unix_ts"))
            if value is None or ts is None:
                continue
            out.append((ts, value))
        out.sort(key=lambda s: s[0])
        return out

    return extract


def _overhead_samples(
    ledger_records: Sequence[Dict[str, Any]],
    history_records: Sequence[Dict[str, Any]],
) -> List[Sample]:
    """Per-commit-interval overhead fraction: the visible stall +
    restore wall paid between consecutive step commits, over the
    interval's wall clock. Resets at run-start so a restart's gap is
    not charged as overhead."""
    out: List[Sample] = []
    prev_ts: Optional[float] = None
    overhead = 0.0
    for rec in sorted(
        ledger_records, key=lambda r: _num(r.get("unix_ts")) or 0.0
    ):
        event = rec.get("event")
        ts = _num(rec.get("unix_ts"))
        if ts is None:
            continue
        if event == names.EVENT_RUN_START:
            prev_ts = ts
            overhead = 0.0
        elif event == names.EVENT_VISIBLE_STALL:
            overhead += _num(rec.get("visible_s")) or 0.0
        elif event == names.EVENT_RESTORE_SERVED:
            overhead += _num(rec.get("restore_s")) or 0.0
        elif event == names.EVENT_STEP_COMMITTED:
            if prev_ts is not None and ts > prev_ts:
                out.append((ts, min(1.0, overhead / (ts - prev_ts))))
            prev_ts = ts
            overhead = 0.0
    return out


def _coordination_samples(
    ledger_records: Sequence[Dict[str, Any]],
    history_records: Sequence[Dict[str, Any]],
) -> List[Sample]:
    """Coordination's share of each take's wall, from the step-history
    summaries (the only place the coordination split is recorded)."""
    out: List[Sample] = []
    for rec in history_records:
        if rec.get("kind") not in ("take", "async_take"):
            continue
        take_s = _num(rec.get("take_s"))
        coord_s = _num(rec.get("coordination_s"))
        ts = _num(rec.get("unix_ts"))
        if take_s is None or coord_s is None or ts is None or take_s <= 0:
            continue
        out.append((ts, min(1.0, coord_s / take_s)))
    out.sort(key=lambda s: s[0])
    return out


@dataclass(frozen=True)
class Objective:
    """One declared promise: a target over a sample stream. ``slo_id``
    must be a ``names.SLO_*`` constant (snaplint's ``slo-ids`` rule
    checks every construction site)."""

    slo_id: str
    description: str
    unit: str
    target: Callable[[], float]
    samples: Callable[
        [Sequence[Dict[str, Any]], Sequence[Dict[str, Any]]], List[Sample]
    ]


OBJECTIVES: Tuple[Objective, ...] = (
    Objective(
        names.SLO_TAKE_VISIBLE_STALL,
        "visible training stall per take/async_take",
        "s",
        knobs.get_async_visible_budget_seconds,
        _ledger_samples(names.EVENT_VISIBLE_STALL, "visible_s"),
    ),
    Objective(
        names.SLO_RESTORE_WALL,
        "restore/async_restore serve wall",
        "s",
        knobs.get_slo_restore_seconds,
        _ledger_samples(names.EVENT_RESTORE_SERVED, "restore_s"),
    ),
    Objective(
        names.SLO_MIRROR_LAG,
        "fast-tier-only exposure per mirrored step",
        "s",
        knobs.get_slo_mirror_lag_seconds,
        _ledger_samples(names.EVENT_MIRROR_SETTLED, "lag_s"),
    ),
    Objective(
        names.SLO_CDN_STALENESS,
        "CDN publish-to-swap staleness per subscriber swap",
        "s",
        knobs.get_cdn_staleness_budget_seconds,
        _ledger_samples(names.EVENT_CDN_SWAPPED, "staleness_s"),
    ),
    Objective(
        names.SLO_GOODPUT_OVERHEAD,
        "checkpoint overhead fraction per commit interval",
        "frac",
        knobs.get_slo_overhead_fraction,
        _overhead_samples,
    ),
    Objective(
        names.SLO_COORDINATION_FRACTION,
        "coordination fraction of take wall",
        "frac",
        knobs.get_slo_coordination_fraction,
        _coordination_samples,
    ),
)


def _window_burn(
    bad_flags: Sequence[bool], window: int, threshold: float, budget: float
) -> Optional[Dict[str, Any]]:
    """Burn over the newest ``window`` samples. None when the window is
    disabled (<= 0); an empty stream reports zero burn rather than
    firing on no evidence."""
    if window <= 0:
        return None
    tail = list(bad_flags[-window:])
    bad = sum(1 for f in tail if f)
    burn = (bad / len(tail)) / budget if tail else 0.0
    return {
        "window": window,
        "samples": len(tail),
        "bad": bad,
        "burn": round(burn, 4),
        "threshold": threshold,
    }


def _window_fires(win: Optional[Dict[str, Any]]) -> bool:
    return (
        win is not None
        and win["samples"] > 0
        and win["burn"] >= win["threshold"]
    )


def evaluate(
    ledger_records: Sequence[Dict[str, Any]],
    history_records: Sequence[Dict[str, Any]] = (),
) -> List[Dict[str, Any]]:
    """Judge every declared objective against the given evidence. Pure
    over its inputs plus the knob vector — the doctor re-runs it over a
    bundle's records and gets the live run's verdicts back."""
    budget = knobs.get_slo_error_budget_fraction()
    fast_window = knobs.get_slo_fast_window()
    slow_window = knobs.get_slo_slow_window()
    fast_threshold = knobs.get_slo_fast_burn_threshold()
    slow_threshold = knobs.get_slo_slow_burn_threshold()
    out: List[Dict[str, Any]] = []
    for objective in OBJECTIVES:
        target = objective.target()
        entry: Dict[str, Any] = {
            "objective": objective.slo_id,
            "description": objective.description,
            "unit": objective.unit,
            "target": target,
            "disabled": target <= 0 or budget <= 0,
            "samples": 0,
            "last_value": None,
            "fast": None,
            "slow": None,
            "burn_rate": 0.0,
            "breaching": False,
        }
        if not entry["disabled"]:
            samples = objective.samples(ledger_records, history_records)
            bad_flags = [value > target for _, value in samples]
            fast = _window_burn(bad_flags, fast_window, fast_threshold, budget)
            slow = _window_burn(bad_flags, slow_window, slow_threshold, budget)
            entry.update(
                samples=len(samples),
                last_value=samples[-1][1] if samples else None,
                fast=fast,
                slow=slow,
                burn_rate=max(
                    fast["burn"] if fast else 0.0,
                    slow["burn"] if slow else 0.0,
                ),
                breaching=_window_fires(fast) or _window_fires(slow),
            )
        out.append(entry)
    return out


def evaluate_root(root: str) -> Optional[Dict[str, Any]]:
    """Judge the objectives over a root's (or bundle's) recorded
    evidence. None when no run ledger is reachable from ``root``."""
    from .history import history_path_for, load_history
    from .ledger import find_ledger_for, load_ledger

    ledger_file = find_ledger_for(root)
    if ledger_file is None:
        return None
    ledger_records = load_ledger(ledger_file)
    history_records: List[Dict[str, Any]] = []
    try:
        hist_path = history_path_for(root)
        if hist_path is not None and os.path.exists(hist_path):
            history_records = load_history(hist_path)
    except Exception as e:  # noqa: BLE001 - history is optional evidence
        logger.warning("slo: could not load step history at %r: %r", root, e)
    objectives = evaluate(ledger_records, history_records)
    return {
        "root": root,
        "ledger_file": ledger_file,
        "objectives": objectives,
        "breaching": [o["objective"] for o in objectives if o["breaching"]],
    }


# Edge-trigger + fleet-plane state: per (root, objective) breach flags
# and the last evaluation's max burn per root. Process-local, guarded —
# async-save commit threads and the training loop both evaluate.
_STATE_LOCK = threading.Lock()
_BREACHING: Dict[Tuple[str, str], bool] = {}
_LAST_BURN: Dict[str, float] = {}


def reset_slo_state() -> None:
    """Drop breach edges and burn caches (tests)."""
    with _STATE_LOCK:
        _BREACHING.clear()
        _LAST_BURN.clear()


def current_burn() -> Optional[float]:
    """Max burn rate across this process's evaluated roots, from the
    most recent per-step evaluation — what the fleet plane publishes as
    the ``slo_burn`` extra. None before any evaluation."""
    with _STATE_LOCK:
        if not _LAST_BURN:
            return None
        return max(_LAST_BURN.values())


def evaluate_step(root: str, step: int) -> Optional[Dict[str, Any]]:
    """The manager's rank-0 post-commit hook: re-judge, export gauges,
    post edge-triggered breach events, and capture one incident bundle
    per evaluation that saw a fresh breach. Best-effort: never raises
    into the commit path."""
    from . import metrics
    from .ledger import post_event

    result = evaluate_root(root)
    if result is None:
        return None
    registry = metrics()
    root_key = os.path.abspath(root)
    fresh: List[str] = []
    max_burn = 0.0
    with _STATE_LOCK:
        for obj in result["objectives"]:
            if obj["disabled"]:
                _BREACHING.pop((root_key, obj["objective"]), None)
                continue
            registry.gauge_set(
                names.OBJECTIVE_BURN_RATE,
                obj["burn_rate"],
                objective=obj["objective"],
            )
            max_burn = max(max_burn, obj["burn_rate"])
            key = (root_key, obj["objective"])
            was_breaching = _BREACHING.get(key, False)
            if obj["breaching"] and not was_breaching:
                fresh.append(obj["objective"])
            _BREACHING[key] = obj["breaching"]
        _LAST_BURN[root_key] = max_burn
    for slo_id in fresh:
        obj = next(
            o for o in result["objectives"] if o["objective"] == slo_id
        )
        fast = obj["fast"] or {}
        slow = obj["slow"] or {}
        post_event(
            root,
            names.EVENT_SLO_BREACH,
            step=step,
            objective=slo_id,
            target=obj["target"],
            last_value=obj["last_value"],
            fast_burn=fast.get("burn"),
            fast_window=fast.get("window"),
            fast_bad=fast.get("bad"),
            slow_burn=slow.get("burn"),
            slow_window=slow.get("window"),
            slow_bad=slow.get("bad"),
        )
        registry.counter_inc(
            names.OBJECTIVE_BREACHES_TOTAL, objective=slo_id
        )
        logger.warning(
            "slo: objective %r breached at step %d (burn %.2f, target %s%s)",
            slo_id,
            step,
            obj["burn_rate"],
            obj["target"],
            obj["unit"],
        )
    if fresh:
        from . import bundle

        bundle.capture_bundle(
            root,
            trigger="slo-breach",
            reason=", ".join(fresh),
            step=step,
        )
    return result


def render(result: Dict[str, Any]) -> str:
    lines = [
        f"slo: {result['root']}",
        f"  ledger: {result['ledger_file']}",
    ]
    for obj in result["objectives"]:
        if obj["disabled"]:
            status = "disabled"
        elif obj["breaching"]:
            status = "BURNING"
        else:
            status = "ok"
        detail = ""
        if not obj["disabled"]:
            windows = []
            for label in ("fast", "slow"):
                win = obj[label]
                if win is not None:
                    windows.append(
                        f"{label} {win['bad']}/{win['samples']} "
                        f"burn {win['burn']:.2f}"
                    )
            detail = (
                f" target {obj['target']}{obj['unit']}"
                f" samples {obj['samples']}"
                + (" " + ", ".join(windows) if windows else "")
            )
        lines.append(f"  {obj['objective']:<24} {status:<8}{detail}")
    if result["breaching"]:
        lines.append(f"  breaching: {', '.join(result['breaching'])}")
    return "\n".join(lines)


def main(argv: Optional[Iterable[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="torchsnapshot_tpu.telemetry slo",
        description=(
            "Judge the declared checkpoint SLOs over a snapshot root's "
            "(or incident bundle's) run ledger and step history."
        ),
    )
    parser.add_argument(
        "root", help="snapshot root, manager root, or bundle directory"
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(list(argv) if argv is not None else None)
    result = evaluate_root(args.root)
    if result is None:
        print(f"no run ledger found at {args.root}")
        return 1
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        print(render(result))
    return 2 if result["breaching"] else 0
