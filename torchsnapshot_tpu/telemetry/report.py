"""SnapshotReport: one JSON-serializable record per checkpoint operation.

Every ``Snapshot.take`` / ``async_take`` / ``restore`` /
``async_restore`` and every tiered mirror job produces one of these.
The record is assembled from two sources:

- the **pipeline telemetry** the scheduler hands back per run (per-phase
  wall-clock durations, bytes/blob counts, memory-budget wait time, peak
  staged bytes) — exact for the operation;
- **registry counter deltas** over the operation's window (per-plugin
  byte/op counts, retry/recover attempts) — process-global, so
  concurrent work (e.g. a mirror draining during the next take) lands
  in the same window; the exact scheduler numbers are authoritative
  where they overlap.

Cross-rank: each rank builds its own report; rank 0 gathers the per-rank
dicts over ``dist_store.Store.gather`` and attaches min/median/max and
the straggler rank per phase (``aggregate_across_ranks``), which is what
FastPersist-style stall hunting actually needs — a single wall-clock
number per phase cannot show one slow rank.
"""

from __future__ import annotations

import dataclasses
import json
import statistics
import time
from typing import Any, Dict, List, Optional

from . import names
from .registry import parse_series_key

SCHEMA_VERSION = 1

# Registry counter names folded into the report's per-plugin table.
_PLUGIN_COUNTERS = {
    names.STORAGE_WRITE_BYTES_TOTAL: "write_bytes",
    names.STORAGE_WRITE_OPS_TOTAL: "write_ops",
    names.STORAGE_READ_BYTES_TOTAL: "read_bytes",
    names.STORAGE_READ_OPS_TOTAL: "read_ops",
}
# ...and into the retry table (summed across scopes/labels).
_RETRY_COUNTERS = {
    names.STORAGE_RETRY_ATTEMPTS_TOTAL: "attempts",
    names.STORAGE_RETRY_BACKOFF_SECONDS_TOTAL: "backoff_s",
    names.STORAGE_RETRIES_EXHAUSTED_TOTAL: "exhausted",
    names.GCS_RECOVER_ATTEMPTS_TOTAL: "gcs_recover_attempts",
}
# ...and into the coordination split (summed across op/phase/impl
# labels): what the op spent on cross-rank coordination — store wire
# round trips, barrier arrive/depart waits, the fan-out exchange, and
# endpoint resolution. The ``coordination-bound`` doctor rule reads
# this against the op's wall time.
_COORD_COUNTERS = {
    names.COORD_STORE_REQUESTS_TOTAL: "store_ops",
    names.COORD_STORE_SECONDS_TOTAL: "store_s",
    names.COORD_BARRIER_WAIT_SECONDS_TOTAL: "barrier_wait_s",
    names.COORD_EXCHANGE_SECONDS_TOTAL: "exchange_s",
    names.COORD_ENDPOINT_SECONDS_TOTAL: "endpoint_s",
}
# ...and into the wire split (summed across endpoint/direction labels,
# with a per-op RPC table kept separately): what the op put on actual
# sockets — frames, bytes, dials, request/reply round trips, and
# context-header degradations. Subsumed by ``coordination`` for store
# traffic but endpoint-true (peer-tier and CDN frames never touch the
# coordination counters).
_WIRE_COUNTERS = {
    names.WIRE_FRAMES_TOTAL: "frames",
    names.WIRE_BYTES_TOTAL: "bytes",
    names.WIRE_DIALS_TOTAL: "dials",
    names.WIRE_DIAL_SECONDS_TOTAL: "dial_s",
    names.WIRE_RPCS_TOTAL: "rpcs",
    names.WIRE_RPC_SECONDS_TOTAL: "rpc_s",
    names.WIRE_CONTEXT_DEGRADED_TOTAL: "context_degraded",
}


@dataclasses.dataclass
class SnapshotReport:
    """Schema (all fields JSON-serializable; see docs/observability.md):

    - ``kind``: take | async_take | restore | async_restore | mirror
    - ``phases``: phase -> seconds (pipeline wall-clock at completion)
    - ``plugins``: plugin -> {write_bytes, write_ops, read_bytes,
      read_ops} counter deltas over the operation
    - ``retries``: {attempts, backoff_s, exhausted,
      gcs_recover_attempts} deltas — always present, zero-filled
    - ``mirror``: tiered operations only — the process mirror's state at
      assembly (upload lag, queue depth); mirror-kind reports carry the
      finished job's own numbers instead
    - ``aggregated``: rank 0 only, world > 1 — per-phase
      {min, median, max, straggler (rank)} across the gathered reports
    - ``clock_offsets_s``: rank 0 only, world > 1 — each rank's
      wall-clock at gather entry minus rank 0's (rank order). Every
      rank reaches the gather within moments of the same commit
      barrier, so this approximates per-rank clock skew; the trace
      merge (telemetry/trace.py) subtracts it to align per-rank
      timelines. Includes barrier-exit jitter — see
      docs/observability.md for the caveat.
    """

    kind: str
    path: str
    rank: int = 0
    world_size: int = 1
    unix_ts: float = 0.0
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    plugins: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )
    bytes_moved: int = 0
    blobs: int = 0
    budget_wait_s: float = 0.0
    peak_staged_bytes: int = 0
    # Async takes only (None elsewhere): the training-visible span
    # (async_take return-to-caller) and the op-relative time at which
    # background staging (D2H + serialize) completed — the
    # visible / staged / committed phase split docs/async.md describes.
    visible_s: Optional[float] = None
    staged_s: Optional[float] = None
    # Device-snapshot drains only: the StagingPool geometry
    # ({capacity_bytes, slab_bytes, slabs}) that bounded this
    # pipeline's host staging — the context an operator needs to read
    # peak_staged_bytes / budget_wait_s on a pool-bounded drain.
    staging_pool: Optional[Dict[str, int]] = None
    # Restore pipelines only (None elsewhere): the read-amplification
    # triple. ``bytes_needed`` is what this rank's read plan had to fill
    # (pre-batching consuming costs); ``bytes_fetched`` is what it
    # actually pulled from the storage plugin (fan-out owners fetch each
    # unique saved shard once); ``bytes_received`` is what arrived from
    # peer owners over the coordination store instead. Fan-out restores
    # record bytes_fetched < bytes_needed on non-owner ranks; a fallback
    # restore reads its own bytes, so fetched ~= needed. The doctor's
    # ``restore-read-amplified`` rule keys off these fields.
    bytes_fetched: Optional[int] = None
    bytes_received: Optional[int] = None
    bytes_needed: Optional[int] = None
    # Peer-tier restores only (None/empty elsewhere): bytes served per
    # tier of the peer RAM -> local fast -> durable ladder
    # (``{"peer": b, "fast": b, "durable": b}``), and the degradation
    # evidence — eligible/served blob counts, transfer failures, and
    # the bytes that fell through to storage despite an eligible peer
    # copy. The ``peer-tier-degraded`` doctor rule keys off these.
    tier_split: Optional[Dict[str, int]] = None
    peer: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Self-healing restores only (None elsewhere): reads whose first
    # copy failed digest verification and were re-served from an
    # alternate tier (``{"blobs": n, "bytes": n}``; the serving tiers
    # land in ``tier_split``). The ``storage-corruption`` doctor rule
    # keys off this — a restore that healed still rode rotting media.
    degraded_reads: Optional[Dict[str, int]] = None
    # Write pipelines only (None elsewhere): bytes served per write-path
    # variant (``{"vectorized": b, "direct": b, "fused": b,
    # "buffered": b}``), as stamped by the storage plugin per write —
    # which path actually served this take, so a ``doctor --trend``
    # efficiency move can be correlated with the write-path knob flip
    # that caused it (the ``tunables`` field below carries the knobs).
    write_path: Optional[Dict[str, int]] = None
    # The *effective* tunable-knob values the operation ran under
    # (knobs.tunable_snapshot(), captured at op start): env > tuner
    # override > default, already resolved. Recorded whether or not the
    # autotuner is on — a history row / doctor --trend regression can
    # then always be correlated with the knob change that caused it.
    tunables: Optional[Dict[str, Any]] = None
    # Restores only (None elsewhere): the cold-start envelope — time
    # spent before the first storage byte moved, attributed to its
    # causes (``{"plugin_open_s": s, "event_loop_s": s,
    # "native_load_s": s}``), and the total. A first-trial restore that
    # is 10-30x slower than warm trials convicts itself here instead of
    # leaving the gap a guess (the cold_restore bench's soft spot).
    cold_start_s: Optional[float] = None
    cold_start: Optional[Dict[str, float]] = None
    # Multi-rank ops only (None when the op issued no coordination
    # traffic): the coordination split over the op's window —
    # ``{store_ops, store_s, barrier_wait_s, exchange_s, endpoint_s}``
    # registry counter deltas (process-global, like the plugin table).
    # The ``coordination-bound`` doctor rule keys off this.
    coordination: Optional[Dict[str, float]] = None
    # Ops whose window put frames on actual sockets (None otherwise):
    # the wire split — ``{frames, bytes, dials, dial_s, rpcs, rpc_s,
    # context_degraded}`` totals plus ``ops`` (per declared RPC op id:
    # {rpcs, rpc_s}). The ``wire-dial-stalled`` / ``wire-hot-endpoint``
    # doctor rules and the history's ``wire_s`` trend key off this.
    wire: Optional[Dict[str, Any]] = None
    # Blocking-chain attribution over the op's flight-recorder window
    # (telemetry/critpath.py; None when no envelope span landed in the
    # window): ``{wall_s, coverage, segments: {segment: seconds},
    # dominant, chain: [{span, segment, gated_s, blob?}]}``. The
    # segments partition the op's wall — each microsecond charged to
    # the innermost open span's path segment — so ``coverage`` sits at
    # ~1.0 and the dominant segment names the op's actual bottleneck.
    # Feeds the history's ``critpath`` rows, ``doctor --trend``'s
    # dominant-shift rule, and the ``telemetry diff`` CLI.
    critical_path: Optional[Dict[str, Any]] = None
    retries: Dict[str, float] = dataclasses.field(default_factory=dict)
    mirror: Dict[str, Any] = dataclasses.field(default_factory=dict)
    aggregated: Optional[Dict[str, Dict[str, float]]] = None
    clock_offsets_s: Optional[List[float]] = None
    error: Optional[str] = None
    schema_version: int = SCHEMA_VERSION

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "SnapshotReport":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


def merge_pipeline_telemetry(
    pipelines: List[Dict[str, Any]]
) -> Dict[str, Any]:
    """Fold several pipeline-telemetry dicts (a restore runs one read
    pipeline per stateful) into one: bytes/blobs/wait sum, per-phase
    durations sum (each pipeline's phase is its own wall-clock span),
    peak staged bytes max."""
    out: Dict[str, Any] = {
        "phases": {},
        "bytes_moved": 0,
        "blobs": 0,
        "budget_wait_s": 0.0,
        "peak_staged_bytes": 0,
    }
    for p in pipelines:
        for phase, s in p.get("phases", {}).items():
            out["phases"][phase] = round(
                out["phases"].get(phase, 0.0) + s, 3
            )
        out["bytes_moved"] += p.get("bytes_moved", 0)
        out["blobs"] += p.get("blobs", 0)
        out["budget_wait_s"] += p.get("budget_wait_s", 0.0)
        out["peak_staged_bytes"] = max(
            out["peak_staged_bytes"], p.get("peak_staged_bytes", 0)
        )
        # Read-amplification accounting (read pipelines only): present
        # in the fold exactly when some pipeline carried it.
        for key in ("bytes_fetched", "bytes_received", "bytes_needed"):
            if key in p:
                out[key] = out.get(key, 0) + int(p[key])
        # Write-path variant split (write pipelines only): per-variant
        # byte sums fold across pipelines.
        if p.get("write_path"):
            wp = out.setdefault("write_path", {})
            for variant, nbytes in p["write_path"].items():
                wp[variant] = wp.get(variant, 0) + int(nbytes)
        # Self-healing accounting (read pipelines with corruption
        # reroutes only): per-tier rerouted bytes and the blob/byte
        # summary both sum across pipelines.
        if p.get("tier_split"):
            ts = out.setdefault("tier_split", {})
            for tier, nbytes in p["tier_split"].items():
                ts[tier] = ts.get(tier, 0) + int(nbytes)
        if p.get("degraded_reads"):
            dr = out.setdefault("degraded_reads", {})
            for key, n in p["degraded_reads"].items():
                dr[key] = dr.get(key, 0) + int(n)
    out["budget_wait_s"] = round(out["budget_wait_s"], 6)
    return out


def plugins_from_deltas(
    deltas: Dict[str, float]
) -> Dict[str, Dict[str, float]]:
    """Per-plugin table from flattened registry counter deltas."""
    out: Dict[str, Dict[str, float]] = {}
    for series, value in deltas.items():
        name, labels = parse_series_key(series)
        field = _PLUGIN_COUNTERS.get(name)
        if field is None:
            continue
        plugin = labels.get("plugin", "unknown")
        out.setdefault(plugin, {})[field] = value
    return out


def coordination_from_deltas(
    deltas: Dict[str, float]
) -> Optional[Dict[str, float]]:
    """Coordination split from counter deltas, summed across labels
    (op/phase/impl); None when the window saw no coordination traffic
    at all (single-process ops stay schema-light)."""
    out = {field: 0.0 for field in _COORD_COUNTERS.values()}
    seen = False
    for series, value in deltas.items():
        name, _ = parse_series_key(series)
        field = _COORD_COUNTERS.get(name)
        if field is not None:
            out[field] += value
            seen = True
    if not seen:
        return None
    return {k: round(v, 6) for k, v in out.items()}


def wire_from_deltas(deltas: Dict[str, float]) -> Optional[Dict[str, Any]]:
    """Wire split from counter deltas: scalar totals summed across
    endpoint/direction/outcome labels, plus a per-op RPC table keyed by
    the declared ``RPC_*`` op ids; None when the window put nothing on
    the wire (single-process ops stay schema-light)."""
    out = {field: 0.0 for field in _WIRE_COUNTERS.values()}
    ops: Dict[str, Dict[str, float]] = {}
    seen = False
    for series, value in deltas.items():
        name, labels = parse_series_key(series)
        field = _WIRE_COUNTERS.get(name)
        if field is None:
            continue
        out[field] += value
        seen = True
        if name in (names.WIRE_RPCS_TOTAL, names.WIRE_RPC_SECONDS_TOTAL):
            op = labels.get("op", "?")
            table = ops.setdefault(op, {"rpcs": 0.0, "rpc_s": 0.0})
            key = "rpcs" if name == names.WIRE_RPCS_TOTAL else "rpc_s"
            table[key] += value
    if not seen:
        return None
    result: Dict[str, Any] = {k: round(v, 6) for k, v in out.items()}
    if ops:
        result["ops"] = {
            op: {k: round(v, 6) for k, v in t.items()}
            for op, t in sorted(ops.items())
        }
    return result


def retries_from_deltas(deltas: Dict[str, float]) -> Dict[str, float]:
    """Retry table from counter deltas; every key present (zero-filled)
    so report consumers never need existence checks."""
    out = {field: 0.0 for field in _RETRY_COUNTERS.values()}
    for series, value in deltas.items():
        name, _ = parse_series_key(series)
        field = _RETRY_COUNTERS.get(name)
        if field is not None:
            out[field] += value
    return out


def build_report(
    kind: str,
    path: str,
    rank: int,
    world_size: int,
    pipeline: Optional[Dict[str, Any]],
    counter_deltas: Dict[str, float],
    mirror: Optional[Dict[str, Any]] = None,
    error: Optional[str] = None,
    tunables: Optional[Dict[str, Any]] = None,
) -> SnapshotReport:
    pipeline = pipeline or {}
    return SnapshotReport(
        kind=kind,
        path=path,
        rank=rank,
        world_size=world_size,
        unix_ts=time.time(),
        phases=dict(pipeline.get("phases", {})),
        plugins=plugins_from_deltas(counter_deltas),
        bytes_moved=int(pipeline.get("bytes_moved", 0)),
        blobs=int(pipeline.get("blobs", 0)),
        budget_wait_s=float(pipeline.get("budget_wait_s", 0.0)),
        peak_staged_bytes=int(pipeline.get("peak_staged_bytes", 0)),
        visible_s=(
            float(pipeline["visible_s"])
            if pipeline.get("visible_s") is not None
            else None
        ),
        staged_s=(
            float(pipeline["staged_s"])
            if pipeline.get("staged_s") is not None
            else None
        ),
        staging_pool=(
            dict(pipeline["staging_pool"])
            if pipeline.get("staging_pool")
            else None
        ),
        bytes_fetched=(
            int(pipeline["bytes_fetched"])
            if pipeline.get("bytes_fetched") is not None
            else None
        ),
        bytes_received=(
            int(pipeline["bytes_received"])
            if pipeline.get("bytes_received") is not None
            else None
        ),
        bytes_needed=(
            int(pipeline["bytes_needed"])
            if pipeline.get("bytes_needed") is not None
            else None
        ),
        tier_split=(
            {k: int(v) for k, v in pipeline["tier_split"].items()}
            if pipeline.get("tier_split")
            else None
        ),
        write_path=(
            {k: int(v) for k, v in pipeline["write_path"].items()}
            if pipeline.get("write_path")
            else None
        ),
        peer=dict(pipeline.get("peer") or {}),
        degraded_reads=(
            {k: int(v) for k, v in pipeline["degraded_reads"].items()}
            if pipeline.get("degraded_reads")
            else None
        ),
        cold_start_s=(
            float(pipeline["cold_start_s"])
            if pipeline.get("cold_start_s") is not None
            else None
        ),
        cold_start=(
            {k: round(float(v), 6) for k, v in pipeline["cold_start"].items()}
            if pipeline.get("cold_start")
            else None
        ),
        tunables=dict(tunables) if tunables is not None else None,
        coordination=coordination_from_deltas(counter_deltas),
        wire=wire_from_deltas(counter_deltas),
        retries=retries_from_deltas(counter_deltas),
        mirror=dict(mirror or {}),
        error=error,
    )


def clock_offsets_from_gather(
    rank_reports: List[Dict[str, Any]]
) -> Optional[List[float]]:
    """Per-rank clock offsets against rank 0 (rank order), from the
    ``gather_unix_ts`` each rank stamps into its gathered report dict
    moments after the shared commit barrier. None when the stamps are
    missing (older-schema peers). A rank with no stamp reports 0.0."""
    if not rank_reports:
        return None
    base = rank_reports[0].get("gather_unix_ts")
    if base is None:
        return None
    out: List[float] = []
    for r in rank_reports:
        ts = r.get("gather_unix_ts")
        out.append(round(float(ts) - float(base), 6) if ts is not None else 0.0)
    return out


def aggregate_across_ranks(
    rank_reports: List[Dict[str, Any]]
) -> Dict[str, Dict[str, float]]:
    """Per-phase min/median/max/straggler across gathered report dicts
    (rank order), plus the same spread for total bytes and budget wait.
    The straggler is the *rank index* of the max — the number an
    operator pages on."""
    out: Dict[str, Dict[str, float]] = {}

    def spread(metric: str, values: List[float]) -> None:
        if not values:
            return
        out[metric] = {
            "min": round(min(values), 3),
            "median": round(statistics.median(values), 3),
            "max": round(max(values), 3),
            "straggler": values.index(max(values)),
        }

    phase_names = sorted(
        {p for r in rank_reports for p in r.get("phases", {})}
    )
    for phase in phase_names:
        spread(
            f"phase_{phase}_s",
            [float(r.get("phases", {}).get(phase, 0.0)) for r in rank_reports],
        )
    spread(
        "bytes_moved", [float(r.get("bytes_moved", 0)) for r in rank_reports]
    )
    spread(
        "budget_wait_s",
        [float(r.get("budget_wait_s", 0.0)) for r in rank_reports],
    )
    # Wire fold: per-rank wire totals spread the same way, so one rank
    # paying disproportionate socket time (a hot owner, a stalled
    # dialer) surfaces as the straggler here without reading N reports.
    if any(r.get("wire") for r in rank_reports):
        for metric, field in (
            ("wire_bytes", "bytes"),
            ("wire_rpc_s", "rpc_s"),
            ("wire_dial_s", "dial_s"),
        ):
            spread(
                metric,
                [
                    float((r.get("wire") or {}).get(field, 0.0))
                    for r in rank_reports
                ],
            )
    # Critical-path fold: per-segment gated seconds spread across ranks
    # (union of segments any rank attributed), so "which rank's write
    # drain gated the step" is one straggler lookup, not N report reads.
    if any(r.get("critical_path") for r in rank_reports):
        segments = sorted(
            {
                seg
                for r in rank_reports
                for seg in (r.get("critical_path") or {}).get(
                    "segments", {}
                )
            }
        )
        for seg in segments:
            spread(
                f"critpath_{seg}_s",
                [
                    float(
                        (r.get("critical_path") or {})
                        .get("segments", {})
                        .get(seg, 0.0)
                    )
                    for r in rank_reports
                ],
            )
    return out
