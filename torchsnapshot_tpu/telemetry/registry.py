"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Design constraints, in order:

- **Thread-safe and asyncio-safe.** Every mutation is a few dict ops
  under one ``threading.Lock`` — no awaits, no I/O, callable from the
  scheduler's event loop, the mirror worker thread, and the async-take
  commit thread alike.
- **Near-zero cost when no sink is attached.** Recording is always on
  (a lock + dict update per observation, ~100 ns); the *sinks* — the
  JSONL event log and the Prometheus text file (sink.py) — only run
  when explicitly enabled via knobs. There is no per-observation
  callback machinery to pay for.
- **Stable exposition.** Series are keyed ``name{label="value",...}``
  with sorted labels — the Prometheus text convention — so counter
  snapshots, deltas, and the exposition writer all agree on identity.

The registry also hosts the machine-readable *phase-timing channel*
that predates it (``scheduler._LAST_PHASE_S``): ``record_phase_timing``
keeps last-writer-wins per-phase wall-clock numbers that
``scheduler.last_phase_timings()`` still serves as a compatibility shim.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# Wall-clock buckets spanning sub-millisecond CRCs to multi-minute
# durable drains; +Inf is implicit (the overflow bucket).
DEFAULT_SECONDS_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 2.5, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_LabelItems = Tuple[Tuple[str, str], ...]
_SeriesKey = Tuple[str, _LabelItems]


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Flattened series identity, Prometheus-style:
    ``name`` or ``name{k="v",...}`` with labels sorted by key."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_series_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`series_key` (reports parse counter deltas back
    into per-plugin tables with this)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    for item in rest.rstrip("}").split(","):
        if not item:
            continue
        k, _, v = item.partition("=")
        labels[k] = v.strip('"')
    return name, labels


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]) -> None:
        self.buckets: Tuple[float, ...] = tuple(buckets)
        self.counts: List[int] = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.sum += value
        self.count += 1


class MetricsRegistry:
    """One process's metrics. Use the module-level singleton via
    ``telemetry.metrics()``; direct construction is for tests."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[_SeriesKey, float] = {}
        self._gauges: Dict[_SeriesKey, float] = {}
        self._histograms: Dict[_SeriesKey, _Histogram] = {}
        self._last_phase_s: Dict[str, float] = {}

    # -- recording -------------------------------------------------------

    @staticmethod
    def _key(name: str, labels: Dict[str, str]) -> _SeriesKey:
        return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))

    def counter_inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge_set(self, name: str, value: float, **labels: str) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._gauges[key] = float(value)

    def histogram_observe(
        self,
        name: str,
        value: float,
        buckets: Optional[Sequence[float]] = None,
        **labels: str,
    ) -> None:
        key = self._key(name, labels)
        with self._lock:
            hist = self._histograms.get(key)
            if hist is None:
                hist = _Histogram(buckets or DEFAULT_SECONDS_BUCKETS)
                self._histograms[key] = hist
            hist.observe(value)

    # -- phase-timing channel (compatibility with scheduler._LAST_PHASE_S)

    def record_phase_timing(self, phase: str, elapsed_s: float) -> None:
        with self._lock:
            self._last_phase_s[phase] = round(elapsed_s, 3)

    def last_phase_timings(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._last_phase_s)

    def reset_phase_timings(self) -> None:
        with self._lock:
            self._last_phase_s.clear()

    # -- reading ---------------------------------------------------------

    def counters_snapshot(self) -> Dict[str, float]:
        """Flattened ``series -> value`` view of every counter; the
        baseline half of per-snapshot report deltas."""
        with self._lock:
            return {
                series_key(name, dict(labels)): v
                for (name, labels), v in self._counters.items()
            }

    def counters_delta_since(
        self, baseline: Dict[str, float]
    ) -> Dict[str, float]:
        """Counter movement since a :meth:`counters_snapshot`, zero-delta
        series dropped. Registry counters are process-global: concurrent
        work (another pipeline, the mirror) lands in the same window."""
        out: Dict[str, float] = {}
        for key, value in self.counters_snapshot().items():
            delta = value - baseline.get(key, 0.0)
            if delta:
                out[key] = delta
        return out

    def collect(self) -> Dict[str, Dict]:
        """Full dump for the exposition writer: ``{"counters": {...},
        "gauges": {...}, "histograms": {series: {"buckets": [(le,
        cumulative), ...], "sum": s, "count": n}}}``."""
        with self._lock:
            counters = {
                series_key(n, dict(l)): v
                for (n, l), v in self._counters.items()
            }
            gauges = {
                series_key(n, dict(l)): v for (n, l), v in self._gauges.items()
            }
            histograms = {}
            for (n, l), h in self._histograms.items():
                cumulative = []
                running = 0
                for le, c in zip(h.buckets, h.counts):
                    running += c
                    cumulative.append((le, running))
                cumulative.append((float("inf"), h.count))
                histograms[series_key(n, dict(l))] = {
                    "buckets": cumulative,
                    "sum": h.sum,
                    "count": h.count,
                }
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
        }

    def reset(self) -> None:
        """Drop everything (tests simulating a fresh process)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._last_phase_s.clear()
