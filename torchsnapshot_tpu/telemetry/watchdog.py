"""Stall watchdog over the flight recorder's open spans.

BENCH_r05 showed takes silently stalling for ~100 s with nothing
attributing the wall time. The watchdog turns such stalls into
artifacts: a daemon thread periodically snapshots the recorder's open
spans, and when some span has been open longer than the knob-set
deadline (``TORCHSNAPSHOT_TPU_WATCHDOG_SECONDS``, default 60 s; <= 0
disables — the test conftest sets 0 so the fast suite never pays for
it) AND the recorder has gone that long without recording ANY event —
i.e. work is wedged, not merely long (a healthy multi-minute take
completes per-blob spans continuously and never trips this) — it

- emits a ``watchdog:stall`` instant event into the recorder (so the
  stall lands on the exported timeline, inside the very trace that
  shows the hung span),
- logs the full open-span tree plus faulthandler-style stacks of every
  live thread (where exactly each thread is wedged),
- increments the ``watchdog_stalls_total`` counter.

Firing is **edge-triggered per stall episode**: the first scan that
observes the stalled-and-idle condition fires once; while the same
stall persists, subsequent scans stay quiet; once progress resumes (or
nothing over-deadline remains open) the trigger re-arms. A single hung
write therefore bumps the counter exactly once regardless of how many
enclosing spans (take -> pipeline -> storage) crossed the deadline
with it, and a later, distinct hang — even inside the same take —
fires again.

The thread starts lazily on the first recorded span (and only when the
deadline knob is positive at that moment); it re-reads the knob every
scan, so test overrides apply to a live thread.
"""

from __future__ import annotations

import logging
import sys
import threading
import traceback
from typing import TYPE_CHECKING, Dict, List, Optional

from .. import knobs
from . import names

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .trace import SpanRecorder

logger: logging.Logger = logging.getLogger(__name__)

_MIN_SCAN_PERIOD_S = 0.05
# A scan is a lock + a snapshot of the (small) open-span table, so even
# a 60 s deadline scans at 1 Hz: stalls are detected within deadline+1s,
# and a knob override (tests shrinking the deadline on a live thread)
# takes effect within a second rather than a deadline/4 sleep later.
_MAX_SCAN_PERIOD_S = 1.0
_IDLE_SCAN_PERIOD_S = 1.0


def _thread_stacks() -> str:
    """Faulthandler-style dump of every live thread's Python stack
    (minus the watchdog's own)."""
    names_by_ident = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    chunks: List[str] = []
    for ident, frame in sys._current_frames().items():
        if ident == me:
            continue
        label = names_by_ident.get(ident, "?")
        stack = "".join(traceback.format_stack(frame))
        chunks.append(f"Thread {label} (ident {ident}):\n{stack}")
    return "\n".join(chunks)


def _progress_rows() -> List[str]:
    """Compact per-op live-progress lines for the stall instant/log:
    how far each active operation got when the process wedged."""
    from .progress import current_progress

    rows: List[str] = []
    try:
        for p in current_progress()[:8]:
            rows.append(
                f"{p['kind']} rank{p['rank']} {p['phase']}: "
                f"{p['written_bytes']}/{p['planned_bytes']}B "
                f"items {p['items_done']}/{p['planned_items']} "
                f"(inflight {p['items_inflight']}, "
                f"budget_wait {p['budget_wait_s']}s)"
            )
    except Exception as e:  # noqa: BLE001 - the stall report must land
        rows.append(f"(progress unavailable: {e!r})")
    return rows


def _span_tree(open_spans: List[Dict]) -> str:
    """Open spans grouped per track, indented by begin order — the
    'what is the process inside right now' view."""
    by_track: Dict[str, List[Dict]] = {}
    for span in open_spans:
        by_track.setdefault(span["thread"], []).append(span)
    lines: List[str] = []
    for track in sorted(by_track):
        lines.append(f"  track {track}:")
        spans = sorted(by_track[track], key=lambda s: -s["age_s"])
        for depth, span in enumerate(spans):
            args = span.get("args") or {}
            arg_str = (
                " " + ", ".join(f"{k}={v}" for k, v in sorted(args.items()))
                if args
                else ""
            )
            lines.append(
                f"  {'  ' * (depth + 1)}{span['name']} "
                f"(open {span['age_s']}s{arg_str})"
            )
    return "\n".join(lines)


class StallWatchdog:
    """One scanning thread per process; see the module docstring."""

    def __init__(self, recorder: "SpanRecorder") -> None:
        self._recorder = recorder
        self._stop = threading.Event()
        self._in_stall = False
        self._thread = threading.Thread(
            target=self._run, name="ts-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            deadline = knobs.get_watchdog_deadline_seconds()
            if deadline > 0:
                period = min(
                    _MAX_SCAN_PERIOD_S,
                    max(_MIN_SCAN_PERIOD_S, deadline / 4.0),
                )
                try:
                    self._scan(deadline)
                except Exception as e:  # noqa: BLE001 - must not die
                    logger.warning("watchdog scan failed: %r", e)
            else:
                # Disabled: re-arm so a later enable sees fresh state.
                self._in_stall = False
                period = _IDLE_SCAN_PERIOD_S
            if self._stop.wait(period):
                return

    def _scan(self, deadline_s: float) -> None:
        # A stall is spans stuck open with NO forward progress: an
        # envelope span (snapshot:take) legitimately stays open for
        # minutes while writes complete underneath it, and the recorder's
        # activity clock ticks on every one of those completions. Both
        # conditions must exceed the deadline to fire.
        idle_s = self._recorder.idle_seconds()
        open_spans = self._recorder.open_spans()
        stalled = [s for s in open_spans if s["age_s"] > deadline_s]
        if not stalled or idle_s <= deadline_s:
            # Progress resumed (or nothing is open): the episode is
            # over and a later, distinct stall fires again.
            self._in_stall = False
            return
        if self._in_stall:
            return  # same episode: already fired
        self._in_stall = True
        for s in stalled:
            self._recorder.flag_stalled(s["token"])
        # Attribute the stall to the deepest (youngest) over-deadline
        # span: that's where the wall time is actually going.
        culprit = min(stalled, key=lambda s: s["age_s"])
        tree = _span_tree(open_spans)
        # Live-progress snapshot of every active op: the stall report
        # says how FAR each op got (bytes written vs planned, in-flight
        # items), not just which spans are open.
        progress_rows = _progress_rows()
        # The critical-path prefix at stall time: the culprit's track's
        # open spans oldest -> youngest — the chain of frames gating the
        # op RIGHT NOW, ending in the culprit. Paired with
        # critpath.segment_for it names the path segment the stall is
        # charged to, so a frozen op reads the same way in the stall
        # instant as in a post-hoc ``critical_path`` report.
        from .critpath import segment_for

        track = [
            s for s in open_spans if s["tid"] == culprit["tid"]
        ]
        critical_prefix = [
            f"{s['name']}@{s['age_s']}s" for s in track[:16]
        ]
        # One black box per stall episode (``_in_stall`` above IS the
        # episode edge): freeze the evidence while the stall is live,
        # before progress resumes and overwrites it. The watchdog has
        # no root of its own — it captures for the first root this
        # process opened a run ledger at; best-effort + rate-limited.
        bundle_path = ""
        try:
            from . import bundle as bundle_mod

            capture_root = bundle_mod.default_capture_root()
            if capture_root is not None:
                bundle_path = (
                    bundle_mod.capture_bundle(
                        capture_root,
                        trigger="watchdog-stall",
                        reason=(
                            f"span {culprit['name']} open "
                            f"{culprit['age_s']}s"
                        ),
                    )
                    or ""
                )
        except Exception as e:  # noqa: BLE001 - capture must not kill the scan
            logger.warning("watchdog: bundle capture failed: %r", e)
        # count_as_progress=False: the stall marker itself must not
        # reset the idle clock and make the stall look resolved.
        self._recorder.instant(
            names.INSTANT_WATCHDOG_STALL,
            count_as_progress=False,
            span=culprit["name"],
            age_s=culprit["age_s"],
            idle_s=round(idle_s, 3),
            thread=culprit["thread"],
            deadline_s=deadline_s,
            critical_path=critical_prefix,
            gating_segment=segment_for(culprit["name"]),
            open_spans=[
                f"{s['name']}@{s['age_s']}s" for s in open_spans[:16]
            ],
            progress=progress_rows,
            bundle=bundle_path,
        )
        from . import metrics

        metrics().counter_inc(names.WATCHDOG_STALLS_TOTAL)
        logger.error(
            "watchdog: span %r open for %.1fs with no recorder activity "
            "for %.1fs (deadline %.1fs); gating segment %s, critical "
            "path %s; incident bundle %s; open-span tree:\n%s\n"
            "op progress:\n%s\nthread stacks:\n%s",
            culprit["name"],
            culprit["age_s"],
            idle_s,
            deadline_s,
            segment_for(culprit["name"]),
            " -> ".join(critical_prefix) or "(none)",
            bundle_path or "(not captured)",
            tree,
            "\n".join(f"  {row}" for row in progress_rows) or "  (none)",
            _thread_stacks(),
        )


_WATCHDOG: Optional[StallWatchdog] = None
_WATCHDOG_LOCK = threading.Lock()


def ensure_started(recorder: "SpanRecorder") -> None:
    """Start the watchdog once, lazily, from the recorder's span path.
    A non-positive deadline knob keeps it unstarted (no thread at all
    in the default test environment)."""
    global _WATCHDOG
    if _WATCHDOG is not None:
        return
    if knobs.get_watchdog_deadline_seconds() <= 0:
        return
    with _WATCHDOG_LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = StallWatchdog(recorder)


def reset_watchdog() -> None:
    """Stop and discard the process watchdog (tests)."""
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        watchdog, _WATCHDOG = _WATCHDOG, None
    if watchdog is not None:
        watchdog.stop()
