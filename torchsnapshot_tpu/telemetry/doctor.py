"""The checkpoint doctor: rule-based diagnosis over checkpoint telemetry.

Until now the only consumer that *interpreted* telemetry (rather than
rendering it) was ~150 lines of private heuristics inside ``bench.py``
— no production caller could ask "why was that take slow?". The doctor
is that shared diagnosis layer: a declared registry of rules, each
consuming a completed (or live) operation's artifacts — SnapshotReport
JSONL, merged trace spans, progress heartbeats, mirror state, fsck
results — and emitting ranked, evidence-cited :class:`Verdict`\\ s.

Every verdict id is declared exactly once in ``telemetry/names.py``
(``RULE_`` constants, kebab-case); snaplint's ``doctor-rule-ids`` rule
fails the lane on a literal id at a ``doctor_rule``/``Verdict`` emit
site, so the id namespace stays stable enough for alerting to key off.

Entry points:

- ``python -m torchsnapshot_tpu.telemetry doctor <snapshot>`` — diagnose
  one snapshot's recorded artifacts;
- ``... doctor --trend <manager-root>`` — flag per-step regressions
  against a rolling median ± MAD baseline (telemetry/history.py);
- library: :func:`diagnose_snapshot`, :func:`diagnose_reports`,
  :func:`diagnose_take_trial` (the bench's per-trial stall/efficiency
  epistemics — ``bench.py`` calls these so the bench and production
  agree on what "stalled" means).

Thresholds are module constants (documented in docs/observability.md);
rules cite the exact metric values that triggered them.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import names
from .. import knobs
from .history import detect_trend_regressions

logger: logging.Logger = logging.getLogger(__name__)

# -- thresholds (each rule cites the one it used) ---------------------------

# d2h-bound: staging consumed at least this fraction of the take wall.
D2H_BOUND_STAGING_FRAC = 0.7
# storage-tier-slow: the post-staging write drain is at least this
# multiple of staging AND at least this many seconds.
STORAGE_SLOW_DRAIN_FACTOR = 2.0
STORAGE_SLOW_MIN_S = 0.25
# budget-starved: cumulative budget wait at least this fraction of the
# pipeline wall clock.
BUDGET_STARVED_WAIT_FRAC = 0.25
# straggler-rank: a rank's phase time at least this multiple of the
# cross-rank median AND at least this many seconds beyond it.
STRAGGLER_FACTOR = 2.0
STRAGGLER_MIN_DELTA_S = 1.0
# mirror-lagging: durability lag beyond this, or this many snapshots
# queued behind the mirror.
MIRROR_LAG_S = 60.0
MIRROR_QUEUE_DEPTH = 2
# write-tail-stall: one storage-write span at least this fraction of
# the op's longest span AND at least this many ms.
TAIL_SPAN_FRAC = 0.5
TAIL_SPAN_MIN_MS = 1000.0
# retry-storm: at least this many retry attempts inside one op window.
RETRY_STORM_ATTEMPTS = 3
# interrupted-take: a non-terminal heartbeat only counts as a crash
# once it is stale — this many missed writer intervals (with an
# absolute floor, below) — so diagnosing a snapshot DURING a healthy
# take never raises a false critical.
INTERRUPTED_STALE_INTERVALS = 10.0
INTERRUPTED_STALE_MIN_S = 30.0
# restore-read-amplified: the restore's per-plugin/storage read bytes
# exceed the manifest-needed bytes by this factor.
READ_AMPLIFIED_FACTOR = 1.5
# restore-cold-start-slow: the restore's recorded ``cold_start_s``
# (event-loop spin-up + plugin open + native-module load) exceeds the
# knob'd fraction of the op wall
# (TORCHSNAPSHOT_TPU_COLD_START_BUDGET_FRACTION, <= 0 disables), over
# an absolute floor so ms-scale test restores never flag.
COLD_START_MIN_S = 1.0
# tuner-thrashing: an A -> B -> A value cycle for one tunable within
# this many trailing decision-log entries (aligned with the trend
# window: oscillation slower than the regression baseline can see is
# indistinguishable from adaptation).
TUNER_THRASH_WINDOW = 8
# goodput-degraded: the run spent at least this fraction of its
# ledger-measured wall time on checkpoint overhead (visible stalls +
# restores + lost work), over at least this much wall (short runs'
# fixed costs — a cold restore, one take — are not a trend).
GOODPUT_DEGRADED_FRAC = 0.15
GOODPUT_MIN_WALL_S = 30.0
# recovery-cost-high: one interruption's checkpoint-attributable price
# (work replayed since the last committed step + the restore that
# recovered it) reached this many seconds.
RECOVERY_COST_S = 60.0
# dedup-ineffective: over at least this many trailing CAS step-committed
# ledger records, the realized chunk-reuse ratio stayed below the floor
# while the on-device digests said at least the unchanged fraction of
# the state did not change — unchanged bytes being re-stored means the
# dedup path is broken in practice.
DEDUP_WINDOW_STEPS = 3
DEDUP_REUSE_FLOOR = 0.05
DEDUP_UNCHANGED_FRAC = 0.5
# coordination-bound: barrier waits + store round trips + the fan-out
# exchange ate at least this fraction of the op's wall (pipeline wall
# plus the coordination time itself — barriers run outside the
# pipeline's phase spans), over an absolute floor so ms-scale test ops
# polling a local store never flag.
COORD_BOUND_FRACTION = 0.3
COORD_MIN_S = 0.05
# cdn-staleness-high: the median publish-to-swap latency across the
# trailing window of cdn-swapped ledger records exceeds the budget knob
# (TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS) — the serving fleet
# is lagging the training job. A minimum sample count keeps one slow
# cold-start swap from convicting the whole fleet.
CDN_STALENESS_WINDOW = 20
CDN_STALENESS_MIN_SAMPLES = 5
# wire-dial-stalled: a fleet member's recent dial latencies cluster on
# whole seconds — the SYN-retransmit signature of a listen backlog
# overflowing (the PR-15 bug class). The quantization thresholds
# themselves (minimum latency, whole-second tolerance, sample and
# fraction floors) live in wire.py beside the dial ring they describe.
# wire-hot-endpoint: one endpoint carries at least this multiple of the
# mean per-endpoint byte volume (folded across every fleet member's
# view), with at least this many endpoints in play — a 2-endpoint
# topology always has a lopsided one — and a byte floor so test-scale
# traffic never flags.
WIRE_HOT_ENDPOINT_FACTOR = 4.0
WIRE_HOT_MIN_ENDPOINTS = 3
WIRE_HOT_MIN_BYTES = float(1 << 20)
# store-hot-shard: one coordination-store shard serves at least this
# multiple of the mean per-shard request count (summed across the
# fleet's reports), over a request floor so short runs never flag.
STORE_HOT_SHARD_FACTOR = 4.0
STORE_HOT_MIN_REQUESTS = 512.0
# Bench-trial epistemics (formerly private to bench.py):
# adjacent probes disagreeing beyond this factor = unstable link;
# achieved/bracket below this ratio on a stable bracket = in-take stall.
UNSTABLE_BRACKET_FACTOR = 1.5
STALL_EFFICIENCY_RATIO = 0.5

_SEVERITY_ORDER = {"critical": 0, "warning": 1, "info": 2}


@dataclasses.dataclass
class Verdict:
    """One diagnosis: a declared rule id, a one-line summary, and the
    metric values that triggered it (``evidence``) with the artifact
    they came from (``source``)."""

    rule: str
    summary: str
    evidence: Dict[str, Any] = dataclasses.field(default_factory=dict)
    severity: str = "warning"
    source: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def format(self) -> str:
        ev = ", ".join(f"{k}={v}" for k, v in sorted(self.evidence.items()))
        src = f" [{self.source}]" if self.source else ""
        return f"{self.severity.upper():>8} {self.rule}: {self.summary} ({ev}){src}"


class _DoctorRule:
    __slots__ = ("rule_id", "fn")

    def __init__(self, rule_id: str, fn: Callable) -> None:
        self.rule_id = rule_id
        self.fn = fn


_REPORT_RULES: List[_DoctorRule] = []
_EVIDENCE_RULES: List[_DoctorRule] = []
_FLEET_RULES: List[_DoctorRule] = []

_RULE_BUCKETS = {
    "report": _REPORT_RULES,
    "evidence": _EVIDENCE_RULES,
    "fleet": _FLEET_RULES,
}


def doctor_rule(
    rule_id: str, scope: str = "report"
) -> Callable[[Callable], Callable]:
    """Register a diagnosis rule under a declared id. ``scope`` is
    "report" (called once per SnapshotReport dict), "evidence" (called
    once with the full artifact bundle), or "fleet" (called once with
    the list of decoded ``__obs/`` metrics-plane entries). The
    decorated function returns a verdict-shaped dict
    (summary/evidence/severity/source), a list of them, or None; the
    engine stamps the registered id so no literal id ever appears at an
    emit site."""

    def deco(fn: Callable) -> Callable:
        _RULE_BUCKETS[scope].append(_DoctorRule(rule_id, fn))
        return fn

    return deco


def registered_rule_ids() -> List[str]:
    """Every registered verdict id (the rule catalogue), sorted."""
    static = [
        names.RULE_IN_TAKE_STALL,
        names.RULE_LINK_UNSTABLE,
        names.RULE_TREND_REGRESSION,
        names.RULE_CRITICAL_PATH_SHIFTED,
        names.RULE_BENCH_REGRESSION,
    ]
    return sorted(
        {
            r.rule_id
            for r in _REPORT_RULES + _EVIDENCE_RULES + _FLEET_RULES
        }
        | set(static)
    )


def _as_verdicts(rule_id: str, raw: Any) -> List[Verdict]:
    if raw is None:
        return []
    items = raw if isinstance(raw, list) else [raw]
    out = []
    for item in items:
        out.append(
            Verdict(
                rule=rule_id,
                summary=item.get("summary", rule_id),
                evidence=dict(item.get("evidence", {})),
                severity=item.get("severity", "warning"),
                source=item.get("source", ""),
            )
        )
    return out


def rank_verdicts(verdicts: List[Verdict]) -> List[Verdict]:
    """Severity first, then the rule id for a stable order."""
    return sorted(
        verdicts,
        key=lambda v: (_SEVERITY_ORDER.get(v.severity, 9), v.rule, v.source),
    )


# ---------------------------------------------------------------------------
# Evidence bundle
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Evidence:
    """Everything the doctor reads about one snapshot: recorded reports,
    trace-span summaries, progress heartbeats (live or leftover), and
    the process mirror's state (None when unavailable)."""

    path: str
    reports: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    trace_spans: Dict[str, List[Dict[str, Any]]] = dataclasses.field(
        default_factory=dict
    )
    # Trace files that exist but could not be parsed (file -> error):
    # an audit surface must list a corrupt artifact, not drop it.
    trace_unreadable: Dict[str, str] = dataclasses.field(
        default_factory=dict
    )
    trace_stalls: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    progress: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    progress_files: List[str] = dataclasses.field(default_factory=list)
    mirror_state: Optional[Dict[str, Any]] = None
    fsck_problems: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    # The write-path autotuner's decision log (.tuner-state.json at the
    # snapshot dir or its manager root), when one exists.
    tuner_state: Optional[Dict[str, Any]] = None
    tuner_state_file: str = ""
    # The run ledger (.ledger.jsonl at the manager root that owns this
    # snapshot), when one exists: the goodput rules' evidence.
    ledger_records: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    ledger_file: str = ""
    # The manager root's step-history summaries
    # (.telemetry-history.jsonl): the coordination-fraction samples the
    # SLO engine judges, gathered here so ``doctor --bundle`` re-judges
    # from a bundle's copy with the original root gone.
    history_records: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    history_file: str = ""


def gather_evidence(snapshot_path: str) -> Evidence:
    """Collect one snapshot's on-disk artifacts. Every source is
    optional — the doctor diagnoses from whatever was recorded."""
    from .stats import find_events_for
    from .trace import find_trace_files, longest_spans_from_doc
    from .progress import find_progress_files, load_progress_file

    ev = Evidence(path=snapshot_path)
    try:
        ev.reports = find_events_for(snapshot_path)
    except Exception as e:  # noqa: BLE001 - diagnose from what exists
        logger.warning("doctor: could not load events: %r", e)
    try:
        import json as _json

        for tf in find_trace_files(snapshot_path):
            # One parse per trace file: the span summary and the
            # watchdog-stall scan both read the same loaded doc.
            try:
                with open(tf, "r", encoding="utf-8") as f:
                    doc = _json.load(f)
            except (OSError, ValueError) as e:
                ev.trace_unreadable[tf] = repr(e)
                continue
            try:
                ev.trace_spans[tf] = longest_spans_from_doc(doc, 10)
            except Exception as e:  # noqa: BLE001
                ev.trace_unreadable[tf] = repr(e)
                continue
            for event in doc.get("traceEvents", []):
                if (
                    event.get("ph") == "i"
                    and event.get("name") == names.INSTANT_WATCHDOG_STALL
                ):
                    ev.trace_stalls.append(
                        {"file": tf, **(event.get("args") or {})}
                    )
    except Exception as e:  # noqa: BLE001
        logger.warning("doctor: could not scan traces: %r", e)
    try:
        for pf in find_progress_files(snapshot_path):
            ev.progress_files.append(pf)
            doc = load_progress_file(pf)
            if doc is not None:
                doc["file"] = pf
                ev.progress.append(doc)
    except Exception as e:  # noqa: BLE001
        logger.warning("doctor: could not load progress files: %r", e)
    try:
        from ..tiered.mirror import mirror_state_for_path

        ev.mirror_state = mirror_state_for_path(snapshot_path)
    except Exception:  # noqa: BLE001 - mirror state is optional evidence
        pass
    try:
        import json as _json

        from ..tuner.state import TUNER_STATE_BASENAME
        from .sink import local_fs_root

        local = local_fs_root(snapshot_path)
        if local is not None:
            # A manager step dir's tuner state lives at the manager
            # ROOT (the parent); a root diagnosed directly carries it
            # adjacent. Check both, nearest first.
            parent = os.path.dirname(os.path.abspath(local))
            for cand_dir in (local, parent):
                cand = os.path.join(cand_dir, TUNER_STATE_BASENAME)
                if os.path.exists(cand):
                    with open(cand, "r", encoding="utf-8") as f:
                        ev.tuner_state = _json.load(f)
                    ev.tuner_state_file = cand
                    break
    except Exception as e:  # noqa: BLE001
        logger.warning("doctor: could not load tuner state: %r", e)
    try:
        from .ledger import find_ledger_for, load_ledger

        lf = find_ledger_for(snapshot_path)
        if lf is not None:
            ev.ledger_records = load_ledger(lf)
            ev.ledger_file = lf
    except Exception as e:  # noqa: BLE001
        logger.warning("doctor: could not load run ledger: %r", e)
    try:
        from .history import HISTORY_BASENAME, load_history
        from .sink import local_fs_root

        local = local_fs_root(snapshot_path)
        if local is not None:
            # Same two-dir probe as the tuner state above: a step dir's
            # history lives at the manager root, a root's (or bundle's)
            # sits adjacent.
            parent = os.path.dirname(os.path.abspath(local))
            for cand_dir in (local, parent):
                cand = os.path.join(cand_dir, HISTORY_BASENAME)
                if os.path.exists(cand):
                    ev.history_records = load_history(cand)
                    ev.history_file = cand
                    break
    except Exception as e:  # noqa: BLE001
        logger.warning("doctor: could not load step history: %r", e)
    return ev


# ---------------------------------------------------------------------------
# Report-scope rules
# ---------------------------------------------------------------------------


def _take_phases(report: Dict[str, Any]):
    """(staging_s, wall_s) for a write-pipeline report; None for reads.
    Phases are completion offsets, so ``writing`` includes staging and
    the max is the pipeline's wall clock."""
    phases = report.get("phases") or {}
    if "staging" not in phases:
        return None
    staging = float(phases["staging"])
    wall = max(float(v) for v in phases.values())
    return staging, wall


@doctor_rule(names.RULE_D2H_BOUND)
def _d2h_bound(report: Dict[str, Any]):
    tp = _take_phases(report)
    if tp is None:
        return None
    staging, wall = tp
    if wall <= 0 or staging / wall < D2H_BOUND_STAGING_FRAC:
        return None
    return {
        "summary": (
            "staging (D2H + serialize) consumed most of the take; the "
            "device link, not storage, bounds this checkpoint"
        ),
        "evidence": {
            "staging_s": staging,
            "wall_s": wall,
            "staging_frac": round(staging / wall, 3),
            "threshold_frac": D2H_BOUND_STAGING_FRAC,
        },
    }


@doctor_rule(names.RULE_STORAGE_TIER_SLOW)
def _storage_tier_slow(report: Dict[str, Any]):
    tp = _take_phases(report)
    if tp is None:
        return None
    staging, wall = tp
    drain = wall - staging
    if drain < STORAGE_SLOW_MIN_S or drain < STORAGE_SLOW_DRAIN_FACTOR * max(
        staging, 1e-9
    ):
        return None
    from . import safe_rate_mb_s

    return {
        "summary": (
            "the write drain after staging dominates the take: the "
            "storage tier (or its link) is the bottleneck"
        ),
        "evidence": {
            "staging_s": staging,
            "write_drain_s": round(drain, 3),
            "wall_s": wall,
            "write_mb_s": round(
                safe_rate_mb_s(report.get("bytes_moved", 0), drain), 3
            ),
            "threshold_factor": STORAGE_SLOW_DRAIN_FACTOR,
        },
    }


@doctor_rule(names.RULE_BUDGET_STARVED)
def _budget_starved(report: Dict[str, Any]):
    phases = report.get("phases") or {}
    wall = max((float(v) for v in phases.values()), default=0.0)
    wait = float(report.get("budget_wait_s", 0.0))
    if wall <= 0 or wait / wall < BUDGET_STARVED_WAIT_FRAC:
        return None
    return {
        "summary": (
            "requests spent a large fraction of the op blocked on the "
            "host-memory budget; raise "
            "TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES or reduce "
            "concurrency"
        ),
        "evidence": {
            "budget_wait_s": wait,
            "wall_s": wall,
            "wait_frac": round(wait / wall, 3),
            "peak_staged_bytes": report.get("peak_staged_bytes", 0),
            "threshold_frac": BUDGET_STARVED_WAIT_FRAC,
        },
    }


@doctor_rule(names.RULE_STRAGGLER_RANK)
def _straggler_rank(report: Dict[str, Any]):
    agg = report.get("aggregated") or {}
    out = []
    for metric, spread in sorted(agg.items()):
        if not metric.startswith("phase_"):
            continue
        median = float(spread.get("median", 0.0))
        mx = float(spread.get("max", 0.0))
        if (
            mx >= STRAGGLER_FACTOR * max(median, 1e-9)
            and mx - median >= STRAGGLER_MIN_DELTA_S
        ):
            out.append(
                {
                    "summary": (
                        f"rank {spread.get('straggler')} is a straggler "
                        f"for {metric}: {mx}s against a {median}s median"
                    ),
                    "evidence": {
                        "metric": metric,
                        "straggler_rank": spread.get("straggler"),
                        "max_s": mx,
                        "median_s": median,
                        "threshold_factor": STRAGGLER_FACTOR,
                    },
                }
            )
    return out or None


@doctor_rule(names.RULE_MIRROR_LAGGING)
def _mirror_lagging(report: Dict[str, Any]):
    mirror = report.get("mirror") or {}
    lag = float(mirror.get("upload_lag_s", mirror.get("lag_s", 0.0)) or 0.0)
    depth = int(mirror.get("snapshots_pending", 0) or 0)
    if lag < MIRROR_LAG_S and depth < MIRROR_QUEUE_DEPTH:
        return None
    return {
        "summary": (
            "the durable-tier mirror is falling behind the take "
            "cadence; durability trails the fast-tier commit"
        ),
        "evidence": {
            "upload_lag_s": lag,
            "snapshots_pending": depth,
            "blobs_pending": mirror.get("blobs_pending", 0),
            "threshold_lag_s": MIRROR_LAG_S,
            "threshold_depth": MIRROR_QUEUE_DEPTH,
        },
    }


@doctor_rule(names.RULE_ASYNC_VISIBLE_STALL)
def _async_visible_stall(report: Dict[str, Any]):
    """An async take blocked its caller beyond the visible-time budget
    (TORCHSNAPSHOT_TPU_ASYNC_VISIBLE_BUDGET_SECONDS): with device
    snapshotting on, the visible span is plan + capture dispatch and
    must not scale with checkpoint size — a breach means staging leaked
    back into the training thread (knob off, a capture fallback paying
    D2H eagerly, or a regression in the deferral path). Cites the
    stage-span evidence: where the drain's staging actually happened
    relative to the visible span."""
    if report.get("kind") != "async_take":
        return None
    visible = report.get("visible_s")
    if visible is None:
        return None
    budget = knobs.get_async_visible_budget_seconds()
    if budget <= 0 or float(visible) <= budget:
        return None
    phases = report.get("phases") or {}
    return {
        "summary": (
            "async_take blocked training beyond the visible budget: "
            "staging ran in the caller's span instead of the "
            "background drain"
        ),
        "evidence": {
            "visible_s": float(visible),
            "budget_s": budget,
            "staged_s": report.get("staged_s"),
            "staging_s": phases.get("staging"),
            "wall_s": max((float(v) for v in phases.values()), default=0.0),
        },
        "severity": "warning",
    }


@doctor_rule(names.RULE_RESTORE_READ_AMPLIFIED)
def _restore_read_amplified(report: Dict[str, Any]):
    """The restore pulled far more bytes from storage than its read plan
    needed (``bytes_fetched`` vs ``bytes_needed`` report fields; older
    reports fall back to the per-plugin read-byte counters): whole-shard
    reads serving partial destinations, fan-out disabled in a wide
    fleet (every rank fetching every shard), or retry-driven re-reads.
    docs/restore.md documents the metric and the fan-out fix."""
    if report.get("kind") not in ("restore", "async_restore"):
        return None
    needed = report.get("bytes_needed")
    if not needed:
        return None
    if report.get("bytes_received"):
        # A fan-out restore ran: an owner rank legitimately fetches its
        # peers' windows on top of its own needs, so the per-rank
        # fetched/needed ratio pages on healthy skew. Fan-out restores
        # are judged at fleet level (total fetched / unique checkpoint
        # bytes — bench.py's fanout_restore leg records it).
        return None
    fetched = report.get("bytes_fetched")
    source = "report"
    if fetched is None:
        fetched = sum(
            float(p.get("read_bytes", 0.0))
            for p in (report.get("plugins") or {}).values()
        )
        source = "plugin-counters"
    fetched = float(fetched)
    needed = float(needed)
    if fetched <= READ_AMPLIFIED_FACTOR * needed:
        return None
    return {
        "summary": (
            "the restore read more bytes from storage than its plan "
            "needed: partial destinations are paying whole-shard (or "
            "every-rank) reads — fan-out restore / ranged reads would "
            "cut this to ~1x"
        ),
        "evidence": {
            "bytes_fetched": int(fetched),
            "bytes_needed": int(needed),
            "bytes_received": report.get("bytes_received"),
            "amplification": round(fetched / needed, 3),
            "threshold_factor": READ_AMPLIFIED_FACTOR,
            "fetched_from": source,
        },
    }


@doctor_rule(names.RULE_PEER_TIER_DEGRADED)
def _peer_tier_degraded(report: Dict[str, Any]):
    """A restore that had an eligible peer-RAM copy was (partly) served
    from storage: peer transfers failed (dead peer, timeout, checksum
    mismatch) or pushed copies were missing, so recovery paid storage
    latency the peer tier existed to avoid. Evidence cites the
    transfer-failure count and the per-tier byte split the report's
    ``tier_split``/``peer`` fields carry (docs/peer.md's degradation
    matrix names the failure modes)."""
    if report.get("kind") not in ("restore", "async_restore"):
        return None
    peer = report.get("peer") or {}
    if not peer:
        return None
    failures = int(peer.get("failures", 0))
    fallthrough = int(peer.get("fallthrough_bytes", 0))
    if failures == 0 and fallthrough == 0:
        return None
    tier_split = report.get("tier_split") or {}
    return {
        "summary": (
            "the restore had eligible peer-RAM copies but fell through "
            "to storage for part of its bytes: peer transfers failed "
            "or cached copies were missing/corrupt — recovery paid "
            "storage latency the peer tier exists to avoid"
        ),
        "evidence": {
            "peer_failures": failures,
            "fallthrough_bytes": fallthrough,
            "eligible_blobs": int(peer.get("eligible_blobs", 0)),
            "served_blobs": int(peer.get("served_blobs", 0)),
            "peer_bytes": int(tier_split.get("peer", 0)),
            "fast_bytes": int(tier_split.get("fast", 0)),
            "durable_bytes": int(tier_split.get("durable", 0)),
        },
    }


@doctor_rule(names.RULE_RESTORE_COLD_START_SLOW)
def _restore_cold_start_slow(report: Dict[str, Any]):
    """The restore spent most of its wall on process cold start —
    event-loop spin-up, storage-plugin opens, native-module load — not
    on moving bytes (the r06 soft spot: first-trial restores 10-28x
    their warm cost). A warm pool / pre-opened plugin fixes this class;
    faster storage does not. Evidence cites the recorded
    ``{event_loop_s, plugin_open_s, native_load_s}`` split."""
    if report.get("kind") not in ("restore", "async_restore"):
        return None
    budget = knobs.get_cold_start_budget_fraction()
    if budget <= 0:
        return None
    cold = report.get("cold_start_s")
    if not cold or float(cold) < COLD_START_MIN_S:
        return None
    cold = float(cold)
    phases = report.get("phases") or {}
    wall = max((float(v) for v in phases.values()), default=0.0)
    # cold_start_s is measured before the phase clocks start: the op's
    # true wall is the pipeline wall plus the cold start itself.
    wall = max(wall, 0.0) + cold
    fraction = cold / wall
    if fraction <= budget:
        return None
    split = report.get("cold_start") or {}
    return {
        "summary": (
            "the restore's wall is dominated by cold start (event-loop "
            "spin-up + plugin open + native-module load), not data "
            "movement — a warm process pool or pre-opened plugins "
            "would cut it; faster storage would not"
        ),
        "evidence": {
            "cold_start_s": round(cold, 3),
            "wall_s": round(wall, 3),
            "cold_fraction": round(fraction, 3),
            "budget_fraction": budget,
            "event_loop_s": split.get("event_loop_s"),
            "plugin_open_s": split.get("plugin_open_s"),
            "native_load_s": split.get("native_load_s"),
        },
    }


@doctor_rule(names.RULE_STORAGE_CORRUPTION)
def _storage_corruption_report(report: Dict[str, Any]):
    """A restore's bytes failed digest verification on their first tier
    and were re-served through the healing ladder (docs/chaos.md): the
    op succeeded, but a stored copy is rotting. Evidence cites the
    rerouted blob/byte counts and the tiers that finally vouched."""
    degraded = report.get("degraded_reads") or {}
    if not int(degraded.get("blobs", 0)):
        return None
    tier_split = report.get("tier_split") or {}
    return {
        "summary": (
            "stored bytes failed checksum verification and restore "
            "rerouted around the corrupt copies — the data survived, "
            "the medium did not; run fsck --repair on the root and "
            "audit the tier the reroutes avoided"
        ),
        "evidence": {
            "degraded_blobs": int(degraded.get("blobs", 0)),
            "degraded_bytes": int(degraded.get("bytes", 0)),
            **{
                f"{tier}_bytes": int(nbytes)
                for tier, nbytes in sorted(tier_split.items())
            },
        },
    }


@doctor_rule(names.RULE_STORAGE_CORRUPTION, scope="evidence")
def _storage_corruption_repairs(ev: Evidence):
    """``fsck --repair`` recorded repair-performed ledger events for
    this root: chunks/blobs were rewritten from a verifying tier, or
    quarantined when no tier verified. Quarantines are critical — a
    referenced blob is now unrestorable by design (never served
    corrupt); rewrites are the medium-rot warning."""
    repairs = [
        r
        for r in ev.ledger_records
        if r.get("event") == names.EVENT_REPAIR_PERFORMED
    ]
    if not repairs:
        return None
    rewritten = sum(int(r.get("rewritten", 0)) for r in repairs)
    quarantined = sum(int(r.get("quarantined", 0)) for r in repairs)
    return {
        "summary": (
            "fsck --repair acted on corrupt stored bytes: "
            f"{rewritten} location(s) rewritten from a verifying tier, "
            f"{quarantined} quarantined (no tier verified — restores "
            "of those blobs now fail loudly instead of serving rot)"
        ),
        "severity": "critical" if quarantined else "warning",
        "evidence": {
            "repair_events": len(repairs),
            "rewritten": rewritten,
            "quarantined": quarantined,
            "last_unix_ts": repairs[-1].get("unix_ts"),
        },
        "source": ev.ledger_file,
    }


@doctor_rule(names.RULE_RETRY_STORM)
def _retry_storm(report: Dict[str, Any]):
    retries = report.get("retries") or {}
    attempts = float(retries.get("attempts", 0.0)) + float(
        retries.get("gcs_recover_attempts", 0.0)
    )
    if attempts < RETRY_STORM_ATTEMPTS:
        return None
    return {
        "summary": (
            "storage retries clustered inside this op: the backend was "
            "throwing transient errors while the checkpoint ran"
        ),
        "evidence": {
            "attempts": attempts,
            "backoff_s": retries.get("backoff_s", 0.0),
            "exhausted": retries.get("exhausted", 0.0),
            "threshold_attempts": RETRY_STORM_ATTEMPTS,
        },
    }


@doctor_rule(names.RULE_COORDINATION_BOUND)
def _coordination_bound(report: Dict[str, Any]):
    """Barrier waits + store round trips + the fan-out exchange ate a
    large fraction of the op: the world size outgrew the coordination
    topology (docs/scaling.md names the levers — tree-barrier fanout,
    store shards, batched store ops)."""
    coord = report.get("coordination") or {}
    if not coord:
        return None
    barrier_s = float(coord.get("barrier_wait_s", 0.0))
    store_s = float(coord.get("store_s", 0.0))
    exchange_s = float(coord.get("exchange_s", 0.0))
    # The exchange's own store round trips are inside exchange_s too;
    # take the max of the two views rather than double-charging.
    coord_s = barrier_s + max(store_s, exchange_s)
    phases = report.get("phases") or {}
    pipeline_wall_s = max((float(v) for v in phases.values()), default=0.0)
    # Barriers and the exchange run OUTSIDE the pipeline's phase spans,
    # so the op wall is at least pipeline + coordination.
    wall_s = pipeline_wall_s + coord_s
    if coord_s < COORD_MIN_S or wall_s <= 0.0:
        return None
    fraction = coord_s / wall_s
    if fraction < COORD_BOUND_FRACTION:
        return None
    return {
        "summary": (
            "coordination (barrier waits + store round-trips + fan-out "
            "exchange), not data movement, dominated this op: the world "
            "size outgrew the coordination topology (see docs/scaling.md "
            "for the barrier-fanout / store-shard levers)"
        ),
        "evidence": {
            "coordination_s": round(coord_s, 3),
            "coordination_fraction": round(fraction, 3),
            "barrier_wait_s": round(barrier_s, 3),
            "store_s": round(store_s, 3),
            "exchange_s": round(exchange_s, 3),
            "store_ops": coord.get("store_ops", 0.0),
            "pipeline_wall_s": round(pipeline_wall_s, 3),
            "spans": [names.SPAN_BARRIER_ARRIVE, names.SPAN_BARRIER_DEPART],
            "threshold_fraction": COORD_BOUND_FRACTION,
            "world_size": report.get("world_size"),
        },
    }


# ---------------------------------------------------------------------------
# Evidence-scope rules
# ---------------------------------------------------------------------------


@doctor_rule(names.RULE_WRITE_TAIL_STALL, scope="evidence")
def _write_tail_stall(ev: Evidence):
    out = []
    for tf, spans in sorted(ev.trace_spans.items()):
        if not spans:
            continue
        op_ms = max(float(s.get("dur_ms", 0.0)) for s in spans)
        writes = [
            s
            for s in spans
            if s.get("name")
            in (names.SPAN_STORAGE_WRITE, names.SPAN_MIRROR_BLOB)
        ]
        if not writes:
            continue
        worst = max(writes, key=lambda s: float(s.get("dur_ms", 0.0)))
        worst_ms = float(worst.get("dur_ms", 0.0))
        if worst_ms < TAIL_SPAN_MIN_MS or worst_ms < TAIL_SPAN_FRAC * op_ms:
            continue
        out.append(
            {
                "summary": (
                    "a single blob write dominated the op: a stuck/slow "
                    "write tail, not uniform slowness"
                ),
                "evidence": {
                    "span": worst.get("name"),
                    "blob": worst.get("blob", "?"),
                    "span_ms": worst_ms,
                    "op_ms": op_ms,
                    "threshold_frac": TAIL_SPAN_FRAC,
                },
                "source": os.path.basename(tf),
            }
        )
    return out or None


@doctor_rule(names.RULE_WATCHDOG_STALLED, scope="evidence")
def _watchdog_stalled(ev: Evidence):
    out = []
    for stall in ev.trace_stalls:
        out.append(
            {
                "summary": (
                    "the stall watchdog fired during this op; the trace "
                    "names the culprit span"
                ),
                "evidence": {
                    "span": stall.get("span"),
                    "age_s": stall.get("age_s"),
                    "idle_s": stall.get("idle_s"),
                },
                "source": os.path.basename(stall.get("file", "")),
                "severity": "critical",
            }
        )
    return out or None


@doctor_rule(names.RULE_INTERRUPTED_TAKE, scope="evidence")
def _interrupted_take(ev: Evidence):
    import time as _time

    out = []
    for doc in ev.progress:
        terminal = doc.get("terminal")
        if terminal == "done":
            continue
        if terminal is None:
            # Non-terminal heartbeat: a crash leftover only once it is
            # STALE relative to the writer's own recorded cadence — a
            # fresh one is a healthy op running right now (the live
            # case the heartbeat exists to serve, not a finding). A
            # heartbeat with no timestamp at all is treated as stale
            # (nothing can refresh it).
            updated = doc.get("updated_unix_ts")
            if updated is not None:
                interval = float(doc.get("interval_s") or 0.0)
                stale_after = max(
                    INTERRUPTED_STALE_INTERVALS * interval,
                    INTERRUPTED_STALE_MIN_S,
                )
                if _time.time() - float(updated) < stale_after:
                    continue
        severity = "critical" if terminal is None else "warning"
        what = (
            "died mid-flight without settling (crash or preemption)"
            if terminal is None
            else f"ended {terminal}: {doc.get('error')}"
        )
        out.append(
            {
                "summary": (
                    f"a {doc.get('kind', '?')} on rank "
                    f"{doc.get('rank', '?')} {what}; its heartbeat shows "
                    f"how far it got"
                ),
                "evidence": {
                    "phase": doc.get("phase"),
                    "written_bytes": doc.get("written_bytes"),
                    "planned_bytes": doc.get("planned_bytes"),
                    "items_done": doc.get("items_done"),
                    "planned_items": doc.get("planned_items"),
                },
                "source": os.path.basename(doc.get("file", "")),
                "severity": severity,
            }
        )
    return out or None


@doctor_rule(names.RULE_TUNER_THRASHING, scope="evidence")
def _tuner_thrashing(ev: Evidence):
    """The autotuner's decision log shows a tunable cycling A -> B -> A
    inside the thrash window: the policy is applying and undoing the
    same move (verdict flapping, or a knob whose effect straddles the
    regression threshold) instead of converging. Evidence cites the
    concrete decision-log entries (steps, values, actions) so the
    operator can pin the oscillating tunable with an env var — env
    always wins — or widen the knob's cooldown."""
    st = ev.tuner_state
    if not st:
        return None
    decisions = list(st.get("decisions") or [])[-TUNER_THRASH_WINDOW:]
    if len(decisions) < 3:
        return None
    tunable_names = sorted(
        {name for d in decisions for name in (d.get("vector") or {})}
    )
    out = []
    for name in tunable_names:
        series = [
            (
                d.get("step"),
                (d.get("vector") or {}).get(name),
                (d.get("decision") or {}).get("action"),
            )
            for d in decisions
        ]
        # Every A -> B -> A value cycle in the window. A SINGLE cycle
        # closed by a "revert" is the revert-on-regression guard rail
        # doing its one job (and the move then cools down) — not a
        # finding; thrashing is a cycle closed by ADJUST decisions
        # (verdict flapping pushing the knob both ways), or the same
        # cycle recurring.
        cycles = []
        for i in range(len(series) - 2):
            (s0, a, _), (s1, b, act1), (s2, c, act2) = series[i : i + 3]
            if a is None or b is None or c is None:
                continue
            if a != b and b != c and a == c:
                cycles.append(
                    {"steps": [s0, s1, s2], "values": [a, b, c],
                     "actions": [act1, act2]}
                )
        flagged = [c for c in cycles if "revert" not in c["actions"]]
        if not flagged and len(cycles) >= 2:
            flagged = cycles
        if flagged:
            cyc = flagged[0]
            a, b, _ = cyc["values"]
            out.append(
                {
                    "summary": (
                        f"the autotuner is oscillating on {name}: "
                        f"{a} -> {b} -> {a} within the last "
                        f"{len(decisions)} decisions"
                    ),
                    "evidence": {
                        "tunable": name,
                        "steps": cyc["steps"],
                        "values": cyc["values"],
                        "actions": cyc["actions"],
                        "cycles_in_window": len(cycles),
                        "window": TUNER_THRASH_WINDOW,
                    },
                    "source": os.path.basename(ev.tuner_state_file),
                }
            )
    return out or None


@doctor_rule(names.RULE_DEDUP_INEFFECTIVE, scope="evidence")
def _dedup_ineffective(ev: Evidence):
    """The content-addressed store is on (step-committed records carry
    ``cas: true`` with exact per-chunk accounting) but the trailing
    window realized ~zero reuse while the on-device digests recorded
    that most of the state was unchanged between steps. When dedup
    works, a digest-unchanged byte is *always* a reused byte (an
    incremental ref, or a chunk the store already held) — so this gap
    means the path is broken in practice: the chunks dir was wiped or
    relocated between steps, serialization stopped being deterministic,
    or chunk geometry churned. Evidence cites the ledger records the
    goodput storage curve is built from."""
    cas_steps = [
        r
        for r in ev.ledger_records
        if r.get("event") == names.EVENT_STEP_COMMITTED and r.get("cas")
    ]
    window = cas_steps[-max(DEDUP_WINDOW_STEPS, 1) :]
    if len(window) < DEDUP_WINDOW_STEPS:
        return None
    total = sum(int(r.get("bytes_total", 0)) for r in window)
    reused = sum(int(r.get("bytes_reused", 0)) for r in window)
    unchanged = sum(
        int(r.get("bytes_digest_unchanged", 0)) for r in window
    )
    covered = sum(int(r.get("bytes_digest_covered", 0)) for r in window)
    if total <= 0 or covered <= 0:
        return None  # no digest evidence: cannot say the state was static
    reuse_frac = reused / total
    unchanged_frac = unchanged / covered
    if (
        reuse_frac >= DEDUP_REUSE_FLOOR
        or unchanged_frac < DEDUP_UNCHANGED_FRAC
    ):
        return None
    return {
        "summary": (
            "the content-addressed store reused almost nothing across "
            "recent steps even though the on-device digests say the "
            "state was mostly unchanged — check that the root's chunks/ "
            "directory persists between steps and that serialization "
            "is deterministic (fsck --cas audits the store)"
        ),
        "severity": "warning",
        "evidence": {
            "steps": [r.get("step") for r in window],
            "reuse_fraction": round(reuse_frac, 4),
            "digest_unchanged_fraction": round(unchanged_frac, 4),
            "bytes_total": total,
            "bytes_reused": reused,
            "window": DEDUP_WINDOW_STEPS,
            "reuse_floor": DEDUP_REUSE_FLOOR,
            "unchanged_threshold": DEDUP_UNCHANGED_FRAC,
        },
        "source": os.path.basename(ev.ledger_file),
    }


@doctor_rule(names.RULE_CDN_STALENESS_HIGH, scope="evidence")
def _cdn_staleness_high(ev: Evidence):
    """The serving fleet is lagging the training job: the median
    publish-to-swap latency over the trailing ``cdn-swapped`` ledger
    records exceeds the staleness budget knob. Evidence cites the
    publish/swap event counts and the per-subscriber staleness spread —
    a uniformly slow fleet points at the announce path or durable
    reads; a long tail points at individual subscribers (dead owner
    endpoints forcing pull-timeout durable fallbacks)."""
    swaps = [
        r
        for r in ev.ledger_records
        if r.get("event") == names.EVENT_CDN_SWAPPED
        and r.get("staleness_s") is not None
    ]
    window = swaps[-max(CDN_STALENESS_WINDOW, 1) :]
    if len(window) < CDN_STALENESS_MIN_SAMPLES:
        return None
    from .. import knobs as _knobs

    budget = _knobs.get_cdn_staleness_budget_seconds()
    if budget <= 0:
        return None
    samples = sorted(float(r["staleness_s"]) for r in window)
    median = samples[len(samples) // 2]
    if median <= budget:
        return None
    publishes = sum(
        1
        for r in ev.ledger_records
        if r.get("event") == names.EVENT_CDN_PUBLISHED
    )
    return {
        "summary": (
            "the serving fleet's median publish-to-swap staleness "
            "exceeds the budget — subscribers are applying steps late; "
            "check owner endpoint health (pull-timeout fallbacks), "
            "announce cadence, and durable-read latency"
        ),
        "evidence": {
            "median_staleness_s": round(median, 4),
            "p90_staleness_s": round(
                samples[min(len(samples) - 1, (len(samples) * 9) // 10)], 4
            ),
            "budget_s": budget,
            "swaps_observed": len(window),
            "publishes_observed": publishes,
            "subscribers": len(
                {r.get("subscriber") for r in window}
            ),
        },
        "source": os.path.basename(ev.ledger_file),
    }


@doctor_rule(names.RULE_SLO_BURNING, scope="evidence")
def _slo_burning(ev: Evidence):
    """A declared SLO objective is burning its error budget
    (telemetry/slo.py): the fast window caught a cliff or the slow
    window caught drift. One verdict per breaching objective, citing
    the per-window burn/bad-sample counts and any ``slo-breach``
    ledger events the live evaluation already posted. Re-judged from
    the gathered evidence (not the live engine's state), so a bundle's
    relocated copy reproduces the live run's verdicts exactly."""
    if not ev.ledger_records:
        return None
    from . import slo

    out = []
    for obj in slo.evaluate(ev.ledger_records, ev.history_records):
        if not obj["breaching"]:
            continue
        breach_events = sum(
            1
            for r in ev.ledger_records
            if r.get("event") == names.EVENT_SLO_BREACH
            and r.get("objective") == obj["objective"]
        )
        fast = obj["fast"] or {}
        slow = obj["slow"] or {}
        out.append(
            {
                "summary": (
                    f"SLO objective {obj['objective']!r} "
                    f"({obj['description']}) is burning its error "
                    f"budget: target {obj['target']}{obj['unit']}, "
                    f"burn rate {obj['burn_rate']:.2f}"
                ),
                "evidence": {
                    "objective": obj["objective"],
                    "target": obj["target"],
                    "unit": obj["unit"],
                    "last_value": obj["last_value"],
                    "samples": obj["samples"],
                    "fast_bad": fast.get("bad"),
                    "fast_window": fast.get("window"),
                    "fast_burn": fast.get("burn"),
                    "slow_bad": slow.get("bad"),
                    "slow_window": slow.get("window"),
                    "slow_burn": slow.get("burn"),
                    "breach_events": breach_events,
                },
                "source": os.path.basename(ev.ledger_file),
            }
        )
    return out or None


@doctor_rule(names.RULE_GOODPUT_DEGRADED, scope="evidence")
def _goodput_degraded(ev: Evidence):
    """The run ledger shows checkpointing eating more than the overhead
    budget of this run's wall time. Per-op telemetry cannot see this —
    every individual take can be within its own thresholds while the
    cadence/latency product still swallows the run; the run-level
    fraction is what decides checkpoint interval and tiering policy
    (docs/goodput.md)."""
    if not ev.ledger_records:
        return None
    from .goodput import analyze, latest_run

    run = latest_run(analyze(ev.ledger_records))
    if run is None or run["wall_s"] < GOODPUT_MIN_WALL_S:
        return None
    if run["overhead_fraction"] < GOODPUT_DEGRADED_FRAC:
        return None
    return {
        "summary": (
            "checkpointing consumed a large fraction of this run's wall "
            "time (visible stalls + restores + lost work against the "
            "run ledger); raise the save interval, move to async/tiered "
            "takes, or cut recovery cost"
        ),
        "evidence": {
            "run_id": run["run_id"],
            "overhead_fraction": run["overhead_fraction"],
            "wall_s": run["wall_s"],
            "visible_stall_s": run["visible_stall_s"],
            "restore_s": run["restore_s"],
            "lost_work_s": run["lost_work_s"],
            "steps_committed": run["steps_committed"],
            "ledger_events": len(ev.ledger_records),
            "threshold_frac": GOODPUT_DEGRADED_FRAC,
        },
        "source": os.path.basename(ev.ledger_file),
    }


@doctor_rule(names.RULE_RECOVERY_COST_HIGH, scope="evidence")
def _recovery_cost_high(ev: Evidence):
    """An interruption recorded in the run ledger cost more than the
    recovery budget: the work replayed since the last committed step
    plus the restore that recovered it. Evidence cites the ledger's
    preemption/step-committed/restore-served records — the fix is a
    tighter checkpoint interval (or peer-redundant hot checkpoints),
    not a faster individual save."""
    if not ev.ledger_records:
        return None
    from .goodput import analyze

    out = []
    for run in analyze(ev.ledger_records)["runs"]:
        for itr in run["interruptions"]:
            if itr["recovery_cost_s"] < RECOVERY_COST_S:
                continue
            where = (
                f"preemption at step {itr['preemption_step']}"
                if itr["preemption_step"] is not None
                else f"segment {itr['segment']}'s interruption"
            )
            lost_steps = (
                f" ({itr['lost_steps']} step(s) replayed)"
                if itr["lost_steps"] is not None
                else ""
            )
            out.append(
                {
                    "summary": (
                        f"{where} cost "
                        f"{itr['recovery_cost_s']:.1f}s to recover: "
                        f"{itr['lost_work_s']:.1f}s of lost work"
                        f"{lost_steps} + {itr['restore_s']:.1f}s of "
                        f"restore"
                    ),
                    "evidence": {
                        "run_id": run["run_id"],
                        "segment": itr["segment"],
                        "recovery_cost_s": itr["recovery_cost_s"],
                        "lost_work_s": itr["lost_work_s"],
                        "lost_steps": itr["lost_steps"],
                        "restore_s": itr["restore_s"],
                        "restart_gap_s": itr["restart_gap_s"],
                        "preemption_step": itr["preemption_step"],
                        "last_committed_step": itr["last_committed_step"],
                        "threshold_s": RECOVERY_COST_S,
                    },
                    "source": os.path.basename(ev.ledger_file),
                    "severity": "warning",
                }
            )
    return out or None


@doctor_rule(names.RULE_MIRROR_LAGGING, scope="evidence")
def _mirror_lagging_live(ev: Evidence):
    m = ev.mirror_state
    if m is None:
        return None
    lag = float(m.get("upload_lag_s", 0.0))
    depth = int(m.get("snapshots_pending", 0))
    if lag < MIRROR_LAG_S and depth < MIRROR_QUEUE_DEPTH:
        return None
    return {
        "summary": (
            "the live process mirror is behind right now (queue state "
            "at diagnosis time, not from a recorded report)"
        ),
        "evidence": {
            "upload_lag_s": lag,
            "snapshots_pending": depth,
            "blobs_pending": m.get("blobs_pending", 0),
            "threshold_lag_s": MIRROR_LAG_S,
            "threshold_depth": MIRROR_QUEUE_DEPTH,
        },
        "source": "live-mirror",
    }


# ---------------------------------------------------------------------------
# Fleet rules (over decoded __obs/ metrics-plane entries — wire.py)
# ---------------------------------------------------------------------------


def _fleet_source(entry: Dict[str, Any]) -> str:
    return f"{entry.get('role', '?')}/{entry.get('id', '?')}"


@doctor_rule(names.RULE_WIRE_DIAL_STALLED, scope="fleet")
def _wire_dial_stalled(entries: Sequence[Dict[str, Any]]):
    """Whole-second-quantized dial latencies on one fleet member: SYNs
    are being retransmitted because the server's listen backlog is
    overflowing — raise its ``request_queue_size`` (the PR-15
    peer-server bug class, now detectable from the live plane)."""
    from .wire import (
        DIAL_STALL_MIN_FRACTION,
        DIAL_STALL_MIN_SAMPLES,
        quantized_dial_fraction,
    )

    out = []
    for entry in entries:
        wire_summary = entry.get("wire") or {}
        dials = [float(s) for s in (wire_summary.get("dials_s") or [])]
        slow, frac = quantized_dial_fraction(dials)
        if slow < DIAL_STALL_MIN_SAMPLES or frac < DIAL_STALL_MIN_FRACTION:
            continue
        out.append(
            {
                "summary": (
                    "dial latencies quantize to whole seconds — the "
                    "SYN-retransmit signature of an overflowing listen "
                    "backlog (raise the server's request_queue_size)"
                ),
                "evidence": {
                    "slow_dials": slow,
                    "quantized_fraction": round(frac, 3),
                    "dial_p95_s": wire_summary.get("dial_p95_s"),
                    "threshold_fraction": DIAL_STALL_MIN_FRACTION,
                },
                "severity": "critical",
                "source": _fleet_source(entry),
            }
        )
    return out


@doctor_rule(names.RULE_WIRE_HOT_ENDPOINT, scope="fleet")
def _wire_hot_endpoint(entries: Sequence[Dict[str, Any]]):
    """One endpoint soaking up a disproportionate share of the fleet's
    wire bytes (every subscriber pulling from the same serving peer, a
    skewed owner map): fold per-endpoint bytes across every member's
    view and flag the outlier against the mean."""
    bytes_by_endpoint: Dict[str, float] = {}
    for entry in entries:
        endpoints = (entry.get("wire") or {}).get("endpoints") or {}
        for endpoint, fields in endpoints.items():
            bytes_by_endpoint[endpoint] = bytes_by_endpoint.get(
                endpoint, 0.0
            ) + float(fields.get("bytes", 0.0))
    if len(bytes_by_endpoint) < WIRE_HOT_MIN_ENDPOINTS:
        return None
    hot, hot_bytes = max(bytes_by_endpoint.items(), key=lambda kv: kv[1])
    mean = sum(bytes_by_endpoint.values()) / len(bytes_by_endpoint)
    if (
        hot_bytes < WIRE_HOT_MIN_BYTES
        or hot_bytes < WIRE_HOT_ENDPOINT_FACTOR * mean
    ):
        return None
    return {
        "summary": (
            "one endpoint is carrying a disproportionate share of the "
            "fleet's wire bytes (skewed owner map or a single serving "
            "peer soaking the whole subscriber fleet)"
        ),
        "evidence": {
            "endpoint": hot,
            "endpoint_mb": round(hot_bytes / 1024**2, 2),
            "fleet_mean_mb": round(mean / 1024**2, 2),
            "endpoints": len(bytes_by_endpoint),
            "threshold_factor": WIRE_HOT_ENDPOINT_FACTOR,
        },
        "source": "fleet",
    }


@doctor_rule(names.RULE_STORE_HOT_SHARD, scope="fleet")
def _store_hot_shard(entries: Sequence[Dict[str, Any]]):
    """One coordination-store shard serving far more requests than its
    siblings (a key-hashing skew or a hot prefix): fold the per-shard
    request counts every member reports and flag max-vs-mean skew."""
    requests_by_shard: Dict[str, float] = {}
    for entry in entries:
        shards = (entry.get("wire") or {}).get("store_shards") or {}
        for shard, count in shards.items():
            requests_by_shard[shard] = requests_by_shard.get(
                shard, 0.0
            ) + float(count)
    if len(requests_by_shard) < 2:
        return None
    total = sum(requests_by_shard.values())
    if total < STORE_HOT_MIN_REQUESTS:
        return None
    hot, hot_requests = max(requests_by_shard.items(), key=lambda kv: kv[1])
    mean = total / len(requests_by_shard)
    if hot_requests < STORE_HOT_SHARD_FACTOR * mean:
        return None
    return {
        "summary": (
            "one coordination-store shard is serving a disproportionate "
            "share of the fleet's requests (hot key prefix or hashing "
            "skew — rebalance the shard map)"
        ),
        "evidence": {
            "shard": hot,
            "shard_requests": round(hot_requests),
            "mean_requests": round(mean, 1),
            "shards": len(requests_by_shard),
            "threshold_factor": STORE_HOT_SHARD_FACTOR,
        },
        "source": "fleet",
    }


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def diagnose_fleet(entries: Sequence[Dict[str, Any]]) -> List[Verdict]:
    """Fleet-scope rules over the decoded ``__obs/`` metrics-plane
    entries — what ``telemetry fleet`` appends under its live table."""
    verdicts: List[Verdict] = []
    for rule in _FLEET_RULES:
        try:
            raw = rule.fn(list(entries))
        except Exception as e:  # noqa: BLE001 - a broken rule must not
            # take down the diagnosis
            logger.warning("doctor: rule %s failed: %r", rule.rule_id, e)
            continue
        verdicts.extend(_as_verdicts(rule.rule_id, raw))
    return rank_verdicts(verdicts)


def diagnose_reports(reports: Sequence[Dict[str, Any]]) -> List[Verdict]:
    """Run every report-scope rule over each report dict."""
    verdicts: List[Verdict] = []
    for report in reports:
        src = f"{report.get('kind', '?')}@rank{report.get('rank', 0)}"
        for rule in _REPORT_RULES:
            try:
                raw = rule.fn(report)
            except Exception as e:  # noqa: BLE001 - a broken rule must not
                # take down the diagnosis
                logger.warning(
                    "doctor: rule %s failed: %r", rule.rule_id, e
                )
                continue
            for v in _as_verdicts(rule.rule_id, raw):
                if not v.source:
                    v.source = src
                verdicts.append(v)
    return verdicts


def diagnose_evidence(ev: Evidence) -> List[Verdict]:
    """Report-scope rules over the recorded reports plus evidence-scope
    rules over the whole bundle, ranked most-severe first."""
    verdicts = diagnose_reports(ev.reports)
    for rule in _EVIDENCE_RULES:
        try:
            raw = rule.fn(ev)
        except Exception as e:  # noqa: BLE001
            logger.warning("doctor: rule %s failed: %r", rule.rule_id, e)
            continue
        verdicts.extend(_as_verdicts(rule.rule_id, raw))
    return rank_verdicts(verdicts)


def diagnose_snapshot(snapshot_path: str) -> List[Verdict]:
    """The library entry point ``fsck``/operators use: gather the
    snapshot's artifacts, run every rule, return ranked verdicts."""
    return diagnose_evidence(gather_evidence(snapshot_path))


def diagnose_ledger(root: str) -> List[Verdict]:
    """Run-level diagnosis from the ledger alone (the goodput rules):
    what ``doctor --trend`` appends so trend regressions speak in run
    cost, not just per-op latency. [] when no ledger exists."""
    from .ledger import find_ledger_for, load_ledger

    lf = find_ledger_for(root)
    if lf is None:
        return []
    ev = Evidence(path=root, ledger_records=load_ledger(lf), ledger_file=lf)
    verdicts: List[Verdict] = []
    for rule in _EVIDENCE_RULES:
        if rule.rule_id not in (
            names.RULE_GOODPUT_DEGRADED,
            names.RULE_RECOVERY_COST_HIGH,
        ):
            continue
        try:
            verdicts.extend(_as_verdicts(rule.rule_id, rule.fn(ev)))
        except Exception as e:  # noqa: BLE001
            logger.warning("doctor: rule %s failed: %r", rule.rule_id, e)
    return rank_verdicts(verdicts)


# ---------------------------------------------------------------------------
# Bench-trial epistemics (shared with bench.py)
# ---------------------------------------------------------------------------


def bracket_stable(probe_a: float, probe_b: float) -> bool:
    """Two temporally-adjacent link probes agree within the stability
    factor (both positive). An unstable bracket means the link itself
    moved; efficiency ratios over it carry no blame signal."""
    lo, hi = min(probe_a, probe_b), max(probe_a, probe_b)
    return lo > 0 and hi / lo <= UNSTABLE_BRACKET_FACTOR


def probes_unstable(probes: Sequence[float]) -> bool:
    """Any adjacent probe pair in the series disagrees beyond the
    stability factor — the series-level ``link_unstable`` flag."""
    return any(
        not bracket_stable(a, b)
        for a, b in zip(probes, probes[1:])
        if min(a, b) > 0
    )


def diagnose_take_trial(
    take_s: float,
    gib: float,
    probe_before_gbps: float,
    probe_after_gbps: float,
    phases: Optional[Dict[str, float]] = None,
) -> List[Verdict]:
    """Diagnose one bracketed take trial (bench.py's former private
    ``in_take_stall`` / ``link_unstable`` internals). The bracket's max
    is the tightest attainable-bandwidth estimate covering the trial's
    window; a *stable* bracket with achieved/bracket below the stall
    ratio means the slowdown happened inside the take."""
    verdicts: List[Verdict] = []
    bracket = max(probe_before_gbps, probe_after_gbps)
    achieved = gib / take_s if take_s > 0 else 0.0
    ratio = achieved / bracket if bracket > 0 else None
    stable = bracket_stable(probe_before_gbps, probe_after_gbps)
    if not stable:
        verdicts.append(
            Verdict(
                rule=names.RULE_LINK_UNSTABLE,
                summary=(
                    "the bracketing probes disagree beyond the stability "
                    "factor; the link moved during the trial window"
                ),
                evidence={
                    "probe_before_gbps": round(probe_before_gbps, 3),
                    "probe_after_gbps": round(probe_after_gbps, 3),
                    "threshold_factor": UNSTABLE_BRACKET_FACTOR,
                },
                severity="info",
            )
        )
    if stable and ratio is not None and ratio < STALL_EFFICIENCY_RATIO:
        evidence: Dict[str, Any] = {
            "take_s": round(take_s, 2),
            "achieved_gbps": round(achieved, 3),
            "bracket_gbps": round(bracket, 3),
            "ratio": round(ratio, 3),
            "threshold_ratio": STALL_EFFICIENCY_RATIO,
        }
        for phase in ("staging", "writing"):
            if phases and phases.get(phase) is not None:
                evidence[f"{phase}_done_s"] = phases[phase]
        verdicts.append(
            Verdict(
                rule=names.RULE_IN_TAKE_STALL,
                summary=(
                    "achieved throughput fell below half of a stable "
                    "attainable-bandwidth bracket: the slowdown happened "
                    "inside the take"
                ),
                evidence=evidence,
            )
        )
    return verdicts


# ---------------------------------------------------------------------------
# Trend diagnosis (history.py consumer)
# ---------------------------------------------------------------------------


def diagnose_trend(
    records: List[Dict[str, Any]], window: int = 0
) -> List[Verdict]:
    """Trend verdicts over a manager's step history (oldest first)."""
    from .history import TREND_WINDOW

    rows = detect_trend_regressions(
        records, window=window or TREND_WINDOW
    )
    verdicts = []
    for row in rows:
        step = row.get("step")
        where = f"step {step}" if step is not None else f"record {row['index']}"
        verdicts.append(
            Verdict(
                rule=names.RULE_TREND_REGRESSION,
                summary=(
                    f"{where} regressed on {row['metric']}: "
                    f"{row['value']} against a rolling baseline median "
                    f"of {row['baseline_median']}"
                ),
                evidence={
                    k: v for k, v in row.items() if k not in ("path",)
                },
                source=str(row.get("path") or ""),
            )
        )
    # Dominant-segment shifts (telemetry/critpath.py): the bottleneck
    # MOVED against the rolling window's modal dominant — a regression
    # class magnitude thresholds cannot see when the wall barely
    # changes (e.g. write drain shrank exactly as coordination grew).
    from .critpath import detect_critical_path_shifts

    for row in detect_critical_path_shifts(records, window=window):
        step = row.get("step")
        where = f"step {step}" if step is not None else f"record {row['index']}"
        verdicts.append(
            Verdict(
                rule=names.RULE_CRITICAL_PATH_SHIFTED,
                summary=(
                    f"{where} critical path shifted to "
                    f"{row['dominant']} (window dominant: "
                    f"{row['previous_dominant']})"
                ),
                evidence={
                    k: v for k, v in row.items() if k not in ("path",)
                },
                source=str(row.get("path") or ""),
            )
        )
    return rank_verdicts(verdicts)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _resolve_history_path(target: str) -> Optional[str]:
    from .history import HISTORY_BASENAME, history_path_for

    if os.path.isfile(target):
        return target
    if target.endswith(HISTORY_BASENAME):
        return target
    return history_path_for(target)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json as _json

    p = argparse.ArgumentParser(
        prog="torchsnapshot_tpu.telemetry doctor",
        description=(
            "Diagnose a snapshot's recorded telemetry (reports, traces, "
            "progress heartbeats) or a manager's step-history trend."
        ),
    )
    p.add_argument(
        "target",
        nargs="?",
        default=None,
        help="snapshot path, or (with --trend) a manager root / "
        ".telemetry-history.jsonl file",
    )
    p.add_argument(
        "--bundle",
        default=None,
        metavar="PATH",
        help="diagnose a captured incident bundle (telemetry/bundle.py) "
        "— the full offline analysis against the bundle's frozen "
        "artifacts, with the original root gone",
    )
    p.add_argument(
        "--trend",
        action="store_true",
        help="trend mode: flag per-step regressions against a rolling "
        "median +/- MAD baseline",
    )
    p.add_argument(
        "--window",
        type=int,
        default=0,
        help="trend baseline window (default: history.TREND_WINDOW)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable verdict list instead of the text report",
    )
    args = p.parse_args(list(argv) if argv is not None else None)

    if args.bundle is not None:
        from .bundle import is_bundle, load_manifest

        if not is_bundle(args.bundle):
            print(
                f"doctor: {args.bundle!r} is not an incident bundle "
                f"(no manifest.json); capture one with "
                f"`telemetry bundle <root> --capture`"
            )
            return 1
        manifest = load_manifest(args.bundle) or {}
        if not args.json:
            print(
                f"doctor bundle: {args.bundle} "
                f"(trigger {manifest.get('trigger')!r}"
                + (
                    f", reason {manifest.get('reason')!r}"
                    if manifest.get("reason")
                    else ""
                )
                + f", captured from {manifest.get('root')!r})"
            )
        # The bundle dir mimics a snapshot dir's layout, so the normal
        # gather/diagnose path below reads it unchanged.
        args.target = args.bundle
    if args.target is None:
        p.error("a target (or --bundle PATH) is required")

    if args.trend:
        from .history import HISTORY_BASENAME, load_history

        path = _resolve_history_path(args.target)
        if path is None or not os.path.exists(path):
            print(
                f"doctor: no step history found for {args.target!r} "
                f"(history records at <root>/{HISTORY_BASENAME}; "
                f"enable with TORCHSNAPSHOT_TPU_HISTORY_MAX_RECORDS > 0)"
            )
            return 1
        records = load_history(path)
        verdicts = diagnose_trend(records, window=args.window)
        # Run-level context rides along: a manager root with a ledger
        # gets the goodput verdicts appended, so a per-step regression
        # and its run-level cost appear in one report.
        verdicts = rank_verdicts(
            [*verdicts, *diagnose_ledger(args.target)]
        )
        if args.json:
            print(_json.dumps([v.to_dict() for v in verdicts], indent=1))
        else:
            print(
                f"doctor trend: {len(records)} step record(s) in {path}"
            )
            if not verdicts:
                print("no regressions against the rolling baseline")
            for v in verdicts:
                print(v.format())
        return 0 if not verdicts else 2

    verdicts = diagnose_snapshot(args.target)
    if args.json:
        print(_json.dumps([v.to_dict() for v in verdicts], indent=1))
        return 0 if not verdicts else 2
    print(f"doctor: {args.target}")
    if not verdicts:
        print(
            "no findings (nothing recorded, or everything within "
            "thresholds); record artifacts with "
            "TORCHSNAPSHOT_TPU_TELEMETRY=1 / TORCHSNAPSHOT_TPU_TRACE=1"
        )
        return 0
    for v in verdicts:
        print(v.format())
    return 2
