"""snapshot-stats: render per-step tables from a telemetry event log.

One consumer for BENCH runs and operators alike: both read the JSONL
event log the sinks write, so the numbers in a benchmark table and the
numbers an operator tails in production are the same numbers.

CLI (also ``python -m torchsnapshot_tpu.telemetry`` and
``tools/snapshot_stats.py``)::

    snapshot-stats <events.jsonl> [--kind take] [--path-contains step_]
    snapshot-stats trace <snapshot-dir>   # merge per-rank flight-recorder
                                          # traces (telemetry/trace.py)
    snapshot-stats doctor <snapshot-dir>  # rule-based diagnosis
                                          # (telemetry/doctor.py)
    snapshot-stats trend <manager-root>   # per-step regression check
                                          # (doctor --trend shorthand)
    snapshot-stats goodput <manager-root> # run-level wall-time
                                          # attribution + storage spend
                                          # (telemetry/goodput.py)
    snapshot-stats diff <before> <after>  # critical-path / bench-record
                                          # differential comparison
                                          # (telemetry/critpath.py;
                                          # operands may be incident
                                          # bundle dirs)
    snapshot-stats slo <root>             # judge the declared SLOs with
                                          # burn-rate math
                                          # (telemetry/slo.py)
    snapshot-stats bundle <root>          # list / capture incident
                                          # black-box bundles
                                          # (telemetry/bundle.py)

Output: one row per (path, kind, rank) record — phase durations,
bytes, throughput, budget wait, retries — followed by a per-tier
throughput table and any cross-rank straggler lines rank 0 attached.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from .sink import SNAPSHOT_EVENTS_BASENAME, load_events


def find_events_for(snapshot_path: str) -> List[dict]:
    """Events recorded for one snapshot, newest last; [] when none
    exist. Used by ``fsck --stats``. Probes both sinks: the
    snapshot-adjacent ``.telemetry.jsonl``, and — when
    ``TORCHSNAPSHOT_TPU_TELEMETRY_DIR`` is set (the higher-precedence
    sink, where reports actually went) — that directory's
    ``events.jsonl`` filtered to this snapshot's path."""
    from .. import knobs
    from .sink import EVENTS_BASENAME, local_fs_root

    events: List[dict] = []
    root = local_fs_root(snapshot_path)
    if root is not None:
        path = os.path.join(root, SNAPSHOT_EVENTS_BASENAME)
        if os.path.exists(path):
            events.extend(load_events(path))
    telemetry_dir = knobs.get_telemetry_dir()
    if telemetry_dir:
        path = os.path.join(telemetry_dir, EVENTS_BASENAME)
        if os.path.exists(path):
            want = _norm_snapshot_path(snapshot_path)
            events.extend(
                e
                for e in load_events(path)
                if _norm_snapshot_path(str(e.get("path", ""))) == want
            )
    return events


def _norm_snapshot_path(path: str) -> str:
    """Spelling-insensitive snapshot-path identity for event filtering:
    local paths resolve (relative vs absolute, trailing slash); URL
    paths only drop the trailing slash."""
    if "://" in path:
        return path.rstrip("/")
    return os.path.normpath(os.path.abspath(path))


def _mb(nbytes: float) -> float:
    return nbytes / 1024**2


def _rate_mb_s(nbytes: float, seconds: float) -> Optional[float]:
    """Table variant of the shared guard: None (rendered '-') when the
    elapsed time carries no signal."""
    from . import MIN_RATE_ELAPSED_S, safe_rate_mb_s

    if seconds < MIN_RATE_ELAPSED_S:
        return None
    return safe_rate_mb_s(nbytes, seconds)


def _fmt_rate(rate: Optional[float]) -> str:
    return "-" if rate is None else f"{rate:.1f}"


def _short_path(path: str, limit: int = 40) -> str:
    return path if len(path) <= limit else "…" + path[-(limit - 1) :]


def render_summary(events: Sequence[dict]) -> str:
    """Per-record table + per-plugin throughput + straggler lines."""
    if not events:
        return "no telemetry events"
    lines: List[str] = []
    header = (
        f"{'path':<42} {'kind':<13} {'rank':>4} {'phases':<34} "
        f"{'MB':>9} {'MB/s':>8} {'wait_s':>7} {'retries':>7}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for ev in events:
        phases = ev.get("phases", {})
        phase_str = " ".join(
            f"{name}={phases[name]:.2f}s" for name in sorted(phases)
        )
        total_bytes = ev.get("bytes_moved", 0)
        # Throughput over the longest phase (the pipeline's wall clock):
        # phases are completion offsets, so the max IS the elapsed time.
        elapsed = max(phases.values(), default=0.0)
        retries = ev.get("retries", {})
        n_retries = int(
            retries.get("attempts", 0) + retries.get("gcs_recover_attempts", 0)
        )
        lines.append(
            f"{_short_path(ev.get('path', '?')):<42} "
            f"{ev.get('kind', '?'):<13} "
            f"{ev.get('rank', 0):>4} "
            f"{phase_str:<34.34} "
            f"{_mb(total_bytes):>9.2f} "
            f"{_fmt_rate(_rate_mb_s(total_bytes, elapsed)):>8} "
            f"{ev.get('budget_wait_s', 0.0):>7.3f} "
            f"{n_retries:>7}"
        )

    plugin_totals: Dict[str, Dict[str, float]] = {}
    for ev in events:
        for plugin, fields in ev.get("plugins", {}).items():
            agg = plugin_totals.setdefault(
                plugin, {"write_bytes": 0.0, "read_bytes": 0.0}
            )
            agg["write_bytes"] += fields.get("write_bytes", 0.0)
            agg["read_bytes"] += fields.get("read_bytes", 0.0)
    if plugin_totals:
        lines.append("")
        lines.append("per-plugin totals:")
        for plugin in sorted(plugin_totals):
            agg = plugin_totals[plugin]
            lines.append(
                f"  {plugin:<8} wrote {_mb(agg['write_bytes']):>10.2f} MB   "
                f"read {_mb(agg['read_bytes']):>10.2f} MB"
            )

    straggler_lines: List[str] = []
    for ev in events:
        agg = ev.get("aggregated")
        if not agg:
            continue
        for metric in sorted(agg):
            spread = agg[metric]
            straggler_lines.append(
                f"  {_short_path(ev.get('path', '?'))} {metric}: "
                f"min={spread['min']} median={spread['median']} "
                f"max={spread['max']} straggler=rank {spread['straggler']}"
            )
    if straggler_lines:
        lines.append("")
        lines.append("cross-rank spread (rank 0 aggregation):")
        lines.extend(straggler_lines)

    mirror_events = [ev for ev in events if ev.get("kind") == "mirror"]
    if mirror_events:
        lines.append("")
        lines.append("mirror jobs:")
        for ev in mirror_events:
            m = ev.get("mirror", {})
            status = "FAILED" if ev.get("error") else "ok"
            lines.append(
                f"  {_short_path(ev.get('path', '?'))}: "
                f"{ev.get('blobs', 0)} blobs, "
                f"{_mb(ev.get('bytes_moved', 0)):.2f} MB, "
                f"lag {m.get('lag_s', 0.0):.2f}s, {status}"
            )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "trace":
        # ``python -m torchsnapshot_tpu.telemetry trace <snapshot>``:
        # cross-rank trace merge + straggler summary.
        from .trace import main as trace_main

        return trace_main(argv[1:])
    if argv and argv[0] == "doctor":
        # ``python -m torchsnapshot_tpu.telemetry doctor <snapshot>``
        # (and ``doctor --trend <root>``): rule-based diagnosis.
        from .doctor import main as doctor_main

        return doctor_main(argv[1:])
    if argv and argv[0] == "trend":
        # ``snapshot-stats trend <root>``: shorthand for doctor --trend.
        from .doctor import main as doctor_main

        return doctor_main(["--trend", *argv[1:]])
    if argv and argv[0] == "goodput":
        # ``python -m torchsnapshot_tpu.telemetry goodput <root>``:
        # run-level wall-time attribution + storage-cost curves from
        # the run ledger (telemetry/goodput.py).
        from .goodput import main as goodput_main

        return goodput_main(argv[1:])
    if argv and argv[0] == "diff":
        # ``python -m torchsnapshot_tpu.telemetry diff <before> <after>``:
        # differential critical-path / bench-record comparison
        # (telemetry/critpath.py).
        from .critpath import diff_main

        return diff_main(argv[1:])
    if argv and argv[0] == "fleet":
        # ``python -m torchsnapshot_tpu.telemetry fleet <target>``:
        # live per-rank/per-subscriber table from the __obs/ metrics
        # plane on the coordination store (telemetry/wire.py).
        from .wire import fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "slo":
        # ``python -m torchsnapshot_tpu.telemetry slo <root>``: judge
        # the declared SLOs over a root's (or bundle's) run ledger +
        # step history with burn-rate math (telemetry/slo.py).
        from .slo import main as slo_main

        return slo_main(argv[1:])
    if argv and argv[0] == "bundle":
        # ``python -m torchsnapshot_tpu.telemetry bundle <root>``: list
        # (or --capture) incident black-box bundles
        # (telemetry/bundle.py).
        from .bundle import main as bundle_main

        return bundle_main(argv[1:])

    p = argparse.ArgumentParser(
        prog="snapshot-stats",
        description="Render per-step tables from a checkpoint-telemetry "
        "JSONL event log.",
    )
    p.add_argument("events", help="events.jsonl / .telemetry.jsonl path")
    p.add_argument(
        "--kind",
        default=None,
        help="only records of this kind (take, restore, mirror, ...)",
    )
    p.add_argument(
        "--path-contains",
        default=None,
        help="only records whose snapshot path contains this substring",
    )
    args = p.parse_args(argv)
    if not os.path.exists(args.events):
        print(f"snapshot-stats: {args.events}: no such file")
        return 1
    events = load_events(args.events)
    if args.kind:
        events = [e for e in events if e.get("kind") == args.kind]
    if args.path_contains:
        events = [
            e for e in events if args.path_contains in e.get("path", "")
        ]
    print(render_summary(events))
    return 0
