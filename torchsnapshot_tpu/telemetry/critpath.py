"""Critical-path engine + differential regression analysis.

The flight recorder (trace.py) answers "which spans ran"; the
SnapshotReport's phases answer "how long each stage's wall clock was".
Neither answers the question a 95 s steady-state stall actually poses:
**which span chain gated the op's commit** — staging overlaps the write
drain, barriers overlap the mirror, and summing phase walls
double-charges every overlapped second. This module closes that gap:

- **Blocking-chain attribution.** For one take/restore op, a sweep over
  the op envelope's span window partitions every microsecond of wall
  into named path segments (device capture -> budget wait -> staging ->
  write drain -> coordination/barrier -> wire RPC -> mirror ...). Each
  elementary interval is charged to the *most recently begun* span
  still open — the innermost frame of the blocking chain, i.e. what the
  process was actually inside while the wall clock advanced. The
  partition is exhaustive by construction (envelope-only time lands in
  ``other``), so the segment sums cover >= 95% of op wall — the
  per-stage attribution ByteCheckpoint-style pipeline tuning needs.
- **Cross-process descent.** The same sweep over a *merged* Chrome
  trace (trace.merge_traces) descends through the wire observatory's
  stitched client->handler pairs: an interval gated by a ``wire:rpc``
  span is re-attributed to whatever the serving peer's handler was
  inside at that moment, so a "slow RPC" resolves to the peer's disk,
  not the socket.
- **Differential layer.** ``python -m torchsnapshot_tpu.telemetry diff``
  compares two ops (snapshot dirs / events files) or two parsed
  ``BENCH_r*.json`` records and names the regressed path segment / bench
  leg with evidence citations; the ``critical-path-shifted`` and
  ``bench-regression`` doctor rules (doctor.py) make the same checks
  fleet-automatic.

The per-op result rides every SnapshotReport as the ``critical_path``
field (computed in-process from the recorder window at report time),
folds across ranks in ``report.aggregate_across_ranks``, lands in
history rows (``history.summarize_report``), and trends via ``doctor
--trend``. See docs/observability.md ("Critical path & differential
analysis").
"""

from __future__ import annotations

import json
import os
import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import names

# ---------------------------------------------------------------------------
# Segment taxonomy
# ---------------------------------------------------------------------------

# Path-segment vocabulary (stable identifiers: history rows, the
# cross-rank fold, and the diff CLI all key on these).
SEG_DEVICE_CAPTURE = "device_capture"
SEG_BUDGET_WAIT = "budget_wait"
SEG_STAGING = "staging"
SEG_WRITE_DRAIN = "write_drain"
SEG_READ_DRAIN = "read_drain"
SEG_COORDINATION = "coordination"
SEG_WIRE = "wire"
SEG_MIRROR = "mirror"
SEG_PEER = "peer"
SEG_CDN = "cdn"
# Envelope-only time: the op span was open but no instrumented child
# was — scheduling gaps, uninstrumented Python. A named segment (it
# counts toward coverage); a LARGE ``other`` share is itself a finding
# (instrument the gap).
SEG_OTHER = "other"

# span name -> path segment. Spans absent here (new layers, envelope
# spans gating nothing) attribute to ``other`` rather than erroring:
# the engine must survive spans younger than itself.
_SEGMENT_BY_SPAN: Dict[str, str] = {
    names.SPAN_DEVICE_CAPTURE: SEG_DEVICE_CAPTURE,
    names.SPAN_PIPELINE_BUDGET_ACQUIRE: SEG_BUDGET_WAIT,
    names.SPAN_PIPELINE_STAGE: SEG_STAGING,
    names.SPAN_LEAF_STAGE: SEG_STAGING,
    names.SPAN_BATCHER_STAGE_SLAB: SEG_STAGING,
    names.SPAN_BATCHER_STAGE_SLAB_VECTORIZED: SEG_STAGING,
    names.SPAN_PIPELINE_WRITE_DRAIN: SEG_WRITE_DRAIN,
    names.SPAN_STORAGE_WRITE: SEG_WRITE_DRAIN,
    names.SPAN_FS_NATIVE_WRITE: SEG_WRITE_DRAIN,
    names.SPAN_FS_NATIVE_PWRITEV: SEG_WRITE_DRAIN,
    names.SPAN_FS_NATIVE_DIRECT_WRITE: SEG_WRITE_DRAIN,
    names.SPAN_PIPELINE_CONSUME: SEG_READ_DRAIN,
    names.SPAN_LEAF_CONSUME: SEG_READ_DRAIN,
    names.SPAN_BATCHER_CONSUME_SPANNING: SEG_READ_DRAIN,
    names.SPAN_STORAGE_READ: SEG_READ_DRAIN,
    names.SPAN_FS_NATIVE_READ: SEG_READ_DRAIN,
    names.SPAN_BARRIER_ARRIVE: SEG_COORDINATION,
    names.SPAN_BARRIER_DEPART: SEG_COORDINATION,
    names.SPAN_FANOUT_EXCHANGE: SEG_COORDINATION,
    names.SPAN_WIRE_RPC: SEG_WIRE,
    names.SPAN_WIRE_HANDLER: SEG_WIRE,
    names.SPAN_MIRROR_JOB: SEG_MIRROR,
    names.SPAN_MIRROR_BLOB: SEG_MIRROR,
    names.SPAN_PEER_JOB: SEG_PEER,
    names.SPAN_PEER_PUSH: SEG_PEER,
    names.SPAN_PEER_PULL: SEG_PEER,
    names.SPAN_CDN_PUBLISH: SEG_CDN,
    names.SPAN_CDN_SYNC: SEG_CDN,
    names.SPAN_CDN_SWAP: SEG_CDN,
}

# Per-kind op envelope span names: the window(s) whose wall the sweep
# partitions. Async takes have TWO envelopes (the training-visible
# stage span and the background commit span); the sweep attributes over
# their union.
_ENVELOPES_BY_KIND: Dict[str, Tuple[str, ...]] = {
    "take": (names.SPAN_TAKE,),
    "restore": (names.SPAN_RESTORE,),
    "async_take": (
        names.SPAN_ASYNC_TAKE_STAGE,
        names.SPAN_ASYNC_TAKE_COMMIT,
    ),
    "async_restore": (names.SPAN_ASYNC_RESTORE_READS,),
    "mirror": (names.SPAN_MIRROR_JOB,),
}
_ALL_ENVELOPE_NAMES = frozenset(
    n for ns in _ENVELOPES_BY_KIND.values() for n in ns
)

# Evidence spans cited per critical_path result (the blocking chain's
# heaviest members), and the coverage the acceptance bar requires.
EVIDENCE_TOP_N = 5
MIN_COVERAGE = 0.95


def segment_for(span_name: str) -> str:
    """The path segment a span attributes to (``other`` for envelope /
    unknown spans) — also the watchdog's gating-segment label."""
    return _SEGMENT_BY_SPAN.get(span_name, SEG_OTHER)


# ---------------------------------------------------------------------------
# Sweep-line attribution
# ---------------------------------------------------------------------------


def _merge_intervals(
    intervals: List[Tuple[int, int]]
) -> List[Tuple[int, int]]:
    """Sorted, non-overlapping union of [begin, end) interval list."""
    out: List[Tuple[int, int]] = []
    for lo, hi in sorted(intervals):
        if hi <= lo:
            continue
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _overlap_us(lo: int, hi: int, windows: List[Tuple[int, int]]) -> int:
    """Length of [lo, hi)'s intersection with the merged window list."""
    total = 0
    for wlo, whi in windows:
        total += max(0, min(hi, whi) - max(lo, wlo))
    return total


def _sweep(
    spans: List[Dict[str, Any]],
    windows: List[Tuple[int, int]],
    descend: Optional[Any] = None,
) -> Tuple[Dict[str, float], Dict[Tuple[str, str], Dict[str, Any]]]:
    """Partition the window wall across the candidate spans.

    ``spans``: ``{"name", "ts", "dur", "order", "args"}`` with ts/dur in
    microseconds and ``order`` a begin-order tiebreak (bseq). Every
    elementary interval between span boundaries is charged to the most
    recently begun span still open there — the innermost frame of the
    blocking chain. ``descend(name, args, lo, hi)``, when given, may
    re-attribute one gated interval (the merged-trace wire descent);
    it returns ``(segment, evidence_key)`` or None.

    Returns ``(segment -> seconds, (segment, span name) -> evidence)``
    where evidence carries the gated seconds and a representative arg
    set (heaviest single contributor).
    """
    segments: Dict[str, float] = {}
    evidence: Dict[Tuple[str, str], Dict[str, Any]] = {}
    if not windows:
        return segments, evidence
    begins = sorted(
        (s for s in spans if s["dur"] > 0),
        key=lambda s: (s["ts"], s["order"]),
    )
    ends = sorted(begins, key=lambda s: s["ts"] + s["dur"])
    bounds = sorted(
        {b for s in begins for b in (s["ts"], s["ts"] + s["dur"])}
        | {b for w in windows for b in w}
    )
    active: Dict[int, Dict[str, Any]] = {}
    bi = ei = 0
    for i, lo in enumerate(bounds[:-1]):
        hi = bounds[i + 1]
        while ei < len(ends) and ends[ei]["ts"] + ends[ei]["dur"] <= lo:
            active.pop(id(ends[ei]), None)
            ei += 1
        while bi < len(begins) and begins[bi]["ts"] <= lo:
            active[id(begins[bi])] = begins[bi]
            bi += 1
        overlap = _overlap_us(lo, hi, windows)
        if overlap <= 0:
            continue
        gating = None
        for s in active.values():
            if gating is None or (s["ts"], s["order"]) > (
                gating["ts"],
                gating["order"],
            ):
                gating = s
        if gating is None:
            seg, name, args = SEG_OTHER, "", {}
        else:
            name, args = gating["name"], gating.get("args") or {}
            seg = segment_for(name)
            if descend is not None:
                deeper = descend(name, args, lo, hi)
                if deeper is not None:
                    seg, name, args = deeper
        seconds = overlap / 1e6
        segments[seg] = segments.get(seg, 0.0) + seconds
        if name:
            slot = evidence.setdefault(
                (seg, name), {"gated_s": 0.0, "peak_s": 0.0, "args": {}}
            )
            slot["gated_s"] += seconds
            if seconds > slot["peak_s"]:
                slot["peak_s"] = seconds
                slot["args"] = args
    return segments, evidence


def _assemble(
    segments: Dict[str, float],
    evidence: Dict[Tuple[str, str], Dict[str, Any]],
    wall_us: int,
) -> Optional[Dict[str, Any]]:
    """Shape the sweep output into the ``critical_path`` dict."""
    if wall_us <= 0:
        return None
    wall_s = wall_us / 1e6
    attributed = sum(segments.values())
    chain: List[Dict[str, Any]] = []
    for (seg, name), slot in sorted(
        evidence.items(), key=lambda kv: -kv[1]["gated_s"]
    )[:EVIDENCE_TOP_N]:
        entry: Dict[str, Any] = {
            "span": name,
            "segment": seg,
            "gated_s": round(slot["gated_s"], 6),
        }
        blob = (slot.get("args") or {}).get("blob")
        if blob:
            entry["blob"] = blob
        chain.append(entry)
    ordered = sorted(segments.items(), key=lambda kv: -kv[1])
    return {
        "wall_s": round(wall_s, 6),
        "coverage": round(min(1.0, attributed / wall_s), 4),
        "segments": {k: round(v, 6) for k, v in ordered},
        "dominant": ordered[0][0] if ordered else SEG_OTHER,
        "chain": chain,
    }


def critical_path_from_events(
    events: Sequence[Dict[str, Any]], kind: str
) -> Optional[Dict[str, Any]]:
    """The ``critical_path`` field for one op, from the flight
    recorder's window (``recorder.events_since(mark)`` — completed "X"
    events, ts/dur in unix-epoch us, begin order in ``bseq``). None
    when the window holds no envelope span for ``kind`` (trace ring
    overrun, or an op that never opened its envelope)."""
    env_names = _ENVELOPES_BY_KIND.get(kind)
    if not env_names:
        return None
    envelopes: List[Tuple[int, int]] = []
    candidates: List[Dict[str, Any]] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e["name"]
        if name in env_names:
            envelopes.append((e["ts"], e["ts"] + e["dur"]))
            continue
        if name in _ALL_ENVELOPE_NAMES:
            # Another op's envelope overlapping this window (async
            # commit draining into the next take): an envelope never
            # gates, it only bounds.
            continue
        candidates.append(
            {
                "name": name,
                "ts": e["ts"],
                "dur": e["dur"],
                "order": e.get("bseq", 0),
                "args": e.get("args") or {},
            }
        )
    windows = _merge_intervals(envelopes)
    wall_us = sum(hi - lo for lo, hi in windows)
    segments, evidence = _sweep(candidates, windows)
    # The remainder of the envelope wall — no instrumented span open —
    # is ``other``: the partition always sums to the wall.
    gap = wall_us / 1e6 - sum(segments.values())
    if gap > 1e-9:
        segments[SEG_OTHER] = segments.get(SEG_OTHER, 0.0) + gap
    return _assemble(segments, evidence, wall_us)


def critical_path_from_doc(
    doc: Dict[str, Any],
    kind: str = "take",
    pid: Optional[int] = None,
) -> Optional[Dict[str, Any]]:
    """The same attribution over a (merged) Chrome trace document, with
    cross-process descent: an interval gated by a ``wire:rpc`` span is
    re-attributed to whatever the serving peer's stitched handler was
    inside at that moment. ``pid`` selects the op's own process in a
    merged doc (default: the pid owning the longest envelope span)."""
    from .trace import spans_from_chrome, stitched_wire_pairs

    spans = spans_from_chrome(doc)
    env_names = _ENVELOPES_BY_KIND.get(kind)
    if not env_names or not spans:
        return None
    env_spans = [s for s in spans if s["name"] in env_names]
    if pid is not None:
        env_spans = [s for s in env_spans if s["pid"] == pid]
    if not env_spans:
        return None
    if pid is None:
        pid = max(env_spans, key=lambda s: s["dur_us"])["pid"]
        env_spans = [s for s in env_spans if s["pid"] == pid]
    windows = _merge_intervals(
        [(s["ts"], s["ts"] + s["dur_us"]) for s in env_spans]
    )
    wall_us = sum(hi - lo for lo, hi in windows)

    def to_cand(s: Dict[str, Any], order: int) -> Dict[str, Any]:
        return {
            "name": s["name"],
            "ts": s["ts"],
            "dur": s["dur_us"],
            # Chrome reconstruction has no bseq; begin ts + closing
            # order approximates it (later begin = deeper frame).
            "order": order,
            "args": s.get("args") or {},
        }

    candidates = [
        to_cand(s, i)
        for i, s in enumerate(spans)
        if s["pid"] == pid and s["name"] not in _ALL_ENVELOPE_NAMES
    ]

    # Wire descent: client span_id -> the handler's (pid, tid) spans,
    # so a gated RPC interval resolves to the peer's own frames.
    handler_tracks: Dict[str, List[Dict[str, Any]]] = {}
    for client, handler in stitched_wire_pairs(doc):
        span_id = str(client.get("args", {}).get("span_id"))
        track = [
            to_cand(s, i)
            for i, s in enumerate(spans)
            if s["pid"] == handler["pid"]
            and s["tid"] == handler["tid"]
            and s["name"] != names.SPAN_WIRE_HANDLER
            and s["ts"] < handler["ts"] + handler["dur_us"]
            and s["ts"] + s["dur_us"] > handler["ts"]
        ]
        handler_tracks[span_id] = track

    def descend(
        name: str, args: Dict[str, Any], lo: int, hi: int
    ) -> Optional[Tuple[str, str, Dict[str, Any]]]:
        if name != names.SPAN_WIRE_RPC:
            return None
        track = handler_tracks.get(str(args.get("span_id")))
        if not track:
            return None
        inner = None
        for s in track:
            if s["ts"] < hi and s["ts"] + s["dur"] > lo:
                if inner is None or (s["ts"], s["order"]) > (
                    inner["ts"],
                    inner["order"],
                ):
                    inner = s
        if inner is None:
            return None
        return (
            segment_for(inner["name"]),
            inner["name"],
            inner.get("args") or {},
        )

    segments, evidence = _sweep(candidates, windows, descend=descend)
    gap = wall_us / 1e6 - sum(segments.values())
    if gap > 1e-9:
        segments[SEG_OTHER] = segments.get(SEG_OTHER, 0.0) + gap
    return _assemble(segments, evidence, wall_us)


# ---------------------------------------------------------------------------
# Trend integration: dominant-segment shift detection
# ---------------------------------------------------------------------------


def detect_critical_path_shifts(
    records: List[Dict[str, Any]], window: int = 0
) -> List[Dict[str, Any]]:
    """Evidence rows for steps whose dominant critical-path segment
    differs from the *modal* dominant of the preceding rolling window
    (same-kind records only, like the magnitude trend): the bottleneck
    moved even if the wall barely did. Requires a consistent baseline —
    the modal segment must hold a strict majority of the window — so an
    already-oscillating history never flags."""
    from .history import TREND_MIN_BASELINE, TREND_WINDOW

    window = window or TREND_WINDOW
    out: List[Dict[str, Any]] = []
    by_kind: Dict[str, List[int]] = {}
    for i, rec in enumerate(records):
        if (rec.get("critpath") or {}).get("dominant"):
            by_kind.setdefault(str(rec.get("kind") or "take"), []).append(i)
    for kind in sorted(by_kind):
        indices = by_kind[kind]
        doms = [
            str(records[i]["critpath"]["dominant"]) for i in indices
        ]
        for j in range(TREND_MIN_BASELINE, len(doms)):
            baseline = doms[max(0, j - window) : j]
            if len(baseline) < TREND_MIN_BASELINE:
                continue
            modal = max(set(baseline), key=baseline.count)
            share = baseline.count(modal) / len(baseline)
            if share <= 0.5 or doms[j] == modal:
                continue
            rec = records[indices[j]]
            cp = rec.get("critpath") or {}
            out.append(
                {
                    "index": indices[j],
                    "step": rec.get("step"),
                    "kind": kind,
                    "path": rec.get("path"),
                    "dominant": doms[j],
                    "previous_dominant": modal,
                    "baseline_share": round(share, 3),
                    "window": len(baseline),
                    "dominant_s": (cp.get("segments") or {}).get(
                        doms[j]
                    ),
                }
            )
    out.sort(key=lambda row: row["index"])
    return out


# ---------------------------------------------------------------------------
# Bench-record differential (BENCH_r*.json)
# ---------------------------------------------------------------------------

# Signal-of-record legs with DECLARED per-leg direction and tolerance
# floors: leg key in the parsed record -> (label, direction, abs
# floor). Direction +1 flags increases (walls), -1 decreases
# (throughput / efficiency). The relative floor below is sized to the
# measured round-to-round link drift of the BENCH_r* series (r06 vs r07
# moves legs ~35% with no code change), so only beyond-drift moves
# convict.
BENCH_LEGS: Dict[str, Tuple[str, int, float]] = {
    "value": ("headline take throughput (GB/s)", -1, 0.02),
    "restore_gbps": ("restore throughput (GB/s)", -1, 0.02),
    "cold_restore_gbps": ("cold restore throughput (GB/s)", -1, 0.02),
    "async_visible_s": ("async take visible stall (s)", 1, 0.1),
    "cold_start_sync_s": ("restore cold start (s)", 1, 0.1),
    "fanout_restore_s": ("fan-out restore wall (s)", 1, 0.1),
    "fallback_restore_s": ("fallback restore wall (s)", 1, 0.1),
    "peer_recovery_wall_s": ("peer recovery wall (s)", 1, 0.1),
    "pipeline_efficiency": ("pipeline efficiency", -1, 0.05),
    "steady_state_final_efficiency": (
        "steady-state final efficiency",
        -1,
        0.05,
    ),
    "write_path_zero_pack_speedup": ("zero-pack speedup", -1, 0.2),
    "incremental_speedup": ("incremental-save speedup", -1, 0.2),
}
BENCH_MAD_K = 4.0
BENCH_MIN_REL = 0.5


def bench_regressions(
    records: Sequence[Tuple[str, Dict[str, Any]]],
    window: int = 6,
    legs: Optional[Dict[str, Tuple[str, int, float]]] = None,
) -> List[Dict[str, Any]]:
    """Regression rows for the NEWEST parsed bench record against the
    rolling baseline of its predecessors (``records`` oldest first,
    each ``(label, parsed)``). Per leg: baseline = the up-to-``window``
    preceding records that carry the leg; a value regresses when its
    signed deviation from the baseline median exceeds
    max(k * MAD, rel_floor * |median|, the leg's declared absolute
    floor). With a single predecessor (a pair diff) the MAD term is
    zero and the relative floor alone judges — sized so r06 vs r07
    (pure link drift) stays quiet while a doctored 5x slowdown fires."""
    if len(records) < 2:
        return []
    legs = legs if legs is not None else BENCH_LEGS
    newest_label, newest = records[-1]
    out: List[Dict[str, Any]] = []
    for leg, (label, sign, abs_floor) in legs.items():
        value = newest.get(leg)
        # Every signal leg is strictly positive when it actually ran; a
        # recorded 0.0 (or null) is a skipped/failed leg, not a
        # measurement — judging it would convict budget gating.
        if not isinstance(value, (int, float)) or value <= 0:
            continue
        carrying = [
            (lbl, float(p[leg]))
            for lbl, p in records[:-1]
            if isinstance(p.get(leg), (int, float)) and p[leg] > 0
        ][-window:]
        if not carrying:
            continue
        baseline = [v for _, v in carrying]
        med = statistics.median(baseline)
        mad = statistics.median(abs(v - med) for v in baseline)
        threshold = max(
            BENCH_MAD_K * mad, BENCH_MIN_REL * abs(med), abs_floor
        )
        deviation = sign * (float(value) - med)
        if deviation > threshold:
            out.append(
                {
                    "leg": leg,
                    "label": label,
                    "record": newest_label,
                    "value": round(float(value), 4),
                    "baseline_median": round(med, 4),
                    "baseline_mad": round(mad, 4),
                    "threshold": round(threshold, 4),
                    "window": len(baseline),
                    "baseline_records": [lbl for lbl, _ in carrying],
                }
            )
    out.sort(key=lambda r: -(abs(r["value"] - r["baseline_median"])))
    return out


def bench_verdicts(rows: List[Dict[str, Any]]) -> List[Any]:
    """``bench-regression`` doctor verdicts from regression rows."""
    from .doctor import Verdict

    out = []
    for row in rows:
        out.append(
            Verdict(
                rule=names.RULE_BENCH_REGRESSION,
                summary=(
                    f"{row['label']} regressed to {row['value']} against "
                    f"a baseline median of {row['baseline_median']} "
                    f"(tolerance {row['threshold']})"
                ),
                evidence={
                    k: v
                    for k, v in row.items()
                    if k not in ("label", "record")
                },
                severity="warning",
                source=str(row.get("record") or ""),
            )
        )
    return out


# ---------------------------------------------------------------------------
# Report differential (two ops' critical paths)
# ---------------------------------------------------------------------------

# A segment's wall regressed when it grew by more than
# max(rel * before, abs floor) — the same epistemics as the trend
# detector, collapsed to a pair.
DIFF_MIN_REL = 0.3
DIFF_MIN_ABS_S = 0.05


def diff_reports(
    before: Dict[str, Any], after: Dict[str, Any]
) -> Dict[str, Any]:
    """Segment-level differential of two report dicts carrying
    ``critical_path``: per-segment before/after/delta, the regressed
    segments (delta beyond tolerance, largest first), and the AFTER
    op's evidence chain filtered to the top regressed segment — the
    span-level citation for "what got slower"."""
    cp_a = before.get("critical_path") or {}
    cp_b = after.get("critical_path") or {}
    segs_a = cp_a.get("segments") or {}
    segs_b = cp_b.get("segments") or {}
    table: Dict[str, Dict[str, float]] = {}
    regressed: List[Dict[str, Any]] = []
    for seg in sorted(set(segs_a) | set(segs_b)):
        a = float(segs_a.get(seg, 0.0))
        b = float(segs_b.get(seg, 0.0))
        delta = b - a
        table[seg] = {
            "before_s": round(a, 6),
            "after_s": round(b, 6),
            "delta_s": round(delta, 6),
        }
        if delta > max(DIFF_MIN_REL * a, DIFF_MIN_ABS_S):
            regressed.append({"segment": seg, "delta_s": round(delta, 6)})
    regressed.sort(key=lambda r: -r["delta_s"])
    evidence: List[Dict[str, Any]] = []
    if regressed:
        top = regressed[0]["segment"]
        evidence = [
            e
            for e in cp_b.get("chain") or []
            if e.get("segment") == top
        ]
    return {
        "before": {
            "path": before.get("path"),
            "kind": before.get("kind"),
            "wall_s": cp_a.get("wall_s"),
            "dominant": cp_a.get("dominant"),
        },
        "after": {
            "path": after.get("path"),
            "kind": after.get("kind"),
            "wall_s": cp_b.get("wall_s"),
            "dominant": cp_b.get("dominant"),
        },
        "segments": table,
        "regressed": regressed,
        "evidence": evidence,
        "dominant_shifted": (
            cp_a.get("dominant") is not None
            and cp_b.get("dominant") is not None
            and cp_a.get("dominant") != cp_b.get("dominant")
        ),
    }


# ---------------------------------------------------------------------------
# diff CLI
# ---------------------------------------------------------------------------


def _looks_like_bench_record(path: str) -> bool:
    if not os.path.isfile(path):
        return False
    if os.path.basename(path).startswith("BENCH") and path.endswith(
        ".json"
    ):
        return True
    try:
        with open(path, "r", encoding="utf-8") as f:
            head = json.load(f)
        return isinstance(head, dict) and "parsed" in head
    except Exception:  # noqa: BLE001 - not a bench record then
        return False


def _load_bench_parsed(path: str) -> Optional[Dict[str, Any]]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    return parsed if isinstance(parsed, dict) else None


def _load_report(path: str, kind: Optional[str]) -> Optional[Dict[str, Any]]:
    """Newest report dict for one diff operand: a snapshot dir (its
    ``.telemetry.jsonl``), an events file, or a single-report JSON."""
    from .sink import SNAPSHOT_EVENTS_BASENAME, load_events

    if os.path.isdir(path):
        path = os.path.join(path, SNAPSHOT_EVENTS_BASENAME)
    if not os.path.isfile(path):
        return None
    if path.endswith(".jsonl"):
        events = load_events(path)
    else:
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            events = [doc] if isinstance(doc, dict) else []
        except ValueError:
            events = load_events(path)
    if kind:
        events = [e for e in events if e.get("kind") == kind]
    else:
        events = [e for e in events if e.get("kind") != "mirror"]
    events = [e for e in events if e.get("critical_path")] or events
    return events[-1] if events else None


def _print_bench_diff(
    rows: List[Dict[str, Any]],
    old_label: str,
    new_label: str,
    old: Dict[str, Any],
    new: Dict[str, Any],
) -> None:
    print(f"bench diff: {old_label} -> {new_label}")
    header = (
        f"  {'leg':<34} {'before':>10} {'after':>10} {'tolerance':>10}"
    )
    print(header)
    print("  " + "-" * (len(header) - 2))
    flagged = {r["leg"] for r in rows}
    for leg, (label, _sign, _floor) in BENCH_LEGS.items():
        a, b = old.get(leg), new.get(leg)
        if a is None and b is None:
            continue
        mark = "  << REGRESSED" if leg in flagged else ""
        fmt = lambda v: "-" if not isinstance(v, (int, float)) else f"{v:.3f}"  # noqa: E731
        print(f"  {label:<34} {fmt(a):>10} {fmt(b):>10}{mark}")
    for v in bench_verdicts(rows):
        print(v.format())


def _print_report_diff(diff: Dict[str, Any]) -> None:
    a, b = diff["before"], diff["after"]
    print(
        f"critical-path diff: {a.get('path')} ({a.get('kind')}, "
        f"wall {a.get('wall_s')}s, dominant {a.get('dominant')})"
    )
    print(
        f"                 -> {b.get('path')} ({b.get('kind')}, "
        f"wall {b.get('wall_s')}s, dominant {b.get('dominant')})"
    )
    header = f"  {'segment':<16} {'before_s':>10} {'after_s':>10} {'delta_s':>10}"
    print(header)
    print("  " + "-" * (len(header) - 2))
    flagged = {r["segment"] for r in diff["regressed"]}
    for seg, row in sorted(
        diff["segments"].items(), key=lambda kv: -kv[1]["after_s"]
    ):
        mark = "  << REGRESSED" if seg in flagged else ""
        print(
            f"  {seg:<16} {row['before_s']:>10.3f} "
            f"{row['after_s']:>10.3f} {row['delta_s']:>+10.3f}{mark}"
        )
    if diff["dominant_shifted"]:
        print(
            f"dominant segment shifted: {a.get('dominant')} -> "
            f"{b.get('dominant')}"
        )
    if diff["regressed"]:
        top = diff["regressed"][0]
        print(
            f"regressed: {top['segment']} (+{top['delta_s']:.3f}s); "
            f"gating spans:"
        )
        for e in diff["evidence"]:
            blob = f" blob={e['blob']}" if e.get("blob") else ""
            print(
                f"  span {e['span']} gated {e['gated_s']:.3f}s{blob}"
            )
    else:
        print("no segment regressed beyond tolerance")


def diff_main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m torchsnapshot_tpu.telemetry diff <A> <B>``: compare
    two steps (snapshot dirs / events files, via their recorded
    ``critical_path``) or two ``BENCH_r*.json`` records (declared
    per-leg tolerances). Incident bundle dirs (telemetry/bundle.py)
    work as operands unchanged — they carry a ``.telemetry.jsonl`` —
    so two black boxes diff offline with both original roots gone.
    Exit 0 = no regression, 2 = regression, 1 = operands unusable."""
    import argparse

    p = argparse.ArgumentParser(
        prog="torchsnapshot_tpu.telemetry diff",
        description=(
            "Differential critical-path / bench-record analysis: which "
            "path segment (or signal-of-record leg) regressed between "
            "two recorded operations, with span evidence citations."
        ),
    )
    p.add_argument(
        "before",
        help="snapshot dir, events file, incident bundle dir, or "
        "BENCH_r*.json",
    )
    p.add_argument("after", help="same (compared against `before`)")
    p.add_argument(
        "--kind",
        default=None,
        help="report kind to compare (default: newest non-mirror record)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable diff instead of the text report",
    )
    args = p.parse_args(list(argv) if argv is not None else None)

    bench_a = _looks_like_bench_record(args.before)
    bench_b = _looks_like_bench_record(args.after)
    if bench_a and bench_b:
        old = _load_bench_parsed(args.before)
        new = _load_bench_parsed(args.after)
        if old is None or new is None:
            print("diff: bench record(s) carry no parsed block")
            return 1
        rows = bench_regressions(
            [
                (os.path.basename(args.before), old),
                (os.path.basename(args.after), new),
            ]
        )
        if args.json:
            print(json.dumps({"bench_regressions": rows}, indent=1))
        else:
            _print_bench_diff(
                rows,
                os.path.basename(args.before),
                os.path.basename(args.after),
                old,
                new,
            )
        return 2 if rows else 0

    before = _load_report(args.before, args.kind)
    after = _load_report(args.after, args.kind)
    if before is None or after is None:
        missing = args.before if before is None else args.after
        print(
            f"diff: no report found for {missing!r} (need a snapshot "
            f"dir with .telemetry.jsonl, an events file, or a pair of "
            f"BENCH_r*.json records; record with "
            f"TORCHSNAPSHOT_TPU_TELEMETRY=1)"
        )
        return 1
    if not (before.get("critical_path") and after.get("critical_path")):
        print(
            "diff: report(s) carry no critical_path field (recorded "
            "by a pre-critpath build, or the trace ring overran the "
            "op window)"
        )
        return 1
    diff = diff_reports(before, after)
    if args.json:
        print(json.dumps(diff, indent=1))
    else:
        _print_report_diff(diff)
    return 2 if diff["regressed"] else 0
