"""Report sinks: JSONL event log + Prometheus text exposition.

Two knob-controlled outputs, both best-effort (a telemetry write must
never fail a checkpoint — failures log a warning and the operation
proceeds):

- **JSONL event log** — one ``SnapshotReport`` JSON object per line.
  ``TORCHSNAPSHOT_TPU_TELEMETRY_DIR`` appends to
  ``<dir>/events.jsonl``; without it, ``TORCHSNAPSHOT_TPU_TELEMETRY=1``
  appends to ``<snapshot_path>/.telemetry.jsonl`` when the snapshot
  path is local (bare/``fs://`` paths; a ``tiered://`` path uses its
  fast tier). Object-store paths have no append primitive, so they
  require the directory knob. ``tools/snapshot_stats.py`` and
  ``python -m torchsnapshot_tpu.telemetry`` consume this log.
- **Prometheus text file** — ``TORCHSNAPSHOT_TPU_PROM_FILE`` names a
  path rewritten atomically (tmp + rename) with the registry's full
  state after every report emission; point a node-exporter textfile
  collector at it.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import List, Optional

from .. import knobs
from . import names
from .registry import MetricsRegistry
from .report import SnapshotReport

logger: logging.Logger = logging.getLogger(__name__)

EVENTS_BASENAME = "events.jsonl"
SNAPSHOT_EVENTS_BASENAME = ".telemetry.jsonl"


def atomic_write_text(path: str, text: str) -> None:
    """Atomic file publish (pid-suffixed tmp + rename, parent created):
    a concurrent reader never observes a torn document. The one
    implementation behind every telemetry artifact that gets rewritten
    in place — the Prometheus textfile, trace exports, progress
    heartbeats, the step history."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)

# Last emitted report per (kind, snapshot path) — process-wide,
# lock-guarded: the in-memory channel the manager's step-history
# recorder reads — a save that just committed needs its own take
# report without re-parsing the sink file (which may not even be
# enabled). Keyed by path too: overlapping async saves (step N's
# commit thread finishing after step N+1's) must each find THEIR
# report, not whichever landed last.
_LAST_REPORTS: dict = {}
_LAST_REPORTS_LOCK = threading.Lock()


def last_report(
    *kinds: str, path: Optional[str] = None
) -> Optional[SnapshotReport]:
    """The most recent report emitted in this process among ``kinds``
    (any kind when none given), optionally restricted to one snapshot
    ``path``; None before a matching emission."""
    with _LAST_REPORTS_LOCK:
        candidates = [
            r
            for (k, p), r in _LAST_REPORTS.items()
            if (not kinds or k in kinds) and (path is None or p == path)
        ]
    if not candidates:
        return None
    return max(candidates, key=lambda r: r.unix_ts)


def local_fs_root(url_path: Optional[str]) -> Optional[str]:
    """The local directory a snapshot URL writes to, or None for
    object-store schemes. Tiered URLs resolve through the fast tier
    (where the take commits — and where an events file survives the
    durable mirror untouched, since the mirror only copies blobs the
    take recorded)."""
    if not url_path:
        return None
    if "://" not in url_path:
        return url_path
    if url_path.startswith("fs://"):
        return url_path[len("fs://") :]
    if url_path.startswith("tiered://"):
        from ..storage_plugin import split_tiered_url

        try:
            tiers = split_tiered_url(url_path)
        except ValueError:
            return None
        if tiers is not None:
            return local_fs_root(tiers[0])
    return None


def events_path_for(snapshot_path: Optional[str]) -> Optional[str]:
    """Where a report about ``snapshot_path`` should be appended, or
    None when no JSONL sink is configured."""
    telemetry_dir = knobs.get_telemetry_dir()
    if telemetry_dir:
        return os.path.join(telemetry_dir, EVENTS_BASENAME)
    if not knobs.is_telemetry_sink_enabled():
        return None
    root = local_fs_root(snapshot_path)
    if root is None:
        return None
    return os.path.join(root, SNAPSHOT_EVENTS_BASENAME)


def emit_report(
    report: SnapshotReport, registry: Optional[MetricsRegistry] = None
) -> Optional[str]:
    """Append ``report`` to the configured JSONL sink (returns the file
    written, or None when no sink applies) and refresh the Prometheus
    text file if one is configured. Never raises."""
    if registry is None:
        from . import metrics

        registry = metrics()
    registry.counter_inc(names.SNAPSHOT_REPORTS_TOTAL, kind=report.kind)
    with _LAST_REPORTS_LOCK:
        _LAST_REPORTS[(report.kind, report.path)] = report
        # Bounded: retention keyed by arbitrary paths must not grow
        # with an arbitrarily long run (one manager produces a new
        # path per step).
        while len(_LAST_REPORTS) > 64:
            _LAST_REPORTS.pop(next(iter(_LAST_REPORTS)))
    path: Optional[str] = None
    try:
        path = events_path_for(report.path)
        if path is not None:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                f.write(report.to_json() + "\n")
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the op
        logger.warning("telemetry: could not append report to %r: %r", path, e)
        path = None
    prom = knobs.get_prometheus_textfile()
    if prom is not None:
        try:
            write_prometheus_textfile(prom, registry)
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "telemetry: could not write prometheus file %r: %r", prom, e
            )
    if report.error and report.rank == 0:
        # A failed op is a black-box trigger: freeze the evidence now,
        # while the failure's traces/heartbeats/ledger tail still
        # exist. Rate-limited + size-capped inside capture_bundle;
        # best-effort like every other sink write here.
        try:
            from .bundle import capture_bundle

            capture_bundle(
                report.path,
                trigger="failed-op",
                reason=f"{report.kind}: {report.error}"[:200],
                snapshot_path=report.path,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "telemetry: failed-op bundle capture failed: %r", e
            )
    return path


def load_events(path: str) -> List[dict]:
    """Parse a JSONL event log, skipping torn/corrupt lines (a crash
    mid-append leaves at most one)."""
    events: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                logger.warning("telemetry: skipping corrupt event line")
    return events


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


def _fmt(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def render_prometheus(registry: MetricsRegistry) -> str:
    """The registry's full state in the Prometheus text format (0.0.4):
    counters, gauges, and histograms with cumulative ``le`` buckets."""
    data = registry.collect()
    lines: List[str] = []
    for series, value in sorted(data["counters"].items()):
        lines.append(f"{series} {_fmt(value)}")
    for series, value in sorted(data["gauges"].items()):
        lines.append(f"{series} {_fmt(value)}")
    for series, hist in sorted(data["histograms"].items()):
        name, brace, rest = series.partition("{")
        base_labels = rest.rstrip("}") if brace else ""
        for le, cumulative in hist["buckets"]:
            label_items = [f'le="{_fmt(le)}"']
            if base_labels:
                label_items.insert(0, base_labels)
            lines.append(
                f"{name}_bucket{{{','.join(label_items)}}} {cumulative}"
            )
        suffix = f"{{{base_labels}}}" if base_labels else ""
        lines.append(f"{name}_sum{suffix} {_fmt(hist['sum'])}")
        lines.append(f"{name}_count{suffix} {hist['count']}")
    return "\n".join(lines) + "\n"


def write_prometheus_textfile(
    path: str, registry: Optional[MetricsRegistry] = None
) -> None:
    """Atomic rewrite (tmp + rename): a scraper never reads a torn file."""
    if registry is None:
        from . import metrics

        registry = metrics()
    atomic_write_text(path, render_prometheus(registry))
