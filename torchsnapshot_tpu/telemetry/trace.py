"""Checkpoint flight recorder: span tracing + Chrome-trace export + merge.

The metrics registry (registry.py) answers "how much, in aggregate";
this module answers "when, exactly, and in what order" — the question a
BENCH stall (`in_take_stall: true`, 120 s vs 71 s steady state) poses
and phase sums cannot answer. Design:

- **Always-on bounded ring.** Every span/instant lands in a process-wide
  ring buffer (capacity knob, default 16384 completed events; oldest
  evict first, evictions counted). Recording is a lock plus a few dict
  ops — the same cost class as a registry observation — so it is never
  gated; only *persistence* is knob-controlled, mirroring the registry's
  always-record/sink-on-demand split.
- **Thread- and asyncio-safe tracks.** A span's track is
  ``(thread, current asyncio task)``: concurrent coroutines on one event
  loop get distinct tracks, so begin/end pairs nest like the sequential
  code that emitted them and the Chrome export never produces crossed
  B/E stacks.
- **Dual emission.** ``utils.tracing.trace_annotation`` call sites feed
  BOTH this recorder and (when a profiler session is active) the jax
  XPlane timeline — one annotation, two sinks.
- **Chrome trace-event export.** Per checkpoint operation (take /
  restore / async variants / mirror job), the op's event window is
  written as Perfetto-loadable Chrome trace JSON next to the snapshot
  (``<snapshot>/.trace-<kind>-rank<r>.json``) or into
  ``TORCHSNAPSHOT_TPU_TRACE_DIR``. Timestamps are unix-epoch
  microseconds so per-rank files share a clock up to host skew.
- **Cross-rank merge.** ``python -m torchsnapshot_tpu.telemetry trace
  <snapshot>`` merges the per-rank files into one trace (one pid per
  rank), optionally correcting per-rank clock offsets measured by the
  SnapshotReport store-gather (report.clock_offsets_s), and renders a
  straggler / longest-span summary.

The stall watchdog (watchdog.py) scans this recorder's open spans.
"""

from __future__ import annotations

import contextlib
import glob
import json
import logging
import os
import threading
import time
from collections import deque
from typing import (
    Any,
    Deque,
    Dict,
    Generator,
    List,
    NamedTuple,
    Optional,
    Tuple,
)

from .. import knobs
from . import names

logger: logging.Logger = logging.getLogger(__name__)

TRACE_BASENAME_PREFIX = "trace-"
SNAPSHOT_TRACE_PREFIX = ".trace-"
MERGED_TRACE_BASENAME = ".trace.merged.json"


def _now_us() -> int:
    return time.time_ns() // 1000


def _track_key() -> Tuple[int, int]:
    """(thread ident, asyncio task id): the unit within which spans are
    guaranteed to nest like sequential code."""
    import asyncio

    try:
        task = asyncio.current_task()
    except RuntimeError:
        task = None
    return (threading.get_ident(), id(task) if task is not None else 0)


class _OpenSpan:
    __slots__ = ("name", "begin_us", "bseq", "tid", "args", "stalled")

    def __init__(
        self, name: str, begin_us: int, bseq: int, tid: int, args: Dict
    ) -> None:
        self.name = name
        self.begin_us = begin_us
        self.bseq = bseq
        self.tid = tid
        self.args = args
        self.stalled = False


class TraceMark(NamedTuple):
    """Opaque cursor from :meth:`SpanRecorder.mark`: the completion
    sequence plus the eviction count at mark time (so an export can
    report drops within ITS window, not the recorder's lifetime)."""

    seq: int
    dropped: int


class SpanRecorder:
    """Bounded in-memory flight recorder. Use the module singleton via
    :func:`get_recorder`; direct construction is for tests.

    Completed events are dicts
    ``{"seq", "bseq", "ph" ("X"|"i"), "name", "ts", "dur", "tid",
    "args"}`` with ``ts``/``dur`` in unix-epoch microseconds; ``seq``
    orders completions (the ring's eviction order and the export-window
    cursor), ``bseq`` orders begins (what the Chrome exporter's B/E
    interleave sorts on).
    """

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = deque(
            maxlen=capacity or knobs.get_trace_buffer_events()
        )
        self._open: Dict[int, _OpenSpan] = {}
        self._seq = 0
        self._next_token = 0
        self._tids: Dict[Tuple[int, int], int] = {}
        self._tid_names: Dict[int, str] = {}
        self.dropped = 0
        # Forward-progress clock: any begin/end/instant refreshes it.
        # The watchdog keys stall detection on this, not on open-span
        # age alone — an envelope span (snapshot:take) legitimately
        # stays open for minutes while events complete underneath.
        self._last_activity = time.monotonic()

    # -- recording -------------------------------------------------------

    def _tid_locked(self, key: Tuple[int, int]) -> int:
        tid = self._tids.get(key)
        if tid is None:
            tid = len(self._tids)
            self._tids[key] = tid
            label = threading.current_thread().name
            if key[1]:
                label = f"{label}:task-{len(self._tids)}"
            self._tid_names[tid] = label
        return tid

    def begin(self, name: str, **args: Any) -> int:
        """Open a span on the caller's track; returns a token for
        :meth:`end`."""
        key = _track_key()
        ts = _now_us()
        with self._lock:
            self._seq += 1
            self._next_token += 1
            self._last_activity = time.monotonic()
            token = self._next_token
            self._open[token] = _OpenSpan(
                name, ts, self._seq, self._tid_locked(key), args
            )
        # Outside the lock: may start the watchdog thread.
        from . import watchdog

        watchdog.ensure_started(self)
        return token

    def end(self, token: int, **extra_args: Any) -> None:
        ts = _now_us()
        with self._lock:
            span = self._open.pop(token, None)
            if span is None:
                return
            if extra_args:
                span.args.update(extra_args)
            self._seq += 1
            self._last_activity = time.monotonic()
            self._append_locked(
                {
                    "seq": self._seq,
                    "bseq": span.bseq,
                    "ph": "X",
                    "name": span.name,
                    "ts": span.begin_us,
                    # A zero-length span would sort its E before its own
                    # B in the ts-major export ordering.
                    "dur": max(1, ts - span.begin_us),
                    "tid": span.tid,
                    "args": span.args,
                }
            )

    @contextlib.contextmanager
    def span(self, name: str, **args: Any) -> Generator[None, None, None]:
        token = self.begin(name, **args)
        try:
            yield
        finally:
            self.end(token)

    def instant(
        self, name: str, count_as_progress: bool = True, **args: Any
    ) -> None:
        """Point-in-time event. ``count_as_progress=False`` keeps the
        forward-progress clock untouched — the watchdog's own stall
        markers must not look like the stalled process doing work."""
        ts = _now_us()
        key = _track_key()
        with self._lock:
            self._seq += 1
            if count_as_progress:
                self._last_activity = time.monotonic()
            self._append_locked(
                {
                    "seq": self._seq,
                    "bseq": self._seq,
                    "ph": "i",
                    "name": name,
                    "ts": ts,
                    "tid": self._tid_locked(key),
                    "args": args,
                }
            )

    def _append_locked(self, event: Dict[str, Any]) -> None:
        if (
            self._events.maxlen is not None
            and len(self._events) == self._events.maxlen
        ):
            self.dropped += 1
        self._events.append(event)

    # -- reading ---------------------------------------------------------

    def idle_seconds(self) -> float:
        """Seconds since ANY event was recorded (begin/end/instant) —
        the watchdog's forward-progress signal. Near zero while a
        pipeline is moving, growing while everything is wedged."""
        with self._lock:
            return time.monotonic() - self._last_activity

    def mark(self) -> "TraceMark":
        """Cursor for a later :meth:`events_since` /
        :func:`export_op_trace`: everything completing after this call
        has ``seq`` greater than the marked value, and the mark carries
        the eviction count so exports can report window-local drops."""
        with self._lock:
            return TraceMark(self._seq, self.dropped)

    def events_since(self, mark: "int | TraceMark" = 0) -> List[Dict[str, Any]]:
        """Completed events newer than ``mark`` (a span that began before
        the mark but finished after it is included — overlap with the
        previous operation is signal, not noise), completion order."""
        seq = mark.seq if isinstance(mark, TraceMark) else mark
        with self._lock:
            return [dict(e) for e in self._events if e["seq"] > seq]

    def tid_names(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._tid_names)

    def open_spans(self) -> List[Dict[str, Any]]:
        """Snapshot of currently-open spans (watchdog + diagnostics):
        ``{"token", "name", "age_s", "tid", "thread", "args",
        "stalled"}``, oldest first."""
        now = _now_us()
        with self._lock:
            out = [
                {
                    "token": token,
                    "name": s.name,
                    "age_s": round((now - s.begin_us) / 1e6, 3),
                    "tid": s.tid,
                    "thread": self._tid_names.get(s.tid, "?"),
                    "args": dict(s.args),
                    "stalled": s.stalled,
                }
                for token, s in self._open.items()
            ]
        out.sort(key=lambda s: -s["age_s"])
        return out

    def flag_stalled(self, token: int) -> bool:
        """Mark one open span as stall-flagged; False if it already was
        (or has since closed) — the watchdog's fire-once latch."""
        with self._lock:
            span = self._open.get(token)
            if span is None or span.stalled:
                return False
            span.stalled = True
            return True

    def reset(self) -> None:
        """Drop everything, re-reading the capacity knob (tests
        simulating a fresh process)."""
        with self._lock:
            self._events = deque(maxlen=knobs.get_trace_buffer_events())
            self._open.clear()
            self._seq = 0
            self._tids.clear()
            self._tid_names.clear()
            self.dropped = 0


_RECORDER: Optional[SpanRecorder] = None
_RECORDER_INIT = threading.Lock()


def get_recorder() -> SpanRecorder:
    """The process-wide flight recorder every instrumented layer records
    into. Lazily constructed so the capacity knob is read at first use,
    not at import."""
    global _RECORDER
    rec = _RECORDER
    if rec is None:
        with _RECORDER_INIT:
            if _RECORDER is None:
                _RECORDER = SpanRecorder()
            rec = _RECORDER
    return rec


def io_span(
    plugin: str,
    op: str,
    blob: str,
    nbytes: Optional[int] = None,
    byte_range: Optional[Tuple[int, int]] = None,
):
    """Recorder span for one storage operation — the shared
    instrumentation hook for the fs/s3/gcs plugins (the recorder-side
    sibling of ``telemetry.observe_io``)."""
    args: Dict[str, Any] = {"plugin": plugin, "blob": blob}
    if nbytes is not None:
        args["bytes"] = int(nbytes)
    if byte_range is not None:
        args["range"] = [int(byte_range[0]), int(byte_range[1])]
    name = names.SPAN_STORAGE_WRITE if op == "write" else names.SPAN_STORAGE_READ
    return get_recorder().span(name, **args)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------


def _event_sort_key(ev: Dict[str, Any]) -> Tuple[int, int, int]:
    """Total order that keeps every track's B/E stack valid: ts-major;
    at equal ts, E before B/i (a span ending exactly where a sibling
    begins must close first); E ties resolve innermost-first (larger
    begin-seq), B ties outermost-first (smaller begin-seq)."""
    if ev["ph"] == "E":
        return (ev["ts"], 0, -ev["bseq"])
    return (ev["ts"], 1, ev["bseq"])


def chrome_trace(
    events: List[Dict[str, Any]],
    tid_names: Dict[int, str],
    rank: int = 0,
    dropped: int = 0,
) -> Dict[str, Any]:
    """Recorder events -> a Chrome trace-event JSON document (one pid =
    this rank; balanced B/E pairs, ts-sorted; Perfetto-loadable)."""
    pid = rank
    out: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "ts": 0,
            "args": {"name": f"rank{rank}"},
        }
    ]
    used_tids = sorted({e["tid"] for e in events})
    for tid in used_tids:
        out.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tid,
                "ts": 0,
                "args": {"name": tid_names.get(tid, f"thread-{tid}")},
            }
        )
    flat: List[Dict[str, Any]] = []
    for e in events:
        if e["ph"] == "X":
            flat.append(
                {
                    "ph": "B",
                    "name": e["name"],
                    "pid": pid,
                    "tid": e["tid"],
                    "ts": e["ts"],
                    "bseq": e["bseq"],
                    "args": e["args"],
                }
            )
            flat.append(
                {
                    "ph": "E",
                    "name": e["name"],
                    "pid": pid,
                    "tid": e["tid"],
                    "ts": e["ts"] + e["dur"],
                    "bseq": e["bseq"],
                }
            )
        else:
            flat.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": e["name"],
                    "pid": pid,
                    "tid": e["tid"],
                    "ts": e["ts"],
                    "bseq": e["bseq"],
                    "args": e["args"],
                }
            )
    flat.sort(key=_event_sort_key)
    for ev in flat:
        del ev["bseq"]  # ordering scaffold only; not Chrome schema
    out.extend(flat)
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "rank": rank,
            "clock": "unix_epoch_us",
            "dropped_events": dropped,
            "exported_unix_ts": round(time.time(), 6),
        },
    }


def write_trace_file(path: str, doc: Dict[str, Any]) -> None:
    """Atomic write (tmp + rename): a concurrent reader/merger never
    sees a torn trace."""
    from .sink import atomic_write_text

    atomic_write_text(path, json.dumps(doc, separators=(",", ":")))


def trace_path_for(
    snapshot_path: Optional[str], kind: str, rank: int
) -> Optional[str]:
    """Where an operation's trace export should go, or None when no
    trace sink is configured (same resolution order as the JSONL report
    sink: explicit dir knob first, then the snapshot-adjacent file for
    local paths)."""
    trace_dir = knobs.get_trace_dir()
    if trace_dir:
        return os.path.join(
            trace_dir, f"{TRACE_BASENAME_PREFIX}{kind}-rank{rank}.json"
        )
    if not knobs.is_trace_sink_enabled():
        return None
    from .sink import local_fs_root

    root = local_fs_root(snapshot_path)
    if root is None:
        return None
    return os.path.join(
        root, f"{SNAPSHOT_TRACE_PREFIX}{kind}-rank{rank}.json"
    )


def export_op_trace(
    kind: str, snapshot_path: str, rank: int, mark: "int | TraceMark"
) -> Optional[str]:
    """Write one operation's event window as a Chrome trace file;
    returns the path, or None (sink off / local root unavailable).
    Best-effort: trace export must never fail a checkpoint."""
    try:
        path = trace_path_for(snapshot_path, kind, rank)
        if path is None:
            return None
        recorder = get_recorder()
        dropped_baseline = (
            mark.dropped if isinstance(mark, TraceMark) else 0
        )
        doc = chrome_trace(
            recorder.events_since(mark),
            recorder.tid_names(),
            rank=rank,
            # Evictions within this op's window only, not the
            # recorder's lifetime total.
            dropped=max(0, recorder.dropped - dropped_baseline),
        )
        write_trace_file(path, doc)
        return path
    except Exception as e:  # noqa: BLE001 - telemetry must not fail the op
        logger.warning("trace: could not export %s trace: %r", kind, e)
        return None


# ---------------------------------------------------------------------------
# Cross-rank merge + summaries
# ---------------------------------------------------------------------------


def find_trace_files(snapshot_path: str) -> List[str]:
    """Per-rank trace files recorded for one snapshot: the
    snapshot-adjacent ``.trace-*.json`` plus, when a trace dir is
    configured, its ``trace-*.json`` exports."""
    out: List[str] = []
    from .sink import local_fs_root

    root = local_fs_root(snapshot_path)
    if root is None and "://" not in snapshot_path:
        root = snapshot_path
    if root is not None:
        out.extend(
            sorted(glob.glob(os.path.join(root, f"{SNAPSHOT_TRACE_PREFIX}*.json")))
        )
    trace_dir = knobs.get_trace_dir()
    if trace_dir:
        out.extend(
            sorted(glob.glob(os.path.join(trace_dir, f"{TRACE_BASENAME_PREFIX}*.json")))
        )
    return [p for p in out if not p.endswith(MERGED_TRACE_BASENAME)]


def merge_traces(
    paths: List[str],
    clock_offsets_s: Optional[Dict[int, float]] = None,
) -> Dict[str, Any]:
    """Merge per-rank Chrome trace files into one document: each file's
    events keep their pid (= rank) and have ``clock_offsets_s[rank]``
    subtracted from their timestamps (the store-gather-measured skew of
    that rank's clock against rank 0). Two files claiming the same rank
    (e.g. two co-hosted processes' mirror exports) get distinct pids —
    overlaying them on one pid would interleave their tracks and tear
    the B/E stacks. The concatenation is stable-sorted by ts only, so
    each (pid, tid) track's internal order — and hence its B/E balance
    — is preserved verbatim."""
    merged: List[Dict[str, Any]] = []
    ranks: List[int] = []
    used_pids: set = set()
    unaligned: List[int] = []
    dropped = 0
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        other = doc.get("otherData", {})
        rank = int(other.get("rank", 0))
        ranks.append(rank)
        pid = rank
        while pid in used_pids:
            pid += 1
        used_pids.add(pid)
        dropped += int(other.get("dropped_events", 0))
        shift_us = 0
        if clock_offsets_s:
            offset = clock_offsets_s.get(rank)
            if offset is None:
                # A rank whose report carried no clock offset (older
                # schema, or it never reached the gather) merges
                # uncorrected rather than failing the whole merge —
                # its pid is simply unaligned, and flagged as such.
                unaligned.append(rank)
                logger.warning(
                    "trace merge: no clock offset for rank %d; its "
                    "timeline is unaligned",
                    rank,
                )
            else:
                shift_us = int(round(offset * 1e6))
        for ev in doc.get("traceEvents", []):
            if shift_us != 0 or pid != ev.get("pid", rank):
                ev = dict(ev)
                if shift_us and ev.get("ph") != "M":
                    ev["ts"] = ev["ts"] - shift_us
                ev["pid"] = pid
            merged.append(ev)
    merged.sort(key=lambda ev: ev["ts"])
    out = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "ranks": sorted(set(ranks)),
            "clock": "unix_epoch_us (rank offsets applied)"
            if clock_offsets_s
            else "unix_epoch_us (no rank offset correction)",
            "dropped_events": dropped,
        },
    }
    if unaligned:
        out["otherData"]["unaligned_ranks"] = sorted(set(unaligned))
    stitch_wire_flows(out)
    return out


def stitched_wire_pairs(
    doc: Dict[str, Any]
) -> List[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """(client RPC span, server handler span) pairs causally linked by
    the propagated wire context: the handler's ``parent_span_id``
    equals the client span's ``span_id`` and both carry the same trace
    id. Works on a single rank's doc or a merged one — the linkage
    rides span args, not pids."""
    spans = spans_from_chrome(doc)
    clients: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        if s["name"] == names.SPAN_WIRE_RPC:
            span_id = s.get("args", {}).get("span_id")
            if span_id:
                clients[str(span_id)] = s
    pairs: List[Tuple[Dict[str, Any], Dict[str, Any]]] = []
    for s in spans:
        if s["name"] != names.SPAN_WIRE_HANDLER:
            continue
        args = s.get("args", {})
        client = clients.get(str(args.get("parent_span_id")))
        if client is None:
            continue
        if client.get("args", {}).get("trace_id") == args.get("trace_id"):
            pairs.append((client, s))
    return pairs


def stitch_wire_flows(doc: Dict[str, Any]) -> int:
    """Append Chrome flow events (``ph: s`` / ``ph: f``) linking each
    cross-process client→handler wire pair, so Perfetto draws the RPC
    arrow from the caller's span to the serving peer's handler span.
    Returns the number of stitched pairs (also recorded in
    ``otherData.wire_stitched``)."""
    pairs = stitched_wire_pairs(doc)
    events = doc.setdefault("traceEvents", [])
    for client, handler in pairs:
        flow_id = str(client["args"]["span_id"])
        common = {"cat": "wire", "name": "wire-rpc", "id": flow_id}
        # Flow endpoints must land INSIDE their slices (ts + 1 beats
        # the >= 1 us minimum span duration) or Perfetto drops them.
        events.append(
            {
                "ph": "s",
                "pid": client["pid"],
                "tid": client["tid"],
                "ts": client["ts"] + 1,
                **common,
            }
        )
        events.append(
            {
                "ph": "f",
                "bp": "e",
                "pid": handler["pid"],
                "tid": handler["tid"],
                "ts": handler["ts"] + 1,
                **common,
            }
        )
    doc.setdefault("otherData", {})["wire_stitched"] = len(pairs)
    return len(pairs)


def spans_from_chrome(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Reconstruct completed spans from a Chrome trace document's B/E
    pairs: ``{"name", "pid", "tid", "ts", "dur_us", "self_us"}``.
    ``self_us`` is the span's inclusive duration minus the durations of
    its direct children on the same track — the time the span spent in
    its OWN frame, which is what separates a genuinely slow stage from
    an envelope that merely contains one."""
    # Stack entries are [begin_event, accumulated_child_us].
    stacks: Dict[Tuple[int, int], List[List[Any]]] = {}
    spans: List[Dict[str, Any]] = []
    for ev in doc.get("traceEvents", []):
        ph = ev.get("ph")
        key = (ev.get("pid", 0), ev.get("tid", 0))
        if ph == "B":
            stacks.setdefault(key, []).append([ev, 0])
        elif ph == "E":
            stack = stacks.get(key)
            if not stack:
                continue  # torn window: span began before the export mark
            begin, child_us = stack.pop()
            dur_us = ev["ts"] - begin["ts"]
            if stack:
                stack[-1][1] += dur_us
            spans.append(
                {
                    "name": begin.get("name", "?"),
                    "pid": key[0],
                    "tid": key[1],
                    "ts": begin["ts"],
                    "dur_us": dur_us,
                    "self_us": max(0, dur_us - child_us),
                    "args": begin.get("args", {}),
                }
            )
    return spans


def longest_spans_from_doc(
    doc: Dict[str, Any], n: int = 3
) -> List[Dict[str, Any]]:
    """Top-``n`` longest spans of an already-loaded trace document —
    for callers (the checkpoint doctor) that also scan the same doc for
    other events and must not parse a multi-MB trace twice."""
    spans = sorted(spans_from_chrome(doc), key=lambda s: -s["dur_us"])
    out = []
    for s in spans[:n]:
        entry = {
            "name": s["name"],
            "dur_ms": round(s["dur_us"] / 1000, 1),
            "self_ms": round(s.get("self_us", s["dur_us"]) / 1000, 1),
        }
        blob = s.get("args", {}).get("blob")
        if blob:
            entry["blob"] = blob
        out.append(entry)
    return out


def longest_spans(
    trace_path: str, n: int = 3
) -> List[Dict[str, Any]]:
    """Top-``n`` longest spans of one trace file, for embedding in
    stall diagnoses (bench.py): ``{"name", "dur_ms", "blob"?}``."""
    with open(trace_path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    return longest_spans_from_doc(doc, n)


def summarize_merged(doc: Dict[str, Any], top: int = 5) -> str:
    """Operator summary of a merged trace: per-rank wall extent, the
    longest individual spans, the per-span-name straggler rank (largest
    total duration), and any watchdog stall events."""
    spans = spans_from_chrome(doc)
    lines: List[str] = []
    if not spans:
        return "no spans in trace"
    ranks = sorted({s["pid"] for s in spans})
    t0 = min(s["ts"] for s in spans)
    for rank in ranks:
        rs = [s for s in spans if s["pid"] == rank]
        begin = min(s["ts"] for s in rs)
        end = max(s["ts"] + s["dur_us"] for s in rs)
        lines.append(
            f"rank {rank}: {len(rs)} spans, window "
            f"[{(begin - t0) / 1e3:.1f} .. {(end - t0) / 1e3:.1f}] ms"
        )
    lines.append("")
    lines.append(f"longest spans (top {top}, inclusive / self):")
    for s in sorted(spans, key=lambda s: -s["dur_us"])[:top]:
        blob = s.get("args", {}).get("blob")
        suffix = f" ({blob})" if blob else ""
        lines.append(
            f"  {s['name']:<32} rank {s['pid']} "
            f"{s['dur_us'] / 1e3:>10.1f} ms / "
            f"{s.get('self_us', s['dur_us']) / 1e3:.1f} ms self{suffix}"
        )
    lines.append("")
    lines.append(f"top self-time spans (top {top}):")
    for s in sorted(
        spans, key=lambda s: -s.get("self_us", s["dur_us"])
    )[:top]:
        lines.append(
            f"  {s['name']:<32} rank {s['pid']} "
            f"{s.get('self_us', s['dur_us']) / 1e3:>10.1f} ms self "
            f"(of {s['dur_us'] / 1e3:.1f} ms)"
        )
    if len(ranks) > 1:
        totals: Dict[str, Dict[int, float]] = {}
        for s in spans:
            totals.setdefault(s["name"], {}).setdefault(s["pid"], 0.0)
            totals[s["name"]][s["pid"]] += s["dur_us"]
        lines.append("")
        lines.append("per-span straggler (max total duration across ranks):")
        for name in sorted(totals):
            per_rank = totals[name]
            straggler = max(per_rank, key=lambda r: per_rank[r])
            lines.append(
                f"  {name:<32} rank {straggler} "
                f"({per_rank[straggler] / 1e3:.1f} ms; min "
                f"{min(per_rank.values()) / 1e3:.1f} ms)"
            )
    pairs = stitched_wire_pairs(doc)
    if pairs:
        lines.append("")
        lines.append(f"wire RPCs stitched across processes: {len(pairs)}")
        for client, handler in pairs[:top]:
            op = client.get("args", {}).get("op", "?")
            lines.append(
                f"  {op:<24} pid {client['pid']} -> pid {handler['pid']} "
                f"({client['dur_us'] / 1e3:.1f} ms round trip)"
            )
    stalls = [
        ev
        for ev in doc.get("traceEvents", [])
        if ev.get("ph") == "i"
        and ev.get("name") == names.INSTANT_WATCHDOG_STALL
    ]
    if stalls:
        lines.append("")
        lines.append(f"WATCHDOG STALLS: {len(stalls)}")
        for ev in stalls:
            args = ev.get("args", {})
            lines.append(
                f"  rank {ev.get('pid', 0)} @ +{(ev['ts'] - t0) / 1e3:.1f} ms: "
                f"{args.get('span', '?')} open {args.get('age_s', '?')}s"
            )
    return "\n".join(lines)


def _clock_offsets_from_events(roots: List[str]) -> Dict[int, float]:
    """Per-rank clock offsets recorded by the newest aggregated
    SnapshotReport found in the JSONL sinks under ``roots`` (see
    report.clock_offsets_s). Empty dict = no correction available."""
    from .sink import EVENTS_BASENAME, SNAPSHOT_EVENTS_BASENAME, load_events

    candidates: List[str] = []
    for root in roots:
        for base in (SNAPSHOT_EVENTS_BASENAME, EVENTS_BASENAME):
            p = os.path.join(root, base)
            if os.path.exists(p):
                candidates.append(p)
    best: Dict[int, float] = {}
    for path in candidates:
        try:
            for ev in load_events(path):
                offsets = ev.get("clock_offsets_s")
                if offsets:
                    # A rank whose slot is null (no gather stamp) gets
                    # no entry: merge_traces leaves it unaligned with a
                    # warning instead of failing the merge.
                    best = {
                        i: float(o)
                        for i, o in enumerate(offsets)
                        if o is not None
                    }
        except Exception:  # noqa: BLE001 - offsets are an optional refinement
            continue
    return best


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m torchsnapshot_tpu.telemetry trace <snapshot>``:
    merge per-rank trace files and print the straggler summary."""
    import argparse

    p = argparse.ArgumentParser(
        prog="telemetry trace",
        description="Merge per-rank checkpoint flight-recorder traces "
        "into one Chrome trace-event JSON (load in Perfetto / "
        "chrome://tracing) and summarize stragglers.",
    )
    p.add_argument(
        "path",
        help="snapshot directory (or trace dir) holding per-rank "
        ".trace-*.json / trace-*.json files, or a single trace file",
    )
    p.add_argument(
        "-o",
        "--output",
        default=None,
        help="merged trace output (default: <path>/.trace.merged.json)",
    )
    p.add_argument(
        "--top", type=int, default=5, help="longest spans to list"
    )
    p.add_argument(
        "--no-clock-offsets",
        action="store_true",
        help="skip the SnapshotReport-derived per-rank clock correction",
    )
    args = p.parse_args(argv)

    if os.path.isfile(args.path):
        files = [args.path]
        root = os.path.dirname(args.path) or "."
    else:
        files = find_trace_files(args.path)
        root = args.path
    if not files:
        print(
            f"telemetry trace: no trace files under {args.path!r} "
            f"(take with TORCHSNAPSHOT_TPU_TRACE=1 or set "
            f"TORCHSNAPSHOT_TPU_TRACE_DIR)"
        )
        return 1
    offsets: Dict[int, float] = {}
    if not args.no_clock_offsets:
        offsets = _clock_offsets_from_events([root])
    merged = merge_traces(files, offsets)
    out_path = args.output or os.path.join(root, MERGED_TRACE_BASENAME)
    write_trace_file(out_path, merged)
    print(f"merged {len(files)} trace file(s) -> {out_path}")
    if offsets and any(offsets.values()):
        print(
            "clock offsets applied (s): "
            + ", ".join(f"rank{r}={o:+.3f}" for r, o in sorted(offsets.items()))
        )
    unaligned = merged.get("otherData", {}).get("unaligned_ranks")
    if unaligned:
        print(
            f"warning: no clock offsets for rank(s) "
            f"{', '.join(map(str, unaligned))} — their timelines are "
            f"unaligned (raw clocks)"
        )
    stitched = merged.get("otherData", {}).get("wire_stitched", 0)
    if stitched:
        print(f"wire RPCs stitched across processes: {stitched}")
    print()
    print(summarize_merged(merged, top=args.top))
    return 0
