"""Wire observatory: cross-process RPC tracing, per-endpoint wire
metrics, and the fleet metrics plane.

Three instruments over the socket seams (``dist_store.send_frame`` /
``recv_frame`` and everything riding them — the TCP coordination
store, the peer tier, the CDN fleet):

**Context propagation.**  A sender inside a :func:`propagate` block
prefixes each framed payload with a fixed-length header carrying a
trace id, the sender's span id, and a declared RPC op id
(``names.RPC_*``).  The receiver strips the header, exposes it via
:func:`last_received_context`, and the handler opens its span with
``trace_id``/``parent_span_id`` args — so one CDN pull or peer push
appears as ONE causally-linked trace across processes once ``python -m
torchsnapshot_tpu.telemetry trace`` stitches the merged timeline.  The
header is guarded by magic + crc32: a corrupted, torn, or
version-skewed header (``install_wire_chaos`` flips bytes on exactly
this seam) degrades to a context-free frame with the body intact —
never a protocol error.

**Per-endpoint wire metrics.**  Always-on registry series recorded at
the framing layer and the dial/request sites: frames/bytes by
``endpoint`` (store | peer) and ``dir`` (send | recv), dial latency
(histogram + a bounded recent-sample ring that feeds the
``wire-dial-stalled`` doctor rule — the listen-backlog SYN-retransmit
bug class shows up as dial latencies quantized at whole seconds),
in-flight requests, connection-pool checkout outcomes, accept-pressure
depth, and per-RPC latency by declared op id.

**Fleet metrics plane.**  Each publisher (rank or CDN subscriber)
writes ONE bounded, crc-guarded JSON snapshot under
``__obs/<role>/<id>`` on the coordination store via ``multi_set``,
paced by a world-scaled interval; readers skip torn or stale entries
and publishers reap their key via ``multi_delete`` on clean shutdown.
``python -m torchsnapshot_tpu.telemetry fleet <host:port | root>``
renders the live per-publisher table and runs the fleet-scope doctor
rules.  Opt-in integration via ``TORCHSNAPSHOT_TPU_FLEET_OBS=1``.

See docs/observability.md ("Wire observatory").
"""

from __future__ import annotations

import contextlib
import json
import os
import struct
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

from . import names

__all__ = [
    "HEADER_LEN",
    "OBS_PREFIX",
    "FleetReporter",
    "WireContext",
    "collect_fleet",
    "current_context",
    "decode_fleet_entry",
    "decode_frame",
    "encode_fleet_entry",
    "encode_frame",
    "fleet_main",
    "fleet_snapshot",
    "last_received_context",
    "local_wire_summary",
    "new_id",
    "observe_accept_depth",
    "observe_dial",
    "observe_frame",
    "observe_pool_checkout",
    "observe_rpc",
    "propagate",
    "quantized_dial_fraction",
    "read_fleet_endpoint",
    "recent_dial_seconds",
    "render_fleet_table",
    "rpc_inflight",
    "set_received_context",
    "write_fleet_endpoint",
]


def _metrics():
    # Lazy: wire.py is imported during telemetry package init, before
    # the package-level registry exists.
    from . import metrics

    return metrics()


# ---------------------------------------------------------------------------
# context propagation: the compact frame header
# ---------------------------------------------------------------------------

# Fixed-length header so a receiver can ALWAYS strip it once the magic
# matches, even when chaos flipped a byte inside it: magic(4) +
# version(1) + reserved(1) + op(24, NUL-padded kebab-case RPC id from
# names.RPC_*) + trace_id(8) + span_id(8) + crc32 of the preceding
# bytes(4).  A failed crc / unknown version degrades to a context-free
# frame with the body intact — never a protocol error (the wire-chaos
# suite pins this).  A frame that starts with the magic but is shorter
# than the header is torn: the context is dropped and the raw payload
# passed through untouched.
_MAGIC = b"TSWC"
_WIRE_VERSION = 1
_OP_FIELD_LEN = 24
_HEADER = struct.Struct("<4sBB24s8s8sI")
HEADER_LEN = _HEADER.size


@dataclass(frozen=True)
class WireContext:
    """One hop's tracing identity, carried inside the frame header."""

    trace_id: str  # 16 hex chars shared by every hop of one logical op
    span_id: str  # 16 hex chars: the sender's span = receiver's parent
    op: str  # declared RPC id (names.RPC_*)


def new_id() -> str:
    """A fresh 64-bit trace/span id as 16 hex chars."""
    return os.urandom(8).hex()


def encode_frame(ctx: WireContext, body: bytes) -> bytes:
    """Prefix ``body`` with the context header for ``ctx``."""
    op = ctx.op.encode("ascii", "replace")[:_OP_FIELD_LEN]
    try:
        tid = bytes.fromhex(ctx.trace_id)[:8].rjust(8, b"\x00")
        sid = bytes.fromhex(ctx.span_id)[:8].rjust(8, b"\x00")
    except ValueError:
        tid = sid = b"\x00" * 8
    head = _HEADER.pack(_MAGIC, _WIRE_VERSION, 0, op, tid, sid, 0)[:-4]
    return head + struct.pack("<I", zlib.crc32(head)) + body


def _count_degraded(reason: str) -> None:
    try:
        _metrics().counter_inc(
            names.WIRE_CONTEXT_DEGRADED_TOTAL, reason=reason
        )
    except Exception:  # noqa: BLE001 - accounting never breaks the wire
        pass


def decode_frame(payload: bytes) -> Tuple[Optional[WireContext], bytes]:
    """Split a received payload into ``(context, body)``.

    Context-free payloads (no magic) pass through untouched.  A
    header whose crc or version fails is stripped but yields no
    context; a torn header (magic present, frame shorter than the
    header) passes the raw payload through.  Every degraded shape
    increments ``wire_context_degraded_total`` with a ``reason``.
    """
    if not payload.startswith(_MAGIC):
        return None, payload
    if len(payload) < HEADER_LEN:
        _count_degraded("torn")
        return None, payload
    _magic, version, _flags, op_raw, tid, sid, crc = _HEADER.unpack_from(
        payload
    )
    if zlib.crc32(payload[: HEADER_LEN - 4]) != crc:
        _count_degraded("crc")
        return None, payload[HEADER_LEN:]
    if version != _WIRE_VERSION:
        _count_degraded("version")
        return None, payload[HEADER_LEN:]
    op = op_raw.rstrip(b"\x00").decode("ascii", "replace")
    return WireContext(tid.hex(), sid.hex(), op), payload[HEADER_LEN:]


_TLS = threading.local()


def current_context() -> Optional[WireContext]:
    """The active outbound context for this thread, if any."""
    return getattr(_TLS, "ctx", None)


@contextlib.contextmanager
def propagate(op: str, trace_id: Optional[str] = None) -> Iterator[WireContext]:
    """Open an outbound wire context: frames sent by this thread while
    the block is active carry ``op`` plus a trace/span id pair.  Nested
    blocks inherit the enclosing trace id, so a composite op (a fan-out
    exchange, a CDN sync) links every frame it causes under one trace.
    """
    parent = current_context()
    ctx = WireContext(
        trace_id=trace_id
        or (parent.trace_id if parent is not None else new_id()),
        span_id=new_id(),
        op=op,
    )
    _TLS.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = parent


def set_received_context(ctx: Optional[WireContext]) -> None:
    """Record the context decoded from the most recent inbound frame on
    this thread (``recv_frame`` calls this; handlers read it back)."""
    _TLS.received = ctx


def last_received_context() -> Optional[WireContext]:
    """The context carried by this thread's most recent inbound frame,
    or None when it was context-free (or degraded by chaos)."""
    return getattr(_TLS, "received", None)


# ---------------------------------------------------------------------------
# per-endpoint wire metrics
# ---------------------------------------------------------------------------

# Bounded ring of recent successful dial latencies per endpoint: the
# raw samples behind the wire-dial-stalled rule and the fleet
# snapshot's dial percentiles (a histogram alone cannot show the
# whole-second quantization signature).
_RECENT_DIALS_KEEP = 64
_DIAL_LOCK = threading.Lock()
_RECENT_DIALS: Dict[str, Deque[float]] = {}

_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT: Dict[str, int] = {}

# Accept-pressure depth is a count, not seconds.
_DEPTH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


def observe_frame(endpoint: str, direction: str, nbytes: int) -> None:
    """One frame on the wire (direction: "send" | "recv"); ``nbytes``
    includes the 4-byte length prefix and any context header."""
    reg = _metrics()
    reg.counter_inc(names.WIRE_FRAMES_TOTAL, endpoint=endpoint, dir=direction)
    reg.counter_inc(
        names.WIRE_BYTES_TOTAL, nbytes, endpoint=endpoint, dir=direction
    )


def observe_dial(endpoint: str, seconds: float, ok: bool = True) -> None:
    """One connection attempt; only successful dials feed the latency
    histogram and the recent-sample ring (the backlog-stall signature
    lives in dials that eventually SUCCEED after SYN retransmits)."""
    reg = _metrics()
    reg.counter_inc(
        names.WIRE_DIALS_TOTAL,
        endpoint=endpoint,
        outcome="ok" if ok else "error",
    )
    if not ok:
        return
    reg.counter_inc(names.WIRE_DIAL_SECONDS_TOTAL, seconds, endpoint=endpoint)
    reg.histogram_observe(names.WIRE_DIAL_SECONDS, seconds, endpoint=endpoint)
    with _DIAL_LOCK:
        ring = _RECENT_DIALS.get(endpoint)
        if ring is None:
            ring = _RECENT_DIALS[endpoint] = deque(maxlen=_RECENT_DIALS_KEEP)
        ring.append(seconds)


def recent_dial_seconds(endpoint: Optional[str] = None) -> List[float]:
    """Recent successful dial latencies (newest last), one endpoint or
    all of them."""
    with _DIAL_LOCK:
        if endpoint is not None:
            return list(_RECENT_DIALS.get(endpoint, ()))
        return [s for ring in _RECENT_DIALS.values() for s in ring]


def reset_recent_dials() -> None:
    """Drop the dial-sample rings (tests simulating a fresh process)."""
    with _DIAL_LOCK:
        _RECENT_DIALS.clear()


def observe_rpc(endpoint: str, op: str, seconds: float) -> None:
    """One completed request/reply round trip for a declared RPC op."""
    reg = _metrics()
    reg.counter_inc(names.WIRE_RPCS_TOTAL, endpoint=endpoint, op=op)
    reg.counter_inc(
        names.WIRE_RPC_SECONDS_TOTAL, seconds, endpoint=endpoint, op=op
    )
    reg.histogram_observe(
        names.WIRE_RPC_SECONDS, seconds, endpoint=endpoint, op=op
    )


def observe_pool_checkout(endpoint: str, outcome: str) -> None:
    """One connection-pool checkout (outcome: "reused" | "new" |
    "dead" — dead meaning the pooled socket had to be discarded)."""
    _metrics().counter_inc(
        names.WIRE_POOL_CHECKOUTS_TOTAL, endpoint=endpoint, outcome=outcome
    )


def observe_accept_depth(endpoint: str, depth: int) -> None:
    """Server-side accept pressure: the number of connections a server
    is concurrently handling when a new one arrives (a userspace proxy
    for the kernel accept queue, which Python cannot read portably)."""
    _metrics().histogram_observe(
        names.WIRE_ACCEPT_QUEUE_DEPTH,
        float(depth),
        buckets=_DEPTH_BUCKETS,
        endpoint=endpoint,
    )


@contextlib.contextmanager
def rpc_inflight(endpoint: str) -> Iterator[None]:
    """Track one in-flight request against the per-endpoint gauge."""
    reg = _metrics()
    with _INFLIGHT_LOCK:
        _INFLIGHT[endpoint] = _INFLIGHT.get(endpoint, 0) + 1
        reg.gauge_set(
            names.WIRE_INFLIGHT_FRAMES, _INFLIGHT[endpoint], endpoint=endpoint
        )
    try:
        yield
    finally:
        with _INFLIGHT_LOCK:
            _INFLIGHT[endpoint] = max(0, _INFLIGHT.get(endpoint, 1) - 1)
            reg.gauge_set(
                names.WIRE_INFLIGHT_FRAMES,
                _INFLIGHT[endpoint],
                endpoint=endpoint,
            )


# ---------------------------------------------------------------------------
# dial-stall signature (the PR 15 listen-backlog bug class)
# ---------------------------------------------------------------------------

# A full accept queue makes the kernel drop SYNs; the client retries on
# the retransmission timer, so successful dials cluster at ~1s, ~2s,
# ~3s.  Healthy dials are either fast (< DIAL_STALL_MIN_S) or smeared
# continuously — a large fraction of slow dials sitting within
# DIAL_STALL_TOLERANCE_S of an integer second is the stall signature.
DIAL_STALL_MIN_S = 0.5
DIAL_STALL_TOLERANCE_S = 0.06
DIAL_STALL_MIN_SAMPLES = 3
DIAL_STALL_MIN_FRACTION = 0.6


def quantized_dial_fraction(samples: Sequence[float]) -> Tuple[int, float]:
    """``(slow_sample_count, quantized_fraction)`` over ``samples``:
    how many dials were slow, and what fraction of those sit within
    tolerance of a whole second."""
    slow = [s for s in samples if s >= DIAL_STALL_MIN_S]
    if not slow:
        return 0, 0.0
    quantized = sum(
        1 for s in slow if abs(s - round(s)) <= DIAL_STALL_TOLERANCE_S
    )
    return len(slow), quantized / len(slow)


# ---------------------------------------------------------------------------
# fleet metrics plane
# ---------------------------------------------------------------------------

OBS_PREFIX = "__obs"
FLEET_ENDPOINT_BASENAME = ".fleet-endpoint"
# One snapshot per publisher, and each snapshot bounded: the plane's
# store footprint is O(publishers), never O(time).
SNAPSHOT_MAX_BYTES = 4096
STALE_AFTER_S = 30.0


def _percentile(sorted_samples: Sequence[float], frac: float) -> float:
    if not sorted_samples:
        return 0.0
    return sorted_samples[
        min(len(sorted_samples) - 1, int(len(sorted_samples) * frac))
    ]


def local_wire_summary() -> Dict[str, Any]:
    """This process's wire health, compact enough for a fleet snapshot:
    per-endpoint frame/byte/rpc totals, dial p50/p95 plus the raw
    recent-dial ring (the stall rule needs samples, not quantiles), and
    per-shard coordination-store request counts."""
    from .registry import parse_series_key

    counters = _metrics().counters_snapshot()
    endpoints: Dict[str, Dict[str, float]] = {}
    shards: Dict[str, float] = {}
    degraded = 0.0
    folds = {
        names.WIRE_FRAMES_TOTAL: "frames",
        names.WIRE_BYTES_TOTAL: "bytes",
        names.WIRE_RPCS_TOTAL: "rpcs",
        names.WIRE_RPC_SECONDS_TOTAL: "rpc_s",
        names.WIRE_DIALS_TOTAL: "dials",
    }
    for series, value in counters.items():
        name, labels = parse_series_key(series)
        field = folds.get(name)
        if field is not None:
            ep = endpoints.setdefault(labels.get("endpoint", "?"), {})
            ep[field] = round(ep.get(field, 0.0) + value, 6)
        elif name == names.COORD_STORE_SHARD_REQUESTS_TOTAL:
            shard = labels.get("shard", "?")
            shards[shard] = shards.get(shard, 0.0) + value
        elif name == names.WIRE_CONTEXT_DEGRADED_TOTAL:
            degraded += value
    dials = sorted(recent_dial_seconds())
    summary: Dict[str, Any] = {
        "endpoints": endpoints,
        "dial_p50_s": round(_percentile(dials, 0.5), 4),
        "dial_p95_s": round(_percentile(dials, 0.95), 4),
        # Newest samples last; bounded by the ring size.
        "dials_s": [round(s, 3) for s in recent_dial_seconds()[-32:]],
    }
    if shards:
        summary["store_shards"] = shards
    if degraded:
        summary["context_degraded"] = degraded
    return summary


def fleet_snapshot(
    role: str,
    ident: str,
    seq: int,
    phase: Optional[str] = None,
    written_bytes: Optional[int] = None,
    extra: Optional[Mapping[str, Any]] = None,
) -> Dict[str, Any]:
    """One publisher's current state as a compact JSON-able dict."""
    snap: Dict[str, Any] = {
        "v": 1,
        "role": role,
        "id": str(ident),
        "seq": int(seq),
        "t": time.time(),
        "wire": local_wire_summary(),
    }
    if phase is not None:
        snap["phase"] = phase
    if written_bytes is not None:
        snap["written_bytes"] = int(written_bytes)
    if extra:
        snap["extra"] = dict(extra)
    return snap


def encode_fleet_entry(snapshot: Mapping[str, Any]) -> bytes:
    """crc-guarded wire form: ``<crc32-hex>:<compact json>``.  A reader
    that observes a torn ``multi_set`` (or a half-written value) sees a
    crc mismatch and skips the entry.  Oversized snapshots shed their
    bulky optional fields rather than growing the plane unboundedly."""
    snap = dict(snapshot)
    body = json.dumps(snap, separators=(",", ":"), sort_keys=True).encode()
    if len(body) > SNAPSHOT_MAX_BYTES:
        for bulky in ("extra", "wire"):
            snap.pop(bulky, None)
            body = json.dumps(
                snap, separators=(",", ":"), sort_keys=True
            ).encode()
            if len(body) <= SNAPSHOT_MAX_BYTES:
                break
    return b"%08x:%s" % (zlib.crc32(body), body)


def decode_fleet_entry(
    raw: Optional[bytes],
    now: Optional[float] = None,
    stale_after_s: float = STALE_AFTER_S,
) -> Optional[Dict[str, Any]]:
    """Parse one ``__obs/`` value; None for torn, malformed, or stale
    entries (a dead publisher's last snapshot ages out rather than
    rendering forever)."""
    if not raw:
        return None
    try:
        head, body = raw.split(b":", 1)
        if int(head, 16) != zlib.crc32(body):
            return None
        entry = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(entry, dict):
        return None
    t = entry.get("t")
    if not isinstance(t, (int, float)):
        return None
    entry["age_s"] = max(0.0, (time.time() if now is None else now) - t)
    if entry["age_s"] > stale_after_s:
        return None
    return entry


def publish_interval_for_world(world: int) -> float:
    """World-scaled publish pacing: a 4-rank job refreshes every 0.25s,
    a 1000-rank fleet backs off to 5s so the plane's store traffic
    stays a rounding error next to the real coordination load."""
    return max(0.25, min(5.0, max(1, world) * 0.02))


class FleetReporter:
    """One process's handle on the fleet plane: publishes ONE bounded
    snapshot key ``__obs/<role>/<id>`` (world-paced, ``multi_set``) and
    reaps it on :meth:`close` via ``multi_delete``."""

    def __init__(
        self,
        store: Any,
        role: str,
        ident: Any,
        world: int = 1,
        interval_s: Optional[float] = None,
    ) -> None:
        self._store = store
        self.key = f"{OBS_PREFIX}/{role}/{ident}"
        self._role = role
        self._ident = str(ident)
        self._seq = 0
        self._interval_s = (
            publish_interval_for_world(world)
            if interval_s is None
            else interval_s
        )
        self._last_pub = float("-inf")
        self._lock = threading.Lock()
        self._closed = False

    def publish(
        self,
        phase: Optional[str] = None,
        written_bytes: Optional[int] = None,
        extra: Optional[Mapping[str, Any]] = None,
        force: bool = False,
    ) -> bool:
        """Publish a fresh snapshot if the pacer allows it; returns
        whether anything was written.  Store errors are swallowed — the
        plane observes the job, it must never fail it."""
        with self._lock:
            if self._closed:
                return False
            now = time.monotonic()
            if not force and now - self._last_pub < self._interval_s:
                return False
            self._last_pub = now
            self._seq += 1
            seq = self._seq
        snap = fleet_snapshot(
            self._role,
            self._ident,
            seq,
            phase=phase,
            written_bytes=written_bytes,
            extra=extra,
        )
        try:
            self._store.multi_set({self.key: encode_fleet_entry(snap)})
        except Exception:  # noqa: BLE001 - observability is best-effort
            return False
        return True

    def close(self) -> None:
        """Reap this publisher's key so a clean shutdown leaves no
        residue under ``__obs/``."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._store.multi_delete([self.key])
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass


def collect_fleet(
    store: Any, stale_after_s: float = STALE_AFTER_S
) -> List[Dict[str, Any]]:
    """All live fleet entries on ``store``, torn/stale ones skipped,
    ordered by (role, id)."""
    keys = store.scan(OBS_PREFIX + "/")
    entries: List[Dict[str, Any]] = []
    if keys:
        for raw in store.multi_get(list(keys)).values():
            entry = decode_fleet_entry(raw, stale_after_s=stale_after_s)
            if entry is not None:
                entries.append(entry)
    entries.sort(key=lambda e: (str(e.get("role", "")), str(e.get("id", ""))))
    return entries


def render_fleet_table(entries: Sequence[Mapping[str, Any]]) -> str:
    """The live fleet table: one row per publisher with phase, written
    bytes, snapshot age, wire totals, dial p95, and a straggler flag
    (a publisher ≥ 2 sequence points behind the fleet head, or one
    whose snapshot is 3x staler than the median)."""
    if not entries:
        return "(no live fleet entries under __obs/)"
    ages = sorted(float(e.get("age_s", 0.0)) for e in entries)
    median_age = _percentile(ages, 0.5)
    max_seq = max(int(e.get("seq", 0)) for e in entries)
    header = (
        "ROLE",
        "ID",
        "SEQ",
        "PHASE",
        "WRITTEN",
        "AGE_S",
        "FRAMES",
        "WIRE_MB",
        "DIAL_P95_S",
        "BURN",
        "NOTE",
    )
    rows: List[Tuple[str, ...]] = [header]
    for e in entries:
        wire = e.get("wire") or {}
        eps = wire.get("endpoints") or {}
        frames = sum(float(ep.get("frames", 0)) for ep in eps.values())
        mb = sum(float(ep.get("bytes", 0)) for ep in eps.values()) / 1024**2
        age = float(e.get("age_s", 0.0))
        # The publisher's SLO burn rate rides the plane as an extra
        # (telemetry/slo.py): >= 1.0 means that member is spending its
        # error budget faster than sustainable.
        burn = (e.get("extra") or {}).get("slo_burn")
        notes = []
        if max_seq - int(e.get("seq", 0)) >= 2:
            notes.append("straggler")
        if len(entries) >= 3 and median_age > 0 and age > 3 * median_age:
            notes.append("stale")
        if isinstance(burn, (int, float)) and float(burn) >= 1.0:
            notes.append("burning")
        rows.append(
            (
                str(e.get("role", "?")),
                str(e.get("id", "?")),
                str(e.get("seq", "?")),
                str(e.get("phase", "-")),
                str(e.get("written_bytes", "-")),
                f"{age:.1f}",
                f"{frames:.0f}",
                f"{mb:.2f}",
                f"{float(wire.get('dial_p95_s', 0.0)):.3f}",
                f"{float(burn):.2f}"
                if isinstance(burn, (int, float))
                else "-",
                ",".join(notes) or "-",
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    return "\n".join(
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        for row in rows
    )


def write_fleet_endpoint(root: str, host: str, port: int) -> str:
    """Advertise the coordination store's address under ``root`` so
    ``telemetry fleet <root>`` can find it (atomic rewrite)."""
    path = os.path.join(root, FLEET_ENDPOINT_BASENAME)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(f"{host}:{port}\n")
    os.replace(tmp, path)
    return path


def read_fleet_endpoint(root: str) -> Tuple[str, int]:
    path = os.path.join(root, FLEET_ENDPOINT_BASENAME)
    with open(path, "r", encoding="utf-8") as f:
        host, _, port = f.read().strip().rpartition(":")
    return host, int(port)


def _open_target_store(target: str):
    """``host:port`` straight to the store; a directory goes through
    its advertised ``.fleet-endpoint`` file."""
    from ..dist_store import TCPStore

    if os.path.isdir(target):
        host, port = read_fleet_endpoint(target)
    else:
        host, _, port_s = target.rpartition(":")
        if not host:
            raise SystemExit(
                f"fleet target {target!r} is neither a directory with a "
                f"{FLEET_ENDPOINT_BASENAME} file nor host:port"
            )
        port = int(port_s)
    return TCPStore(host, port, is_server=False)


def fleet_main(argv: Sequence[str]) -> int:
    """``python -m torchsnapshot_tpu.telemetry fleet <target>``."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu.telemetry fleet",
        description=(
            "Render the live fleet table from __obs/ snapshots on the "
            "coordination store and run the fleet-scope doctor rules."
        ),
    )
    parser.add_argument(
        "target",
        help="coordination store as host:port, or a snapshot root "
        f"containing {FLEET_ENDPOINT_BASENAME}",
    )
    parser.add_argument(
        "--once",
        action="store_true",
        help="render one table and exit (default: watch)",
    )
    parser.add_argument(
        "--interval", type=float, default=2.0, help="watch refresh seconds"
    )
    parser.add_argument(
        "--stale-after",
        type=float,
        default=STALE_AFTER_S,
        help="ignore snapshots older than this many seconds",
    )
    args = parser.parse_args(list(argv))
    from . import doctor

    store = _open_target_store(args.target)
    try:
        while True:
            entries = collect_fleet(store, stale_after_s=args.stale_after)
            print(render_fleet_table(entries))
            verdicts = doctor.diagnose_fleet(entries)
            for verdict in verdicts:
                print(verdict.format())
            if args.once:
                break
            print(f"-- {len(entries)} publisher(s); ^C to stop --", flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        store.close()
    return 0
